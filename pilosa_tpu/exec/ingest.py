"""Streaming ingest engine: delta-buffered writes with interval-batched,
donated device scatter-merges (ROADMAP item 4, "Production write path").

The problem: PR 7 made acked writes durable, but stack maintenance still
happened on the READ path — every import bumps fragment generations, and
the next query over a stale cached stack repairs it inline (host gather +
device patch dispatch under the process-wide dispatch lock), while
compressed containers decay to dense on the first write. Sustained
ingest therefore taxes read p99 once per (fragment, interval) — the
reference never pays this because roaring absorbs write churn in an
op-log-over-snapshot delta (roaring.go:228-249); this module is the
device analogue.

Shape:

  server/api.py import paths      exec/ingest.py merge thread
  ------------------------------  ---------------------------------
  oplog append  (durability)      every --ingest-merge-interval, or
  fragment apply (host truth)       at the rows/bytes high-water mark:
  record() -> delta buffer        drain: ONE batched scatter-merge
  ack (unchanged)                   dispatch folds all pending deltas
                                    into the touched resident stacks
                                    (jax.jit, donated stack buffers)

Reads whose cache-entry drift is FULLY covered by pending deltas serve
the resident stack as-is (bounded staleness <= one merge interval; see
covers_pending). Drift the buffer does not cover — a PQL Set/Clear on a
fragment with no pending entry, a dropped/recreated fragment — falls
back to the legacy read-path repair unchanged. Interval 0 (the default)
never constructs an engine: the import path is one `is None` check and
every read behaves byte-identically to the legacy per-import
invalidation.

Crash semantics: buffered-but-unmerged deltas are ALREADY durable — the
oplog record precedes the buffer append, and the host fragments hold the
applied bits. Only the device stack cache is behind; a crash loses
nothing and boot replay needs no new machinery. Under fsync=interval the
engine also group-commits the applied watermark: mark_applied calls for
acked imports batch per merge interval (bounded by the oplog's existing
gap set), flushed at every drain and at close().

Donation lifecycle: the merge scatter donates the resident stack buffer
(update-in-place on TPU — no second copy of a 512 MB pool at peak; the
CPU backend ignores donation and copies). The dispatch runs under the
process-wide dispatch lock, so no serving launch interleaves with it; a
reader that grabbed the OLD container right before the merge and
dispatches after it will see a donated-buffer error on TPU — the window
is one lock handoff wide and retries resolve it, but it is why merges
swap entries only after the barrier, never mid-flight.
"""

import threading
import time
import warnings

import numpy as np

from ..utils import faultpoints
from ..utils import flightrec as _flightrec
from ..utils.stats import global_stats

__all__ = [
    "IngestEngine",
    "covers_pending",
    "mode",
    "DEFAULT_MAX_ROWS",
    "DEFAULT_MAX_BYTES",
]

#: high-water marks that force an early drain (and 503 back-pressure
#: past them): enough headroom for seconds of bulk import without
#: letting an unmerged backlog grow unboundedly between intervals
DEFAULT_MAX_ROWS = 1_000_000
DEFAULT_MAX_BYTES = 64 << 20

# jax warns once per donated jit on backends that ignore donation (the
# CPU test backend); the fallback is exactly the legacy copying scatter,
# so the warning is noise here
warnings.filterwarnings(
    "ignore", message="Some donated buffers were not usable")

_REGISTRY_LOCK = threading.Lock()
_REGISTRY = []  # active engines; read lock-free on the serving path


def covers_pending(index, field, view, shards, old_gens, gens):
    """True when EVERY drifted shard of a stale cache entry is covered
    by a pending ingest delta at its current generation — the read may
    serve the resident stack as-is and leave the fold to the interval
    merge. One list check when no engine is active (the default)."""
    engines = _REGISTRY
    if not engines:
        return False
    for eng in engines:
        if eng.covers(index, field, view, shards, old_gens, gens):
            return True
    return False


def mode():
    """'off' or 'interval=<seconds>s' — bench attempt tagging."""
    engines = _REGISTRY
    if not engines:
        return "off"
    return f"interval={engines[0].interval:g}s"


def _build_scatter_axis0():
    import jax

    return jax.jit(lambda stack, jdx, block: stack.at[jdx].set(block),
                   donate_argnums=(0,))


def _build_scatter_axis1():
    import jax

    return jax.jit(lambda stack, jdx, block: stack.at[:, jdx].set(block),
                   donate_argnums=(0,))


def _build_scatter_bsi():
    import jax

    def scatter(planes, sign, exists, jdx, block):
        return (planes.at[:, jdx].set(block[2:]),
                sign.at[jdx].set(block[1]),
                exists.at[jdx].set(block[0]))

    return jax.jit(scatter, donate_argnums=(0, 1, 2))


class IngestEngine:
    """Bounded host-side delta buffer + background interval merger for
    one API's local evaluator. Construct only with interval > 0; the
    thread starts immediately and close() drains the tail."""

    def __init__(self, api, interval, max_rows=None, max_bytes=None):
        if interval <= 0:
            raise ValueError("ingest merge interval must be > 0")
        self.api = api
        self.interval = float(interval)
        self.max_rows = int(max_rows or DEFAULT_MAX_ROWS)
        self.max_bytes = int(max_bytes or DEFAULT_MAX_BYTES)
        # pending: (index, field, view, shard) -> [uid, gen, rows, bytes]
        # — the (uid, gen) is the fragment's generation AFTER the
        # recorded apply, which is what covers() compares reads against
        self._pending = {}
        self._rows = 0
        self._bytes = 0
        self._deferred = []  # lsns whose mark_applied group-commits
        self._plock = threading.Lock()
        self._merge_lock = threading.Lock()  # serializes drains
        self._wake = threading.Event()
        self._stop = threading.Event()
        self._closed = False
        # counters (under _plock; ints, so snapshots are cheap)
        self.merges = 0
        self.merged_keys = 0
        self.scatter_entries = 0
        self.overlay_entries = 0
        self.rebuilt_entries = 0
        self.dropped_entries = 0
        self.overflows = 0
        self.merges_shed = 0
        self.group_commit_flushed = 0
        self.last_merge = None  # {wall_seconds, at, entries, deltas}
        # admission-ladder hook: when set and truthy at a TIMER tick,
        # the interval merge is skipped (deltas keep buffering; reads
        # serve the resident — stale — stacks). Overflow wakes always
        # merge: shedding those would deadlock the write path behind
        # its own back-pressure gate.
        self._shed_probe = None
        with _REGISTRY_LOCK:
            _REGISTRY.append(self)
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="ingest-merge")
        self._thread.start()

    # -- write-path hooks (called by server/api.py) ---------------------------

    def set_shed_probe(self, fn):
        """Install the admission ladder's merge-shed predicate (called
        once at API construction; None clears)."""
        self._shed_probe = fn

    def admit(self, rows, nbytes):
        """Back-pressure gate BEFORE the oplog append: returns a
        retry-after in seconds when the buffer is past its high-water
        mark (the API turns it into 503 + Retry-After), else None. An
        overflow also wakes the merger immediately."""
        with self._plock:
            over = (self._rows + rows > self.max_rows
                    or self._bytes + nbytes > self.max_bytes)
            if over:
                self.overflows += 1
        if over:
            _flightrec.record("ingest.overflow", rows=self._rows,
                              bytes=self._bytes)
            global_stats.count("ingest_overflows", 1)
            self._wake.set()
            return max(1.0, self.interval)
        return None

    def record(self, index_name, field, shard_rows, nbytes):
        """Buffer one applied import's deltas: for every view of `field`
        and every touched shard, remember the fragment's post-apply
        (uid, generation). The merge gathers planes from the
        authoritative host fragments, so recording the CURRENT gens is
        exact — any earlier un-recorded write to the same fragment rides
        the same fold. `shard_rows` maps shard -> input rows landed
        there; `nbytes` is the import's wire-size estimate (distributed
        per shard for the high-water accounting)."""
        if not shard_rows:
            return
        total = sum(shard_rows.values()) or 1
        entries = []
        for view in list(field.views.values()):
            for shard, n in shard_rows.items():
                frag = view.fragment(shard)
                if frag is None:
                    continue
                entries.append(
                    ((index_name, field.name, view.name, shard),
                     frag.uid, frag.generation, n,
                     nbytes * n // total))
        if not entries:
            return
        high = False
        with self._plock:
            for key, uid, gen, n, nb in entries:
                rec = self._pending.get(key)
                if rec is not None and (rec[0], rec[1]) == (uid, gen):
                    rec[2] += n
                    rec[3] += nb
                else:
                    prev_rows = rec[2] if rec is not None else 0
                    prev_bytes = rec[3] if rec is not None else 0
                    self._pending[key] = [uid, gen, prev_rows + n,
                                          prev_bytes + nb]
                self._rows += n
                self._bytes += nb
            high = (self._rows >= self.max_rows
                    or self._bytes >= self.max_bytes)
        if high:
            self._wake.set()

    def defer_applied(self, lsn):
        """Group-commit hook: True = this record's mark_applied is
        deferred to the next drain (fsync=interval only — under
        fsync=always the watermark IS the durability contract and
        advances per record as before)."""
        if lsn is None or self._closed:
            return False
        oplog = self.api.oplog
        if oplog is None or oplog.fsync != "interval":
            return False
        with self._plock:
            if self._closed:
                return False
            self._deferred.append(lsn)
        return True

    def covers(self, index, field, view, shards, old_gens, gens):
        """True when every drifted shard's current generation matches a
        pending delta record — i.e. the merge will fold exactly the
        drift this read sees."""
        hit = False
        with self._plock:
            pending = self._pending
            for j, (o, n) in enumerate(zip(old_gens, gens)):
                if o == n:
                    continue
                rec = pending.get((index, field, view, shards[j]))
                if rec is None or (rec[0], rec[1]) != n:
                    return False
                hit = True
        return hit

    # -- merge ---------------------------------------------------------------

    def _evaluator(self):
        ex = getattr(self.api.executor, "local", self.api.executor)
        return getattr(ex, "_stacked", None)

    def flush(self):
        """Synchronous drain (tests; close). Serialized with the
        background thread's drains."""
        with self._merge_lock:
            self._drain()

    def _loop(self):
        while True:
            forced = self._wake.wait(self.interval)
            self._wake.clear()
            if self._stop.is_set():
                return
            probe = self._shed_probe
            if not forced and probe is not None and probe():
                # SHED_BATCH+: skip the interval merge to keep the
                # device free for interactive reads. Deltas stay
                # buffered; an overflow (forced wake) still merges.
                with self._plock:
                    self.merges_shed += 1
                    pending = bool(self._pending or self._deferred)
                if pending:
                    _flightrec.record("ingest.merge_shed",
                                      rows=self._rows, bytes=self._bytes)
                continue
            try:
                self.flush()
            except Exception as exc:  # noqa: BLE001 — keep merging
                global_stats.count("ingest_merge_errors", 1)
                _flightrec.record("ingest.merge_error", error=str(exc))

    def _drain(self):
        with self._plock:
            snapshot = dict(self._pending)
            deferred = self._deferred
            self._deferred = []
        if not snapshot and not deferred:
            return
        faultpoints.reached("ingest.pre-merge")
        t0 = time.perf_counter()
        stats = {"entries": 0, "scatters": 0, "overlays": 0,
                 "rebuilds": 0, "drops": 0}
        if snapshot:
            touched = {(k[0], k[1]) for k in snapshot}
            ev = self._evaluator()
            if ev is not None:
                self._merge_into(ev, touched, stats)
        if deferred:
            for lsn in deferred:
                self.api._oplog_applied(lsn)
            global_stats.timing("oplog_group_commit_records",
                                float(len(deferred)))
        # retire folded keys: a record() that landed mid-merge replaced
        # the key's value object, so the identity compare keeps it for
        # the next interval (its write IS newer than the gathered plane)
        with self._plock:
            for k, v in snapshot.items():
                if self._pending.get(k) is v:
                    del self._pending[k]
                    self._rows -= v[2]
                    self._bytes -= v[3]
            if not self._pending:
                self._rows = 0
                self._bytes = 0
            self.merges += 1
            self.merged_keys += len(snapshot)
            self.scatter_entries += stats["scatters"]
            self.overlay_entries += stats["overlays"]
            self.rebuilt_entries += stats["rebuilds"]
            self.dropped_entries += stats["drops"]
            self.group_commit_flushed += len(deferred)
            wall = time.perf_counter() - t0
            self.last_merge = {
                "wall_seconds": round(wall, 6),
                "at": time.time(),
                "entries": stats["entries"],
                "deltas": len(snapshot),
                "group_commit_records": len(deferred),
            }
        global_stats.timing("ingest_merge_seconds", wall)
        _flightrec.record(
            "ingest.merge", deltas=len(snapshot),
            entries=stats["entries"], scatters=stats["scatters"],
            overlays=stats["overlays"], rebuilds=stats["rebuilds"],
            drops=stats["drops"], group_commit=len(deferred),
            wall_seconds=round(wall, 6))

    def _merge_into(self, ev, touched, stats):
        """Fold pending deltas into every touched resident stack: plan +
        host-gather outside any lock, then ONE dispatch-lock window for
        all donated scatters, then swap entries in. Entries too drifted
        to patch drop (the next read rebuilds cold — a build, not a
        read-path patch); compressed containers take an overlay term or
        a full rebuild with the repr re-chosen."""
        import jax.numpy as jnp

        from ..core.fragment import (
            BSI_EXISTS_BIT,
            BSI_OFFSET_BIT,
            BSI_SIGN_BIT,
        )
        from ..core.view import VIEW_STANDARD
        from ..ops import containers as _containers
        from . import stacked as _stacked

        holder = self.api.holder
        with ev._lock:
            items = list(ev._stacks.items()) + list(ev._rows_stacks.items())
        scatters = []
        for key, entry in items:
            if (key[1], key[2]) not in touched:
                continue
            kind = key[0]
            idx = holder.index(key[1])
            field = idx.field(key[2]) if idx is not None else None
            if field is None:
                if ev.merge_drop(key, entry):
                    stats["drops"] += 1
                continue
            if kind == "leaf":
                view_name, shards, rows = VIEW_STANDARD, key[4], [key[3]]
            elif kind == "rows":
                view_name, shards, rows = key[3], key[5], list(key[4])
            elif kind == "bsi":
                view_name = field.bsi_view_name()
                shards = key[4]
                rows = [BSI_EXISTS_BIT, BSI_SIGN_BIT] + [
                    BSI_OFFSET_BIT + i for i in range(key[3])]
            else:
                continue
            view = field.view(view_name)
            if view is None:
                if ev.merge_drop(key, entry):
                    stats["drops"] += 1
                continue
            gens = ev._fragment_gens(idx, key[2], shards, view_name,
                                     view=view)
            old_gens = entry[0]
            if gens is None or len(old_gens) != len(gens):
                if ev.merge_drop(key, entry):
                    stats["drops"] += 1
                continue
            if old_gens == gens:
                continue  # already current
            changed = [j for j, (o, n) in enumerate(zip(old_gens, gens))
                       if o != n]
            ent = entry[1]
            stats["entries"] += 1
            if (kind == "leaf" and isinstance(ent, _containers.Container)
                    and ent.kind != "dense"):
                self._merge_compressed(ev, key, entry, ent, gens, view,
                                       shards, changed, stats,
                                       _containers, VIEW_STANDARD)
                continue
            if len(changed) * 2 > len(shards):
                # past the patch cutoff a merge-time fold would re-upload
                # most of the stack anyway — drop and let demand rebuild
                if ev.merge_drop(key, entry):
                    stats["drops"] += 1
                continue
            block = ev._host_rows(view, rows,
                                  [shards[j] for j in changed], pad=False)
            scatters.append((kind, key, entry, gens,
                             np.asarray(changed), block))
        if not scatters:
            return
        nbytes_in = sum(p[5].nbytes for p in scatters)
        outs = []
        with ev._locked_dispatch("ingest_merge", nbytes_in=nbytes_in) as ph:
            for kind, key, entry, gens, jdx, block in scatters:
                ent = entry[1]
                if kind == "leaf":
                    fn = ev._get_fn(("ingest_scatter", 0),
                                    _build_scatter_axis0)
                    stack = (ent.arrays[0]
                             if isinstance(ent, _containers.Container)
                             else ent)
                    outs.append(fn(stack, jnp.asarray(jdx),
                                   jnp.asarray(block[0])))
                elif kind == "rows":
                    fn = ev._get_fn(("ingest_scatter", 1),
                                    _build_scatter_axis1)
                    outs.append(fn(ent, jnp.asarray(jdx),
                                   jnp.asarray(block)))
                else:
                    fn = ev._get_fn(("ingest_scatter", "bsi"),
                                    _build_scatter_bsi)
                    planes, sign, exists = ent
                    outs.append(fn(planes, sign, exists,
                                   jnp.asarray(jdx), jnp.asarray(block)))
            ph.mark("dispatch_ack")
            for out in outs:
                _stacked._launch_barrier(out)
            ph.mark("sync")
        for (kind, key, entry, gens, jdx, block), out in zip(scatters,
                                                             outs):
            if kind == "leaf":
                cont = _containers.dense_container(out)
                ok = ev.merge_swap(key, entry, gens, cont, cont.nbytes)
            elif kind == "rows":
                ok = ev.merge_swap(key, entry, gens, out,
                                   int(out.size) * 4)
            else:
                ok = ev.merge_swap(key, entry, gens, tuple(out), entry[2])
            if ok:
                stats["scatters"] += 1

    def _merge_compressed(self, ev, key, entry, ent, gens, view, shards,
                          changed, stats, _containers, view_standard):
        """Compressed leaf: park the drifted planes as an overlay term
        beside the sparse/rle base, or — past the overlay budget — do a
        full rebuild with the representation re-chosen from the measured
        density (the interval is where repr churn is allowed)."""
        over_budget = (
            ent.overlay + 1 > _containers.OVERLAY_MAX_TERMS
            or (_containers.overlay_rows(ent) + len(changed)
                > max(1, len(shards) // 2)))
        if over_budget:
            host = ev._host_rows(view, [key[3]], shards)
            cont = _containers.build(
                host[0],
                place_sharded=lambda a: ev._place(a, shard_axis=0),
                place_replicated=ev._place_replicated,
                fragment=(key[1], key[2], view_standard, key[3]))
            if ev.merge_swap(key, entry, gens, cont, cont.nbytes):
                stats["rebuilds"] += 1
            return
        block = ev._host_rows(view, [key[3]],
                              [shards[j] for j in changed], pad=False)
        cont = _containers.with_overlay(
            ent, ev._place_replicated,
            np.asarray(changed, np.int32), block[0])
        if ev.merge_swap(key, entry, gens, cont, cont.nbytes):
            stats["overlays"] += 1

    # -- observability / lifecycle -------------------------------------------

    def snapshot(self):
        """GET /debug/ingest payload."""
        with self._plock:
            per_field = {}
            for (index, field, _view, _shard), v in self._pending.items():
                e = per_field.setdefault(
                    f"{index}/{field}",
                    {"deltas": 0, "rows": 0, "bytes": 0})
                e["deltas"] += 1
                e["rows"] += v[2]
                e["bytes"] += v[3]
            last = dict(self.last_merge) if self.last_merge else None
            out = {
                "enabled": True,
                "interval_seconds": self.interval,
                "max_rows": self.max_rows,
                "max_bytes": self.max_bytes,
                "pending": {
                    "entries": len(self._pending),
                    "rows": self._rows,
                    "bytes": self._bytes,
                    "deferred_lsns": len(self._deferred),
                },
                "per_field": per_field,
                "merges": self.merges,
                "merged_keys": self.merged_keys,
                "scatter_entries": self.scatter_entries,
                "overlay_entries": self.overlay_entries,
                "rebuilt_entries": self.rebuilt_entries,
                "dropped_entries": self.dropped_entries,
                "overflows": self.overflows,
                "merges_shed": self.merges_shed,
                "group_commit_flushed": self.group_commit_flushed,
                "last_merge": last,
            }
        if last is not None:
            out["last_merge"]["age_seconds"] = round(
                time.time() - last["at"], 3)
        return out

    def close(self):
        """Stop the merger and drain the tail (pending deltas fold,
        deferred watermarks flush). Idempotent."""
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        self._wake.set()
        self._thread.join(timeout=10)
        self.flush()
        with _REGISTRY_LOCK:
            try:
                _REGISTRY.remove(self)
            except ValueError:
                pass
