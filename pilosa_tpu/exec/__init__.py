"""Query engine (reference: executor.go)."""

from .executor import ExecError, ExecOptions, Executor, FieldNotFound
from .result import FieldRow, GroupCount, Pair, RowIdentifiers, ValCount
