"""Adaptive execution engine: the consumer that closes the loop from the
observability stack (cost model, kernel timings, fragment heat, container
ledger) back into dispatch decisions.

Three decision surfaces, all priced through one calibration table:

1. **Strategy** — stacked vs per-shard-fallback for Count/Sum/Min/Max/
   TopN/GroupBy. The static gates (MIN_SHARDS + coverage) stay as hard
   eligibility; when they pass, the adaptive layer prices BOTH paths and
   may send an eligible query down the fallback anyway (a cold one-off
   over many missing fragments can be cheaper per-shard than paying the
   stack build). Decisions change *which path* runs, never *what answer*
   comes back — both paths are exact.
2. **Tiling** — the GroupBy pairwise [tile, tile] shape. Dispatch count
   falls with tile² while per-dispatch wall grows with tile²; the sweet
   spot moves with the dispatch RTT regime, so it is priced from
   per-tile EWMA observations instead of pinned at CHUNK_BYTES.
3. **Cache policy** — victim selection in both stack-cache pools moves
   from pure LRU to a heat×cost benefit score:

       benefit = heat × rebuild_seconds / resident_bytes

   (heat: the workload ledger's decayed access count; rebuild: fixed
   dispatch overhead + upload of the entry's *actual* resident bytes —
   compressed containers are cheaper to rebuild and score accordingly).
   The lowest-benefit entry is evicted — which may be the entry just
   admitted, i.e. the score doubles as an admission filter: a one-off
   export can no longer strip a hot TopN field's residency. A bounded
   *proactive* admission path (Executor.maybe_proactive_admit) pulls
   `hot_but_not_resident` fragments back in during idle dispatch-lock
   windows.

Calibration: per-kernel-family seconds come from the `kernel_seconds`
EWMA (utils/stats.py — recency-weighted, unlike the cumulative /metrics
histograms), seeded from cached XLA cost_analysis when no sample exists
yet, with DEFAULT_DISPATCH_SECONDS as the cold-process floor. Fallback
(per-shard) costs are learned the same way from observed fallback walls.
/debug/plans misestimates feed back in two ways: a >factor wall deviation
re-injects the observed per-dispatch seconds into the family's EWMA
(`note_wall_misestimate`), and a repeated `container_repr` misestimate
forces the offending fragments dense at next rebuild
(ops/containers.py repr overrides).

Escape hatch: --adaptive off|on|shadow. `off` (the default) keeps every
legacy code path byte-for-byte — zero probes, zero scoring. `shadow`
computes, counts, and logs every decision but acts on none of them — the
A/B harness for the bench gates. Module-singleton state with
configure()/reset(), like exec/plan.py and utils/workload.py.
"""

import threading
import time

from ..utils.stats import global_stats

MODES = ("off", "on", "shadow")

#: cold-process per-dispatch floor (mirrors exec/plan.py's constant;
#: defined locally so plan can import adaptive without a cycle)
DEFAULT_DISPATCH_SECONDS = 2e-3

#: per-shard fallback op floor before any observation: one dispatch-ish
#: unit per shard, which reproduces the static gate's bias (stacked wins
#: at MIN_SHARDS+) until real fallback walls teach otherwise
DEFAULT_FALLBACK_SHARD_SECONDS = 2e-3

#: host→device upload pricing for cold-stack builds (~8 GB/s effective;
#: only relative scale matters — it prices missing bytes against
#: dispatch counts, and EWMA recalibration dominates once samples exist)
UPLOAD_SECONDS_PER_BYTE = 1.0 / (8 << 30)

#: fixed component of a rebuild (one dispatch round trip) in the cache
#: benefit score — keeps small-but-hot entries from scoring as free
REBUILD_FIXED_SECONDS = DEFAULT_DISPATCH_SECONDS

#: on-device bytes-touched pricing for incremental stack patches: the
#: scatter's functional update copies the whole resident stack at HBM
#: speed (~64 GB/s effective), which is ~8x cheaper per byte than the
#: host→device re-upload a rebuild pays — so the priced patch/rebuild
#: cutoff lands near 7/8 of the shards drifted, not the static half
DEVICE_TOUCH_SECONDS_PER_BYTE = 1.0 / (64 << 30)

#: proactive admission bounds per idle window: never more than this many
#: leaf builds / bytes in one round, so admission can't monopolize the
#: dispatch lock ahead of real queries
ADMIT_MAX_ROWS = 64
ADMIT_MAX_BYTES = 32 << 20

#: container_repr misestimate strikes before the fragment's next rebuild
#: is forced dense ("repeatedly", not a single noisy sample)
REPR_STRIKE_LIMIT = 2

#: recent-decision ring size for /debug/optimizer
DECISION_RING = 64

_lock = threading.Lock()
_mode = "off"
_forced_tile = None  # bench sweep override (decide_tile honors it)

# EWMA state the stats module doesn't own: per-op fallback per-shard
# seconds and per-tile pairwise per-dispatch seconds.
_EWMA_ALPHA = 0.2
_fallback = {}   # op -> [ewma_seconds_per_shard, samples]
_pairwise = {}   # tile -> [ewma_seconds_per_dispatch, samples]

# decision counters + recent ring (all guarded by _lock)
_strategy_counts = {}   # (op, strategy) -> count
_tile_counts = {}       # tile -> count
_recent = []            # bounded decision dicts, newest last
_cache_counters = {
    "benefit_evictions": 0,   # victims chosen by score (mode=on)
    "lru_evictions": 0,       # victims chosen by recency (off/shadow)
    "shadow_divergences": 0,  # shadow: score disagreed with LRU
}
_admission_counters = {
    "admitted_fragments": 0, "admitted_rows": 0, "admitted_bytes": 0,
    "shadow_candidates": 0, "rounds": 0,
}
_calibration_bumps = {}  # family -> count (wall-misestimate feedback)
_repr_strikes = {}       # (index, field) -> strikes
_patch_counts = {"patch": 0, "rebuild": 0}  # decide_patch outcomes


def configure(mode=None, forced_tile=None):
    """Apply --adaptive (off|on|shadow). `forced_tile` pins the GroupBy
    pairwise tile regardless of pricing — the bench sweep's hook."""
    global _mode, _forced_tile
    if mode is not None:
        if mode not in MODES:
            raise ValueError(
                f"adaptive mode must be one of {'|'.join(MODES)}: "
                f"{mode!r}")
        with _lock:
            _mode = mode
    if forced_tile is not None:
        with _lock:
            _forced_tile = int(forced_tile) if forced_tile else None


def set_forced_tile(tile):
    """Pin (or with None, unpin) the pairwise tile for sweeps."""
    global _forced_tile
    with _lock:
        _forced_tile = int(tile) if tile else None


def mode():
    return _mode


def enabled():
    """True when the engine observes and decides (on OR shadow)."""
    return _mode != "off"


def acting():
    """True only when decisions are allowed to change behavior."""
    return _mode == "on"


def reset():
    """Test isolation: back to cold defaults (mode off, no state)."""
    global _mode, _forced_tile
    with _lock:
        _mode = "off"
        _forced_tile = None
        _fallback.clear()
        _pairwise.clear()
        _fuse_compile.clear()
        _strategy_counts.clear()
        _tile_counts.clear()
        _recent.clear()
        for k in _cache_counters:
            _cache_counters[k] = 0
        for k in _admission_counters:
            _admission_counters[k] = 0
        _calibration_bumps.clear()
        _repr_strikes.clear()
        for k in _patch_counts:
            _patch_counts[k] = 0


# ------------------------------------------------------------- calibration


def _kernel_ewma():
    """{family: (seconds, samples)} from the kernel_seconds EWMA — the
    recency-weighted view stats.py keeps alongside the cumulative
    histograms (satellite: the cumulative mean can never forget a slow
    cold-start regime; this can)."""
    out = {}
    for (_, tags), (ewma, n) in \
            global_stats.timing_ewma("kernel_seconds").items():
        family = dict(tags).get("kernel")
        if family and n:
            out[family] = (ewma, n)
    return out


def _xla_seconds(stacked):
    """{family: optimal_seconds} from costs ALREADY computed by a prior
    /debug/kernels request — never compiles (same contract as the plan
    cost model)."""
    if stacked is None:
        return {}
    out = {}
    try:
        with stacked._lock:
            costs = dict(stacked._kernel_costs)
    except Exception:  # pragma: no cover - observability only
        return {}
    for key, cost in costs.items():
        secs = (cost or {}).get("optimal_seconds")
        if isinstance(secs, (int, float)) and secs > 0:
            family = str(key[0])
            out[family] = max(out.get(family, 0.0), float(secs))
    return out


def dispatch_seconds(family, stacked=None, ewma=None, xla=None):
    """(seconds, source) for one dispatch of `family`. Source ranking:
    ewma (recent observed) > cost_analysis (cached XLA) > default."""
    ewma = _kernel_ewma() if ewma is None else ewma
    e = ewma.get(family)
    if e is not None:
        return e[0], "ewma"
    xla = _xla_seconds(stacked) if xla is None else xla
    x = xla.get(family)
    if x:
        return x, "cost_analysis"
    return DEFAULT_DISPATCH_SECONDS, "default"


def fallback_seconds(op):
    """(per-shard seconds, source) of the per-shard fallback for `op`."""
    with _lock:
        e = _fallback.get(op)
        if e is not None and e[1]:
            return e[0], "ewma"
    return DEFAULT_FALLBACK_SHARD_SECONDS, "default"


def _ewma_update(table, key, value, alpha=_EWMA_ALPHA):
    e = table.get(key)
    if e is None:
        table[key] = [float(value), 1]
    else:
        e[0] += alpha * (float(value) - e[0])
        e[1] += 1


def observe_fallback(op, wall_seconds, n_shards):
    """Feed one observed per-shard fallback wall (any enabled mode —
    shadow learns too, that's what makes its decisions honest)."""
    if _mode == "off" or n_shards <= 0 or wall_seconds <= 0:
        return
    with _lock:
        _ewma_update(_fallback, op, wall_seconds / n_shards)


def observe_pairwise(tile, wall_seconds):
    """Feed one observed pairwise dispatch wall at nominal `tile`."""
    if _mode == "off" or wall_seconds <= 0:
        return
    with _lock:
        _ewma_update(_pairwise, int(tile), wall_seconds)


def note_wall_misestimate(kernels, actual_wall_seconds):
    """A strategy's kernel-wall estimate deviated past the misestimate
    factor: re-inject the OBSERVED per-dispatch seconds into each
    family's EWMA at full weight, so the next estimate starts from
    reality instead of repeating the drifted number."""
    if _mode == "off" or not kernels:
        return
    total = sum(kernels.values())
    if total <= 0 or actual_wall_seconds <= 0:
        return
    per_dispatch = actual_wall_seconds / total
    for family in kernels:
        global_stats.timing_ewma_force(
            "kernel_seconds", per_dispatch, {"kernel": family})
        with _lock:
            _calibration_bumps[family] = \
                _calibration_bumps.get(family, 0) + 1


def note_repr_misestimate(index, fields):
    """A plan's container_repr choice read MORE bytes than the dense
    scan it competed against. Strike each involved fragment; past
    REPR_STRIKE_LIMIT the fragment is forced dense at its next rebuild
    (shadow: strikes count, no override lands)."""
    if _mode == "off" or not index or not fields:
        return
    from ..ops import containers

    for field in fields:
        with _lock:
            k = (index, field)
            _repr_strikes[k] = _repr_strikes.get(k, 0) + 1
            strikes = _repr_strikes[k]
        if strikes >= REPR_STRIKE_LIMIT and _mode == "on":
            containers.set_repr_override(index, field, "dense")


# --------------------------------------------------------------- decisions


class Decision:
    """One priced strategy choice. `act` is False in shadow mode — the
    caller computes-and-logs but follows the static path."""

    __slots__ = ("op", "strategy", "act", "est_stacked", "est_fallback",
                 "source", "chosen_by")

    def __init__(self, op, strategy, act, est_stacked, est_fallback,
                 source):
        self.op = op
        self.strategy = strategy
        self.act = act
        self.est_stacked = est_stacked
        self.est_fallback = est_fallback
        self.source = source
        self.chosen_by = (
            f"cost-model (est stacked={est_stacked * 1000:.2f}ms vs "
            f"fallback={est_fallback * 1000:.2f}ms)")


def _record_decision(kind, detail):
    with _lock:
        _recent.append({"kind": kind, "ts": round(time.time(), 3),
                        **detail})
        del _recent[:-DECISION_RING]


def decide_strategy(op, kernels, n_shards, missing_bytes=0, stacked=None):
    """Price stacked (Σ family dispatches × calibrated seconds + cold
    upload) vs per-shard fallback (shards × learned per-shard seconds)
    for one ELIGIBLE query. Returns None when the engine is off; the
    static gates have already vetoed ineligible shapes before this is
    called. The same inputs produce the same decision on the plan path
    (exec/plan.py) and the execute path — that is the plan-vs-actual
    agreement contract."""
    if _mode == "off":
        return None
    ewma = _kernel_ewma()
    xla = _xla_seconds(stacked)
    est_stacked = missing_bytes * UPLOAD_SECONDS_PER_BYTE
    rank = {"ewma": 0, "cost_analysis": 1, "default": 2}
    worst = "ewma"
    for family, n in (kernels or {}).items():
        secs, src = dispatch_seconds(family, ewma=ewma, xla=xla)
        est_stacked += secs * n
        if rank[src] > rank[worst]:
            worst = src
    fb_secs, fb_src = fallback_seconds(op)
    est_fallback = n_shards * fb_secs
    if rank[fb_src] > rank[worst]:
        worst = fb_src
    strategy = "stacked" if est_stacked <= est_fallback else "fallback"
    dec = Decision(op, strategy, acting(), est_stacked, est_fallback,
                   worst)
    with _lock:
        k = (op, strategy)
        _strategy_counts[k] = _strategy_counts.get(k, 0) + 1
    _record_decision("strategy", {
        "op": op, "strategy": strategy, "acted": dec.act,
        "est_stacked_ms": round(est_stacked * 1000, 3),
        "est_fallback_ms": round(est_fallback * 1000, 3),
        "source": worst})
    return dec


class FuseDecision:
    """One priced fuse-vs-interpret choice (exec/fusion.py consults it
    AFTER the frequency gate has already admitted the fingerprint)."""

    __slots__ = ("fuse", "act", "est_fused", "est_interpret", "source",
                 "chosen_by")

    def __init__(self, fuse, act, est_fused, est_interpret, source):
        self.fuse = fuse
        self.act = act
        self.est_fused = est_fused
        self.est_interpret = est_interpret
        self.source = source
        self.chosen_by = (
            f"cost-model (est fused={est_fused * 1000:.2f}ms vs "
            f"interpret={est_interpret * 1000:.2f}ms)")


#: compile-cost prior for one fused trace before any observation:
#: trace+compile of a small count DAG is tens-of-ms-scale on every
#: backend we run; the fused_compile_ms EWMA replaces it after the
#: first real compile
DEFAULT_FUSE_COMPILE_SECONDS = 50e-3

_fuse_compile = {}  # single-key EWMA table: "compile" -> [seconds, n]


def observe_fuse_compile(wall_seconds):
    """Feed one observed fused trace+compile wall (any enabled mode)."""
    if _mode == "off" or wall_seconds <= 0:
        return
    with _lock:
        _ewma_update(_fuse_compile, "compile", wall_seconds)


def decide_fuse(n_calls, fp_hits, cached, stacked=None):
    """Price fused (one dispatch + compile amortized over the
    fingerprint's observed frequency) vs interpreted (one count
    dispatch per top-level call). `cached`: a live program means the
    compile is sunk and fused strictly dominates. Returns None when
    the engine is off — the fusion module then relies on its frequency
    gate alone."""
    if _mode == "off":
        return None
    per_dispatch, source = dispatch_seconds("count", stacked=stacked)
    est_interpret = n_calls * per_dispatch
    if cached:
        est_fused = per_dispatch
    else:
        with _lock:
            e = _fuse_compile.get("compile")
        compile_s = e[0] if e is not None and e[1] \
            else DEFAULT_FUSE_COMPILE_SECONDS
        # amortize the compile over the reuse the frequency ranking
        # predicts: a shape seen N times is priced as if it returns N
        # more times before churning out of the workload
        est_fused = per_dispatch + compile_s / max(1, fp_hits)
    fuse = est_fused <= est_interpret
    dec = FuseDecision(fuse, acting(), est_fused, est_interpret, source)
    with _lock:
        k = ("Fuse", "fused" if fuse else "interpret")
        _strategy_counts[k] = _strategy_counts.get(k, 0) + 1
    _record_decision("fuse", {
        "calls": n_calls, "fp_hits": fp_hits, "cached": cached,
        "fuse": fuse, "acted": dec.act,
        "est_fused_ms": round(est_fused * 1000, 3),
        "est_interpret_ms": round(est_interpret * 1000, 3),
        "source": source})
    return dec


def decide_patch(n_changed, n_shards, rows, plane_bytes):
    """Price the read-path patch-vs-rebuild cutoff for a stale cached
    stack with `n_changed` of `n_shards` drifted shard rows (`rows`
    planes of `plane_bytes` each per shard). Patch = one dispatch +
    upload only the drifted planes + the on-device copy of the whole
    stack the functional scatter pays; rebuild = one dispatch + re-upload
    of every plane. Returns True to patch. Only consulted when
    acting() — off/shadow keep exec/stacked's static half-the-shards
    rule, so the default path stays byte-identical."""
    row_bytes = rows * plane_bytes
    est_patch = (DEFAULT_DISPATCH_SECONDS
                 + n_changed * row_bytes * UPLOAD_SECONDS_PER_BYTE
                 + n_shards * row_bytes * DEVICE_TOUCH_SECONDS_PER_BYTE)
    est_rebuild = (REBUILD_FIXED_SECONDS
                   + n_shards * row_bytes * UPLOAD_SECONDS_PER_BYTE)
    patch = est_patch <= est_rebuild
    with _lock:
        _patch_counts["patch" if patch else "rebuild"] += 1
    _record_decision("patch", {
        "changed": n_changed, "shards": n_shards, "rows": rows,
        "patch": patch, "acted": True,
        "est_patch_ms": round(est_patch * 1000, 3),
        "est_rebuild_ms": round(est_rebuild * 1000, 3)})
    return patch


class TileDecision:
    __slots__ = ("tile", "act", "estimates", "source", "chosen_by")

    def __init__(self, tile, act, estimates, source, static_tile):
        self.tile = tile
        self.act = act
        self.estimates = estimates
        self.source = source
        self.chosen_by = (
            f"cost-model (tile {tile} est "
            f"{estimates.get(tile, 0.0) * 1000:.2f}ms; static "
            f"{static_tile} est "
            f"{estimates.get(static_tile, 0.0) * 1000:.2f}ms)")


def _pairwise_model():
    """(overhead_seconds, seconds_per_cell, source) fitted from the
    per-tile EWMA samples: per_dispatch(t) = overhead + t² × cell. With
    no samples the cell term is 0 — every candidate prices identically
    per-tile, the dispatch-count term dominates, and the largest
    (static) tile wins, reproducing the legacy choice."""
    overhead = DEFAULT_DISPATCH_SECONDS
    with _lock:
        samples = {t: e[0] for t, e in _pairwise.items() if e[1]}
    if not samples:
        return overhead, 0.0, "default"
    # the smallest sampled tile's wall is the best overhead estimate
    # available (its t² term is the smallest share of its wall)
    t_min = min(samples)
    overhead = min(overhead, samples[t_min])
    cells = [max(w - overhead, 0.0) / float(t * t)
             for t, w in samples.items() if t > 0]
    cell = sum(cells) / len(cells) if cells else 0.0
    return overhead, cell, "ewma"


def decide_tile(static_tile, n_a, n_b, outer=1):
    """Choose the pairwise [tile, tile] shape from {static, static/2,
    static/4, static/8} by total priced wall: tiles(t) × per_dispatch(t).
    Honors the bench sweep's forced tile. Returns None when off."""
    if _mode == "off":
        return None
    with _lock:
        forced = _forced_tile
    overhead, cell, source = _pairwise_model()
    candidates = sorted({max(1, static_tile >> s) for s in range(4)})
    estimates = {}
    for t in candidates:
        tiles = (-(-n_a // t)) * (-(-n_b // t)) * max(1, outer)
        # price the full [t, t] shape per dispatch — the kernel pads
        # ragged edges to it, which is exactly why an oversized static
        # tile loses on small row sets (1 padded dispatch costs t² cells
        # no matter how few rows are real)
        estimates[t] = tiles * (overhead + cell * t * t)
    if forced:
        best = forced
    else:
        best = min(sorted(estimates, reverse=True),
                   key=lambda t: estimates[t])
    dec = TileDecision(best, acting(), estimates, source, static_tile)
    with _lock:
        _tile_counts[best] = _tile_counts.get(best, 0) + 1
    _record_decision("tile", {
        "tile": best, "acted": dec.act, "forced": bool(forced),
        "estimates_ms": {t: round(s * 1000, 3)
                         for t, s in estimates.items()},
        "source": source})
    return dec


# ------------------------------------------------------------ cache policy


def cache_mode():
    """off|on|shadow for the stack-cache eviction sites — one read, so
    a concurrent configure() can't split a single eviction's checks."""
    return _mode


def benefit_score(heat, nbytes):
    """heat × rebuild_seconds / resident_bytes — the admission/eviction
    score. Lower = better victim. Compressed entries hold fewer bytes
    AND rebuild cheaper, so the two effects don't cancel: small hot
    entries dominate, large cold entries go first."""
    nbytes = max(int(nbytes), 1)
    rebuild = REBUILD_FIXED_SECONDS + nbytes * UPLOAD_SECONDS_PER_BYTE
    return heat * rebuild / nbytes


def select_victim(entries):
    """Victim key among [(key, heat, nbytes)] — the minimum benefit
    score; FIFO position breaks exact ties (entries arrive in LRU
    order, so degenerate inputs still evict like LRU)."""
    best_key, best_score = None, None
    for key, heat, nbytes in entries:
        score = benefit_score(heat, nbytes)
        if best_score is None or score < best_score:
            best_key, best_score = key, score
    return best_key


def note_eviction(policy, diverged=False):
    """Count one eviction by the policy that chose the victim."""
    with _lock:
        if policy == "benefit":
            _cache_counters["benefit_evictions"] += 1
        else:
            _cache_counters["lru_evictions"] += 1
            if diverged:
                _cache_counters["shadow_divergences"] += 1


# -------------------------------------------------------------- admission


def note_admission(index, field, rows, nbytes, shadow=False):
    with _lock:
        if shadow:
            _admission_counters["shadow_candidates"] += 1
            return
        _admission_counters["admitted_fragments"] += 1
        _admission_counters["admitted_rows"] += rows
        _admission_counters["admitted_bytes"] += nbytes


def note_admission_round():
    with _lock:
        _admission_counters["rounds"] += 1


# ------------------------------------------------------------- /debug view


def snapshot(stacked=None):
    """GET /debug/optimizer: mode, the calibration table with per-family
    sources, decision counters, cache/admission counters, calibration
    bumps, repr strikes, and the recent-decision ring."""
    ewma = _kernel_ewma()
    xla = _xla_seconds(stacked)
    families = sorted(set(ewma) | set(xla))
    calibration = {}
    for family in families:
        secs, src = dispatch_seconds(family, ewma=ewma, xla=xla)
        calibration[family] = {
            "seconds": round(secs, 6), "source": src,
            "samples": ewma.get(family, (0, 0))[1]}
    with _lock:
        fallback = {op: {"seconds_per_shard": round(e[0], 6),
                         "source": "ewma", "samples": e[1]}
                    for op, e in _fallback.items()}
        pairwise = {t: {"seconds": round(e[0], 6), "samples": e[1]}
                    for t, e in _pairwise.items()}
        strategy = {}
        for (op, chosen), n in _strategy_counts.items():
            strategy.setdefault(op, {})[chosen] = n
        out = {
            "mode": _mode,
            "forced_tile": _forced_tile,
            "calibration": {
                "kernels": calibration,
                "fallback": fallback,
                "pairwise_tiles": pairwise,
                "default_dispatch_seconds": DEFAULT_DISPATCH_SECONDS,
            },
            "decisions": {
                "strategy": strategy,
                "tile": dict(sorted(_tile_counts.items())),
                "patch": dict(_patch_counts),
                "cache": dict(_cache_counters),
                "admission": dict(_admission_counters),
            },
            "calibration_bumps": dict(_calibration_bumps),
            "repr_strikes": {f"{i}/{f}": n
                             for (i, f), n in _repr_strikes.items()},
            "recent": list(_recent),
        }
    return out


def decision_counts():
    """Flat counters for bench attempt tagging (one JSON-safe dict)."""
    with _lock:
        strategy = {}
        for (op, chosen), n in _strategy_counts.items():
            strategy[f"{op}:{chosen}"] = n
        return {
            "strategy": strategy,
            "tile": {str(t): n for t, n in _tile_counts.items()},
            "patch": dict(_patch_counts),
            "cache": dict(_cache_counters),
            "admission": dict(_admission_counters),
        }
