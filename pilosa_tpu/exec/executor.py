"""PQL executor: per-shard device evaluation + cross-shard reduce.

Reference: executor.go (Execute :113, executeCall :274, per-shard map fns
:651-1789, mapReduce :2455). The TPU-native redesign:

- Every bitmap call tree evaluates per shard as a chain of device-plane ops
  (pilosa_tpu.ops). Planes are lazily-uploaded, cached fragment rows; ops
  dispatch asynchronously, so an entire call tree becomes one fused stream
  of XLA elementwise kernels with NO host sync until the final reduce.
- Scalar reduces (Count/Sum/Min/Max/TopN counts) stay on device as 0-d
  arrays; the executor stacks them and syncs ONCE per query.
- Cross-shard reduce runs on host (sums/merges), mirroring the reference's
  mapReduce tree but with shard-batched device work (the multi-device path
  in pilosa_tpu.parallel shard-maps the same evaluation over a mesh).
- Per-shard fallback paths (trees the stacked evaluator can't cover) fan
  their shard maps across the shared bounded worker pool
  (utils/workpool.py — the reference's mapReduce worker pool,
  executor.go:2455), reducing IN SHARD ORDER so every worker count gives
  bit-identical results. Workers only issue single-device host/plane
  work; multi-device launches stay behind the stacked evaluator's
  process-wide dispatch lock.

Aggregate semantics (baseValue clamping, notNull fast paths, sign handling)
follow the reference exactly: executeRowBSIGroupShard executor.go:1533,
bsiGroup.baseValue field.go:1583.
"""

import numpy as np

from ..core.field import FIELD_TYPE_INT, FIELD_TYPE_TIME
from ..core.fragment import BSI_EXISTS_BIT, BSI_OFFSET_BIT, BSI_SIGN_BIT
from ..core.row import Row
from ..core import timeq
from ..core.view import VIEW_STANDARD
from ..pql import Call, Condition, parse
from ..shardwidth import SHARD_WIDTH, WORDS_PER_ROW
from ..utils.workpool import shard_map_reduce
from .result import FieldRow, GroupCount, Pair, RowIdentifiers, ValCount

_TOPN_STACK_CHUNK = 256  # rows per stacked device batch


class ExecError(Exception):
    pass


class FieldNotFound(ExecError):
    pass


class ExecOptions:
    def __init__(self, shards=None, exclude_columns=False,
                 column_attrs=False, exclude_row_attrs=False, remote=False,
                 profile=False, explain=None, deadline=None):
        self.shards = shards
        self.exclude_columns = exclude_columns
        self.column_attrs = column_attrs
        self.exclude_row_attrs = exclude_row_attrs
        self.remote = remote
        self.profile = profile
        # None (execute normally), "plan" (?explain=true: build the plan
        # tree, execute NOTHING), or "analyze" (?explain=analyze: execute
        # and graft actual costs onto the plan) — see exec/plan.py
        self.explain = explain
        # absolute time.monotonic() instant after which remaining work
        # is dropped (checked per call and per dispatch), or None
        self.deadline = deadline


def uint_arg(call, key):
    """(value, present) for a non-negative integer argument; rejects
    negatives with the reference's message (pql.Call.UintArg
    pql/ast.go:315: "value for 'x' must be positive, but got -1" — the
    reference errors rather than silently serving an empty result)."""
    val = call.args.get(key)
    if val is None:
        return 0, False
    if isinstance(val, bool) or not isinstance(val, int):
        raise ExecError(
            f"could not convert {val!r} to an unsigned integer "
            f"for '{key}'")
    if val < 0:
        raise ExecError(
            f"value for '{key}' must be positive, but got {val}")
    return val, True


def uint_arg_or_none(call, key):
    """Validated optional unsigned arg: the value, or None when absent."""
    val, has = uint_arg(call, key)
    return val if has else None


def check_write_limit(query, max_writes):
    """(reference: executor.Execute executor.go:135 + ErrTooManyWrites)"""
    if max_writes and max_writes > 0:
        n = sum(1 for c in query.calls if c.writes())
        if n > max_writes:
            raise ExecError("too many write commands")


#: unsigned-integer argument names validated per CALL NAME (the
#: reference rejects negatives via Call.UintArg exactly where these are
#: read; Shift's `n` is deliberately absent — it is a signed IntArg,
#: executor.go:1770)
_UINT_ARGS_BY_CALL = {
    "TopN": ("n", "threshold", "tanimotoThreshold"),
    "Rows": ("limit", "previous", "column"),
    "GroupBy": ("limit", "offset"),
}


def groupby_previous(call, n_children):
    """Validated GroupBy `previous` list cursor, or None when absent: one
    non-negative row id per Rows child, naming the last group a prior page
    returned; results resume lexicographically after it. Per-child
    validation mirrors the reference (Call.UintSliceArg pql/ast.go +
    executeGroupBy's per-field check, executor.go:2737-2745) — a length
    mismatch or a non-uint element errors rather than silently serving
    the wrong page."""
    prev = call.args.get("previous")
    if prev is None:
        return None
    if not isinstance(prev, (list, tuple)):
        raise ExecError(
            "'previous' argument must be a list of row ids for GroupBy")
    if len(prev) != n_children:
        raise ExecError(
            "'previous' argument must have a value for each GroupBy field")
    out = []
    for val in prev:
        if isinstance(val, bool) or not isinstance(val, int):
            raise ExecError(
                f"could not convert {val!r} to an unsigned integer "
                f"for 'previous'")
        if val < 0:
            raise ExecError(
                f"value for 'previous' must be positive, but got {val}")
        out.append(val)
    return out


def validate_uint_args(call):
    """Recursive negative-argument rejection for a whole call tree. Runs
    at the COORDINATOR entry (cluster executor, AFTER key translation) as
    well as inside the local executor, so fast paths that read args raw —
    the SPMD collective plane in particular — can never serve a silently
    wrong slice for a negative n/limit/offset."""
    for key in _UINT_ARGS_BY_CALL.get(call.name, ()):
        if key in call.args:
            uint_arg(call, key)
    if call.name == "GroupBy" and "previous" in call.args:
        groupby_previous(call, len(call.children))
    for child in call.children:
        validate_uint_args(child)
    filt = call.args.get("filter")
    if isinstance(filt, Call):
        validate_uint_args(filt)


def unwrap_options(call, opt):
    """(inner_call, merged_opt) through Options() wrappers (reference:
    executeOptionsCall executor.go:244) — the cluster coordinator uses
    this so result decoration sees the effective call + options."""
    while call.name == "Options" and call.children:
        merged = ExecOptions(
            shards=opt.shards, exclude_columns=opt.exclude_columns,
            column_attrs=opt.column_attrs,
            exclude_row_attrs=opt.exclude_row_attrs,
            remote=opt.remote, profile=opt.profile,
            explain=getattr(opt, "explain", None),
            deadline=getattr(opt, "deadline", None))
        for key, value in call.args.items():
            if key == "excludeColumns":
                merged.exclude_columns = bool(value)
            elif key == "columnAttrs":
                merged.column_attrs = bool(value)
            elif key == "excludeRowAttrs":
                merged.exclude_row_attrs = bool(value)
        opt = merged
        call = call.children[0]
    return call, opt


def fragment_topn_candidates(frag, use_cache=True):
    """THE per-fragment TopN candidate policy: cache ids when a cache is
    populated (the reference's approximation), else every present row.
    Shared by the local executor and the SPMD data plane."""
    if use_cache and frag.cache is not None and len(frag.cache):
        return frag.cache.ids()
    return frag.row_ids()


class Executor:
    """Single-node executor over a Holder. The cluster layer (parallel/)
    wraps this with shard->node fan-out."""

    def __init__(self, holder, max_writes_per_request=0):
        from .stacked import StackedEvaluator

        import threading

        self.holder = holder
        # reject write batches past this many write calls; <=0 = unlimited
        # (reference: Executor.MaxWritesPerRequest executor.go:55)
        self.max_writes_per_request = max_writes_per_request
        self._stacked = StackedEvaluator()
        # ?explain=analyze strategy capture: decision points append the
        # path they actually took to `notes` (set per top-level call by
        # explain_analyze_call; every strategy choice runs on the calling
        # thread, so a thread-local cannot observe another query's calls)
        self._explain_tls = threading.local()

    def stacked_stats(self):
        """Stack-cache observability snapshot (see StackedEvaluator)."""
        return self._stacked.cache_stats()

    def hbm_stats(self, top=50):
        """HBM ledger snapshot (see StackedEvaluator.hbm_snapshot)."""
        return self._stacked.hbm_snapshot(top=top)

    def kernel_stats(self, include_costs=True):
        """Per-kernel attribution (see StackedEvaluator.kernels_snapshot)."""
        return self._stacked.kernels_snapshot(include_costs=include_costs)

    def dispatch_phase_stats(self):
        """Per-kernel dispatch-phase RTT decomposition (see
        StackedEvaluator.dispatch_phases)."""
        return {"phases": self._stacked.dispatch_phases()}

    # ------------------------------------------------------------------ API

    def execute(self, index_name, query, shards=None, options=None):
        """Execute a PQL string or Query; returns a list of results, one per
        top-level call (reference: executor.Execute executor.go:113)."""
        import jax.numpy as jnp  # noqa: F401  (ensures device runtime ready)

        idx = self.holder.index(index_name)
        if idx is None:
            raise ExecError(f"index not found: {index_name}")
        if isinstance(query, str):
            query = parse(query)
        opt = options or ExecOptions()
        check_write_limit(query, self.max_writes_per_request)

        # Key translation happens only on the coordinating node; remote
        # shards always receive integer IDs (reference: executor.go:2610).
        if not opt.remote:
            from .translate import translate_calls, translate_results

            translate_calls(idx, query.calls)

        explain = getattr(opt, "explain", None)
        if explain == "plan":
            # EXPLAIN without ANALYZE: build the annotated plan tree from
            # host-side metadata only and execute NOTHING — the stacked
            # dispatch counters must not move (tests pin the delta at 0)
            from . import plan as plan_mod

            nodes = plan_mod.Planner(self).plan_query(
                idx, query.calls, shards, opt)
            plan_mod.stash(plan_mod.envelope(
                idx.name, "plan", nodes,
                shards=len(self._call_shards(idx, shards))))
            return []

        from ..utils import profile as profile_mod
        from ..utils import tracing
        from ..utils import workload as workload_mod
        from ..utils.stats import global_stats

        import time as _time

        # Per-query stacked-counter deltas: the before/after cache_stats
        # diff attributes dispatches, cache traffic, and upload bytes to
        # THIS query — for the profile when one is active, and for the
        # always-on workload fingerprint table on every non-remote query
        # (remote fan-out legs don't fingerprint themselves, matching
        # the profile rule: the coordinator's entry covers them). The
        # evaluator is shared, so concurrent queries can bleed into each
        # other's deltas — still the right order of magnitude, and exact
        # when queries are serialized (the acceptance path).
        prof = profile_mod.current()
        wctx = None if opt.remote else workload_mod.begin_query(
            idx.name, query)
        wl_before = self._stacked.counters() if wctx is not None else None
        before = self._stacked.cache_stats() if prof is not None else None

        # a previous query's fused-batch stamp must not leak into this
        # query's batch= attribution; same for the whole-plan fused=
        # stamp (both take-last thread-locals, reset per query)
        from .stacked import note_batch_size
        from . import fusion as fusion_mod
        note_batch_size(0)
        fusion_mod.note_fused(0)

        plan_nodes = [] if explain == "analyze" else None
        results = []
        t_query = _time.perf_counter()
        # Deadline propagation: arm the dispatch-boundary thread-local
        # for this query (stacked._locked_dispatch refuses expired work
        # before taking the lock) and check between top-level calls so a
        # multi-call query stops at the first lapsed boundary. None →
        # both checks are no-ops (legacy path).
        from .stacked import DeadlineExceededError, set_thread_deadline
        deadline = getattr(opt, "deadline", None)
        if deadline is not None:
            set_thread_deadline(deadline)
        try:
            with tracing.start_span(
                    "executor.Execute", index=index_name) as span:
                from . import adaptive as adaptive_mod

                # Whole-plan fusion: an eligible multi-call query runs
                # as ONE jitted device program (exec/fusion.py); None →
                # legacy per-call loop, byte-identical to pre-fusion
                fused_results = None
                if fusion_mod.enabled():
                    if plan_nodes is None:
                        fused_results = fusion_mod.maybe_execute(
                            self, idx, query, shards, opt)
                    else:
                        fused_results = self._fused_analyze(
                            idx, query, shards, opt, plan_nodes)
                if fused_results is not None:
                    results = fused_results
                else:
                    for call in query.calls:
                        if deadline is not None \
                                and _time.monotonic() >= deadline:
                            raise DeadlineExceededError(
                                "request deadline expired between calls")
                        t_call = _time.perf_counter()
                        self._explain_tls.last = None
                        with tracing.start_span(
                                f"executor.execute{call.name}"):
                            if plan_nodes is None:
                                results.append(self.execute_call(
                                    idx, call, shards, opt))
                            else:
                                result, node = self.explain_analyze_call(
                                    idx, call, shards, opt)
                                results.append(result)
                                plan_nodes.append(node)
                        call_wall = _time.perf_counter() - t_call
                        # per-PQL-op latency histogram (global registry:
                        # the executor predates any per-server stats
                        # wiring, and registry_of() resolves /metrics to
                        # this registry)
                        global_stats.timing(
                            "query_op_seconds", call_wall,
                            {"op": call.name})
                        if adaptive_mod.enabled():
                            # observed per-shard fallback walls calibrate
                            # the engine's est_fallback side (shadow
                            # learns too)
                            last = getattr(self._explain_tls, "last",
                                           None)
                            if last is not None and last[0] == call.name \
                                    and last[1].startswith("per-shard"):
                                adaptive_mod.observe_fallback(
                                    call.name, call_wall,
                                    len(self._call_shards(idx, shards)))
                if span is not None:
                    span.set_tag("calls", len(query.calls))

            if prof is not None:
                after = self._stacked.cache_stats()
                prof.set_tag("shards_touched",
                             len(self._call_shards(idx, shards)))
                for key, tag in (("dispatches", "dispatches"),
                                 ("pairwise_dispatches",
                                  "pairwise_dispatches"),
                                 ("pairwise_syncs", "pairwise_syncs"),
                                 ("hits", "cache_hits"),
                                 ("misses", "cache_misses")):
                    prof.add(tag, after[key] - before[key])
                prof.add("bytes_materialized",
                         (after["planes_uploaded"]
                          - before["planes_uploaded"])
                         * WORDS_PER_ROW * 4)
        finally:
            if deadline is not None:
                set_thread_deadline(None)
            # even a failed query records its shape — a recurring error
            # shape is exactly what the workload view should surface
            if wctx is not None:
                wl_after = self._stacked.counters()
                workload_mod.end_query(
                    wctx, _time.perf_counter() - t_query, deltas={
                        "dispatches": wl_after[0] - wl_before[0],
                        "cache_hits": wl_after[1] - wl_before[1],
                        "cache_misses": wl_after[2] - wl_before[2],
                        "bytes_materialized":
                            (wl_after[3] - wl_before[3])
                            * WORDS_PER_ROW * 4,
                    })

        if plan_nodes is not None:
            from . import plan as plan_mod

            env = plan_mod.envelope(
                idx.name, "analyze", plan_nodes,
                shards=len(self._call_shards(idx, shards)),
                trace_id=prof.root.trace_id if prof is not None else None)
            plan_mod.stash(env)
            if prof is not None:
                prof.set_tag("plan_summary", plan_mod.summary(plan_nodes))
            # only misestimated plans earn a ring slot: the ring is the
            # triage queue for cost-model drift, not a second query log
            if any(n.misestimates for n in plan_nodes):
                plan_mod.record(
                    env,
                    fingerprint=wctx.fingerprint
                    if wctx is not None else None)

        if not opt.remote:
            results = translate_results(idx, query.calls, results)
        return results

    def explain_analyze_call(self, idx, call, shards, opt):
        """One ?explain=analyze step: build the call's plan node FIRST
        (so estimates can't peek at the outcome), execute it while
        capturing strategy notes + stacked-counter and per-kernel-family
        deltas, then graft the actuals and flag misestimates. Returns
        (result, PlanNode)."""
        import time as _time

        from . import plan as plan_mod

        node = plan_mod.Planner(self).plan_call(idx, call, shards, opt)
        notes = self._explain_tls.notes = []
        before = self._stacked.cache_stats()
        kern_before = self._stacked.kernel_profile()
        phases_before = self._stacked.dispatch_phases()
        t0 = _time.perf_counter()
        try:
            result = self.execute_call(idx, call, shards, opt)
        finally:
            self._explain_tls.notes = None
        wall = _time.perf_counter() - t0
        plan_mod.graft_actual(
            node, wall, before, self._stacked.cache_stats(),
            kern_before, self._stacked.kernel_profile(), strategies=notes,
            phases_before=phases_before,
            phases_after=self._stacked.dispatch_phases())
        return result, node

    def _fused_analyze(self, idx, query, shards, opt, plan_nodes):
        """?explain=analyze over the fused path: build EVERY top-level
        plan node first (so estimates can't peek at the outcome), then
        run the whole query as one fused program, then graft the single
        dispatch's actuals — the whole-query delta lands on the first
        node and the rest graft a zero delta, so the summed per-node
        `dispatches` actuals equal the real total (the ==1 claim the
        bench leg asserts). Returns the results list, or None when the
        query didn't fuse — the caller's legacy analyze loop then
        builds its own nodes (the ones made here are discarded)."""
        import time as _time

        from . import fusion as fusion_mod
        from . import plan as plan_mod

        nodes = plan_mod.Planner(self).plan_query(
            idx, query.calls, shards, opt)
        notes = self._explain_tls.notes = []
        before = self._stacked.cache_stats()
        kern_before = self._stacked.kernel_profile()
        phases_before = self._stacked.dispatch_phases()
        t0 = _time.perf_counter()
        try:
            results = fusion_mod.maybe_execute(
                self, idx, query, shards, opt)
        finally:
            self._explain_tls.notes = None
        if results is None:
            return None
        wall = _time.perf_counter() - t0
        after = self._stacked.cache_stats()
        kern_after = self._stacked.kernel_profile()
        phases_after = self._stacked.dispatch_phases()
        for i, node in enumerate(nodes):
            if i == 0:
                plan_mod.graft_actual(
                    node, wall, before, after, kern_before, kern_after,
                    strategies=notes, phases_before=phases_before,
                    phases_after=phases_after)
            else:
                # later calls rode the first node's dispatch: zero delta
                plan_mod.graft_actual(node, 0.0, after, after,
                                      kern_after, kern_after,
                                      strategies=notes)
        plan_nodes.extend(nodes)
        return results

    def _note_strategy(self, op, strategy, **detail):
        """Record the strategy a decision point ACTUALLY took. Feeds the
        analyze grafting (thread-local notes), the workload fingerprint
        table's per-shape strategy distribution (always on), and, when a
        profile is active, the profile's `strategies` tag — which is
        what SLOW QUERY lines print, so a wedge can be triaged from logs
        alone."""
        from ..utils import profile as profile_mod
        from ..utils import workload as workload_mod

        workload_mod.note_strategy(op, strategy)
        # last (op, strategy) taken on THIS thread — execute()'s per-call
        # timing reads it to attribute fallback walls to the adaptive
        # engine's per-shard calibration
        self._explain_tls.last = (op, strategy)
        notes = getattr(self._explain_tls, "notes", None)
        prof = profile_mod.current()
        if notes is None and prof is None:
            return  # nothing else listening: stay off the hot path
        entry = {"op": op, "strategy": strategy}
        entry.update(detail)
        if notes is not None:
            notes.append(entry)
        if prof is not None:
            prof.note("strategies", entry)

    # ------------------------------------------------------------ adaptive

    def _adaptive_decide(self, op, idx, cover_call, shard_list, kernels,
                         extra_missing_bytes=0):
        """Stacked-vs-fallback pricing for one ELIGIBLE decision point.
        Mirrors the planner's kernel map for the op (exec/plan.py builds
        the same {family: n} before pricing), so the plan path and the
        execute path reach the same decision from the same calibration.
        Returns None when the engine is off or the static gates already
        force the choice — a None means "behave exactly as before"."""
        from . import adaptive
        from .stacked import MIN_SHARDS

        if not adaptive.enabled():
            return None
        if len(shard_list) < MIN_SHARDS:
            return None
        kernels = dict(kernels)
        missing = int(extra_missing_bytes)
        if cover_call is not None:
            # side-effect-free residency walk (no stacks built, no heat)
            probe = self._stacked.residency_probe(
                idx, cover_call, tuple(shard_list))
            if not probe.get("covered"):
                return None
            for family, n in probe.get("extra_kernels", {}).items():
                kernels[family] = kernels.get(family, 0) + n
            missing += int(probe.get("missing_bytes", 0))
        return adaptive.decide_strategy(
            op, kernels, len(shard_list), missing, stacked=self._stacked)

    @staticmethod
    def _chosen_detail(dec):
        """EXPLAIN detail for a priced decision (empty when static)."""
        return {} if dec is None else {"chosen_by": dec.chosen_by}

    def _bsi_missing_bytes(self, idx, field, shard_list):
        """Upload bytes a cold BSI stack build would pay — the planner's
        (depth + 2) planes pricing (_plan_bsi_agg)."""
        st = tuple(shard_list)
        if self._stacked.bsi_stack_resident(idx, field.name, st):
            return 0
        plane = self._stacked._padded_len(st) * WORDS_PER_ROW * 4
        return (field.options.bit_depth + 2) * plane

    def _row_counts_decision(self, idx, field, call, candidates,
                             filter_call, shard_list, view_name):
        """Adaptive pricing for the chunked row-counts gate (TopN /
        single-field GroupBy) — the planner's _plan_topn kernel map."""
        from . import adaptive

        if call is None or not adaptive.enabled():
            return None
        st = tuple(shard_list)
        chunk = self._stacked.row_chunk_size(st)
        n_chunks = -(-len(candidates) // chunk) if candidates else 0
        kernels = {}
        if n_chunks:
            kernels["row_counts"] = n_chunks
        if filter_call is not None:
            kernels["filter"] = 1
        missing_rows = 0
        plane = self._stacked._padded_len(st) * WORDS_PER_ROW * 4
        for i in range(0, len(candidates), chunk):
            part = tuple(candidates[i:i + chunk])
            if not self._stacked.rows_chunk_resident(
                    idx, field.name, part, st, view_name):
                missing_rows += len(part)
        return self._adaptive_decide(
            call.name, idx, filter_call, shard_list, kernels,
            extra_missing_bytes=missing_rows * plane)

    def maybe_proactive_admit(self, max_rows=None, max_bytes=None):
        """Bounded proactive admission of hot_but_not_resident fragments
        — called from idle windows (the coalescer drain loop between
        batches) so demand heat translates into residency BEFORE the
        next query pays the cold build. Skips entirely when the adaptive
        engine is off or a dispatch is in flight (admission must never
        queue behind — or ahead of — real serving traffic). Returns the
        number of fragments admitted (shadow: candidates counted, none
        built)."""
        from . import adaptive
        from ..utils import workload as workload_mod
        from ..utils.stats import global_stats

        if not adaptive.enabled():
            return 0
        st_eval = self._stacked
        if st_eval._dispatch_lock.locked():
            return 0
        max_rows = adaptive.ADMIT_MAX_ROWS if max_rows is None \
            else int(max_rows)
        max_bytes = adaptive.ADMIT_MAX_BYTES if max_bytes is None \
            else int(max_bytes)
        try:
            report = workload_mod.heat().report(
                st_eval.hbm_snapshot(top=0), top=8)
        except Exception:
            return 0
        candidates = report.get("hot_but_not_resident") or []
        if not candidates:
            return 0
        adaptive.note_admission_round()
        admitted = rows_built = bytes_built = 0
        for cand in candidates:
            if rows_built >= max_rows or bytes_built >= max_bytes:
                break
            idx = self.holder.index(cand["index"])
            field = idx.field(cand["field"]) if idx is not None else None
            if field is None:
                continue
            if not adaptive.acting():
                adaptive.note_admission(cand["index"], cand["field"],
                                        0, 0, shadow=True)
                continue
            shard_list = self._call_shards(idx, None)
            if not shard_list:
                continue
            st = tuple(shard_list)
            plane_bytes = st_eval._padded_len(st) * WORDS_PER_ROW * 4
            frag_rows = frag_bytes = 0
            from ..core.field import FIELD_TYPE_INT
            if field.type == FIELD_TYPE_INT:
                if st_eval.bsi_stack(idx, field.name, st) is None:
                    continue
                frag_rows = field.options.bit_depth + 2
                frag_bytes = frag_rows * plane_bytes
            else:
                view = field.view(VIEW_STANDARD)
                if view is None:
                    continue
                row_ids = sorted({r for shard in st
                                  for frag in (view.fragment(shard),)
                                  if frag is not None
                                  for r in frag.row_ids()})
                budget_rows = min(len(row_ids), max_rows - rows_built)
                for row_id in row_ids[:budget_rows]:
                    if bytes_built + frag_bytes >= max_bytes:
                        break
                    if st_eval.leaf_stack(idx, field.name, row_id,
                                          st) is None:
                        break
                    frag_rows += 1
                    frag_bytes += plane_bytes
                if frag_rows == 0:
                    continue
            rows_built += frag_rows
            bytes_built += frag_bytes
            admitted += 1
            # converge /debug/heat: the fragment is resident now, so its
            # heat drops to the hot threshold and the candidate list
            # stops re-recommending it (ISSUE 13 satellite)
            workload_mod.heat().note_admitted(cand["index"], cand["field"])
            adaptive.note_admission(cand["index"], cand["field"],
                                    frag_rows, frag_bytes)
            global_stats.count("stacked_admissions", 1, {"cause": "heat"})
        return admitted

    def execute_call(self, idx, call, shards, opt):
        handler = {
            "Sum": self._exec_sum,
            "Min": self._exec_min,
            "Max": self._exec_max,
            "MinRow": self._exec_min_row,
            "MaxRow": self._exec_max_row,
            "Count": self._exec_count,
            "TopN": self._exec_topn,
            "Rows": self._exec_rows,
            "GroupBy": self._exec_group_by,
            "Options": self._exec_options,
            "Set": self._exec_set,
            "Clear": self._exec_clear,
            "ClearRow": self._exec_clear_row,
            "Store": self._exec_store,
            "SetRowAttrs": self._exec_set_row_attrs,
            "SetColumnAttrs": self._exec_set_column_attrs,
        }.get(call.name)
        if handler is not None:
            return handler(idx, call, shards, opt)
        # default: bitmap call
        return self._exec_bitmap_call(idx, call, shards, opt)

    # ------------------------------------------------------- shard selection

    def _call_shards(self, idx, shards):
        if shards is not None:
            return list(shards)
        return idx.available_shards()

    # --------------------------------------------------- batched execution

    #: single-call read families the batched pipeline can vectorize into
    #: one vmapped dispatch; anything else (aggregates, TopN, writes,
    #: multi-call requests) falls back to the per-query path per member
    BATCHABLE_CALLS = frozenset((
        "Count", "Row", "Range", "Intersect", "Union", "Difference",
        "Xor"))

    def launch_batch(self, index_name, queries, shards=None, options=None):
        """Phase 1 of batched execution: parse/translate/classify every
        query, gather leaf stacks for the batchable ones, and fuse them
        into bucket-padded vmapped dispatches WITHOUT fetching results.
        Returns (handle, state) for resolve_batch. Per-member failures
        are captured in the member's slot, never raised — one bad query
        must not sink its batchmates (per-query error isolation)."""
        import copy
        import time as _time

        from ..utils import workload as workload_mod
        from .translate import translate_calls

        idx = self.holder.index(index_name)
        if idx is None:
            raise ExecError(f"index not found: {index_name}")
        opt = options or ExecOptions()
        shard_list = self._call_shards(idx, shards)
        entries = []
        items = []
        # Coalesced traffic repeats hot queries, so identical PQL
        # strings in one batch share a single parsed (and translated)
        # AST: members only ever read it past this loop. Translation is
        # tracked per AST so a shared tree is key-translated exactly
        # once — it mutates in place and is not idempotent.
        parsed_cache = {}
        translated = set()
        for query in queries:
            # e["raw"] is the member's UNTRANSLATED form: key translation
            # mutates the call tree in place and is not idempotent (a
            # keyed row arg becomes an int; re-translating raises), so
            # every fallback re-execution — not-batchable shape, gather
            # miss, fused-dispatch failure — must start from this, never
            # from e["query"], which execute() would translate again.
            e = {"query": query, "raw": query, "error": None, "item": None,
                 "fallback": False, "wctx": None, "deltas": None,
                 "call": None, "kind": None, "t0": _time.perf_counter()}
            entries.append(e)
            try:
                if isinstance(query, str):
                    q = parsed_cache.get(query)
                    if q is None:
                        q = parsed_cache[query] = parse(query)
                    query = e["query"] = q
                check_write_limit(query, self.max_writes_per_request)
                call = query.calls[0] if len(query.calls) == 1 else None
                if call is None or call.name not in self.BATCHABLE_CALLS:
                    # left untranslated: execute() runs translation
                    e["fallback"] = True
                    continue
                if not opt.remote:
                    if not isinstance(e["raw"], str):
                        e["raw"] = copy.deepcopy(query)
                    if id(query) not in translated:
                        translate_calls(idx, query.calls)
                        translated.add(id(query))
                if call.name == "Count":
                    if len(call.children) != 1:
                        raise ExecError(
                            "Count() takes exactly one row query")
                    tree, kind = call.children[0], "count"
                else:
                    tree, kind = call, "plane"
                self.validate_bitmap_call(idx, tree)
                if kind == "plane":
                    self._bump_fallback_heat(idx, call)
                wctx = workload_mod.begin_query(idx.name, query)
                e["wctx"] = wctx
                wl_before = self._stacked.counters()
                gathered = self._stacked.gather_for_batch(
                    idx, tree, shard_list)
                if gathered is None:
                    # not stack-coverable: the per-query fallback opens
                    # (and records) its own context
                    workload_mod.abort_query(wctx)
                    e["wctx"] = None
                    e["fallback"] = True
                    continue
                wl_after = self._stacked.counters()
                # gather-side deltas now, one dispatch at resolve: the
                # fused launch serves the whole batch, so a per-member
                # counter diff spanning it would bleed batchmates' work.
                # dispatches stays 0 here — resolve_batch charges each
                # fused dispatch to exactly ONE of the members that rode
                # it, so per-shape dispatch counts don't inflate N× on
                # the very path that exists to reduce them
                e["deltas"] = {
                    "dispatches": 0,
                    "cache_hits": wl_after[1] - wl_before[1],
                    "cache_misses": wl_after[2] - wl_before[2],
                    "bytes_materialized":
                        (wl_after[3] - wl_before[3]) * WORDS_PER_ROW * 4,
                }
                e["call"] = call
                e["kind"] = kind
                sig, stacks = gathered
                e["item"] = len(items)
                items.append((kind, sig, stacks))
            except Exception as exc:  # noqa: BLE001 — per-query isolation
                if e["wctx"] is not None:
                    workload_mod.abort_query(e["wctx"])
                    e["wctx"] = None
                e["error"] = exc
        handle = self._stacked.launch_query_batch(items) if items else []
        return handle, (idx, opt, shards, shard_list, entries)

    def resolve_batch(self, handle, state):
        """Phase 2: ONE transfer resolves every fused dispatch, then the
        per-member demux — counts to exact ints, plane stacks to Row
        segments — and fallback members run the ordinary per-query path.
        Returns a list of (results, error, batch_size, fingerprint)
        tuples in submission order: error is the member's exception
        (None on success), batch_size is the fused-dispatch occupancy
        the member rode (0 = per-query path). If the fused dispatch
        itself failed, batched members re-run individually on the legacy
        path so an infrastructure fault degrades to per-query serving
        instead of a batch-wide error."""
        import time as _time

        from ..utils import workload as workload_mod
        from .translate import translate_results

        idx, opt, shards, shard_list, entries = state
        try:
            resolved = self._stacked.resolve_query_batch(handle) \
                if handle else {}
        except Exception:  # noqa: BLE001 — degrade to per-query serving
            resolved = None
        out = []
        charged = set()  # fused dispatches already attributed to a member
        for e in entries:
            query = e["query"]
            wctx = e["wctx"]
            fp = wctx.fingerprint if wctx is not None else None
            try:
                if e["error"] is not None:
                    raise e["error"]
                if e["fallback"] or resolved is None:
                    if wctx is not None:
                        workload_mod.abort_query(wctx)
                    # re-execute from the untranslated form: e["query"]
                    # may already be key-translated (see launch_batch),
                    # and translation is not idempotent
                    results = self.execute(
                        idx.name, e["raw"], shards=shards, options=opt)
                    out.append((results, None, 0,
                                workload_mod.last_fingerprint()))
                    continue
                val, bsize, dseq = resolved[e["item"]]
                if dseq not in charged:
                    charged.add(dseq)
                    e["deltas"]["dispatches"] = 1
                if e["kind"] == "count":
                    results = [val]
                else:
                    row = Row()
                    for j, shard in enumerate(shard_list):
                        seg = val[j]
                        if seg.any():
                            # copy: a view would pin the whole [B, S, W]
                            # transfer buffer for the row's lifetime
                            row.segments[shard] = np.array(seg)
                    if opt.exclude_columns:
                        row.segments = {}
                    if not opt.remote:
                        self.attach_row_attrs(idx, e["call"], row, opt)
                    results = [row]
                if not opt.remote:
                    results = translate_results(idx, query.calls, results)
                # strategy + batch attribution on the member's own ctx
                # (the thread-local points at the LAST member begun, so
                # write through the entry's handle, not note_strategy)
                wctx.strategies.append(
                    f"{e['call'].name}=stacked-batched")
                wctx.batch = bsize
                workload_mod.end_query(
                    wctx, _time.perf_counter() - e["t0"],
                    deltas=e["deltas"])
                out.append((results, None, bsize, fp))
            except Exception as exc:  # noqa: BLE001 — per-query isolation
                if wctx is not None:
                    workload_mod.abort_query(wctx)
                out.append((None, exc, 0, fp))
        return out

    def execute_batch(self, index_name, queries, shards=None,
                      options=None):
        """Batched execution, launch + resolve in one call (the explicit
        POST /index/{i}/query-batch route). The coalescer drives the two
        phases separately so batch N+1's launch overlaps batch N's
        resolve (double buffering)."""
        handle, state = self.launch_batch(
            index_name, queries, shards=shards, options=options)
        return self.resolve_batch(handle, state)

    # ------------------------------------------------------- bitmap calls

    def validate_bitmap_call(self, idx, call):
        """Structural checks independent of shard data (so empty indexes
        still reject malformed queries, matching the reference's per-shard
        errors)."""
        name = call.name
        if name in ("Intersect", "Difference", "Xor") and not call.children:
            raise ExecError(f"empty {name} query is currently not supported")
        if name == "Not":
            if len(call.children) != 1:
                raise ExecError("Not() takes exactly one row query")
            if not idx.options.track_existence:
                raise ExecError("Not() requires existence tracking on the index")
        if name == "Shift" and len(call.children) != 1:
            raise ExecError("Shift() takes exactly one row query")
        if name in ("Row", "Range"):
            field_name = call.field_arg() if not call.has_conditions() else \
                next(iter(call.args))
            if idx.field(field_name) is None:
                raise FieldNotFound(f"field not found: {field_name}")
        known = {"Row", "Range", "Intersect", "Union", "Difference", "Xor",
                 "Not", "Shift", "All"}
        if name not in known:
            raise ExecError(f"unknown call: {name}")
        for child in call.children:
            self.validate_bitmap_call(idx, child)

    def _bump_fallback_heat(self, idx, call):
        """Host-fallback accesses feed the fragment heat ledger too: a
        working set that never enters the stacked path must still look
        hot to the admission policy (the stacked cache probes in
        exec/stacked.py cover the cached path). One bump per Row/Range
        leaf per query — demand frequency, not shard fan-out."""
        from ..utils import workload as workload_mod

        if call.name in ("Row", "Range") and call.args:
            from ..pql.ast import is_reserved_arg

            field_name = next(
                (k for k in call.args if not is_reserved_arg(k)), None)
            if field_name is not None \
                    and idx.field(field_name) is not None:
                workload_mod.heat_bump(
                    idx.name, field_name, VIEW_STANDARD)
        for child in call.children:
            self._bump_fallback_heat(idx, child)

    def _exec_bitmap_call(self, idx, call, shards, opt):
        import jax

        self.validate_bitmap_call(idx, call)
        self._bump_fallback_heat(idx, call)
        # Dispatch every shard's plane chain asynchronously (fanned over
        # the worker pool), then fetch all result planes in ONE
        # device->host transfer (the per-shard chains themselves never
        # sync; see module docstring).
        shard_list = self._call_shards(idx, shards)
        per_shard = shard_map_reduce(
            shard_list, lambda shard: self.bitmap_call_shard(idx, call, shard))
        planes = [(shard, plane)
                  for shard, plane in zip(shard_list, per_shard)
                  if plane is not None]
        row = Row()
        if planes:
            hosts = jax.device_get([p for _, p in planes])
            for (shard, _), host in zip(planes, hosts):
                if host.any():
                    row.segments[shard] = host
        if opt.exclude_columns:
            # strip at the source: remote partials must not ship column
            # payloads the coordinator would immediately discard
            row.segments = {}
        if not opt.remote:
            self.attach_row_attrs(idx, call, row, opt)
        return row

    def attach_row_attrs(self, idx, call, row, opt):
        """Coordinator-side Row result decoration (reference:
        executeBitmapCall executor.go:605-645): plain Row() calls carry
        the row's attributes unless excludeRowAttrs; excludeColumns strips
        the column payload (attrs-only responses). Remote partials skip
        this — only the coordinating node decorates."""
        if call.name in ("Row", "Range") and not call.has_conditions() \
                and "from" not in call.args and "to" not in call.args:
            if opt.exclude_row_attrs:
                row.attrs = {}
            else:
                field_name = call.field_arg()
                field = idx.field(field_name) if field_name else None
                row_id = call.args.get(field_name) if field_name else None
                if field is not None and field.row_attr_store is not None \
                        and isinstance(row_id, int) \
                        and not isinstance(row_id, bool):
                    attrs = field.row_attr_store.attrs(row_id)
                    if attrs:
                        row.attrs = attrs
        if opt.exclude_columns:
            row.segments = {}

    def _zeros(self):
        import jax.numpy as jnp

        return jnp.zeros(WORDS_PER_ROW, dtype=jnp.uint32)

    def bitmap_call_shard(self, idx, call, shard):
        """Evaluate a bitmap call tree for one shard -> device plane (or
        None when provably empty). Reference: executeBitmapCallShard
        executor.go:651."""
        from ..ops import bitplane

        name = call.name
        if name == "Row":
            return self._row_shard(idx, call, shard)
        if name == "Range":  # deprecated alias for Row
            return self._row_shard(idx, call, shard)
        if name == "Intersect":
            if not call.children:
                raise ExecError("empty Intersect query is currently not supported")
            planes = [self.bitmap_call_shard(idx, c, shard)
                      for c in call.children]
            if any(p is None for p in planes):
                return None
            out = planes[0]
            for p in planes[1:]:
                out = bitplane.intersect(out, p)
            return out
        if name == "Union":
            planes = [self.bitmap_call_shard(idx, c, shard)
                      for c in call.children]
            planes = [p for p in planes if p is not None]
            if not planes:
                return None
            out = planes[0]
            for p in planes[1:]:
                out = bitplane.union(out, p)
            return out
        if name == "Difference":
            if not call.children:
                raise ExecError("empty Difference query is currently not supported")
            first = self.bitmap_call_shard(idx, call.children[0], shard)
            if first is None:
                return None
            out = first
            for c in call.children[1:]:
                p = self.bitmap_call_shard(idx, c, shard)
                if p is not None:
                    out = bitplane.difference(out, p)
            return out
        if name == "Xor":
            planes = [self.bitmap_call_shard(idx, c, shard)
                      for c in call.children]
            planes = [p if p is not None else self._zeros() for p in planes]
            if not planes:
                raise ExecError("empty Xor query is currently not supported")
            out = planes[0]
            for p in planes[1:]:
                out = bitplane.xor(out, p)
            return out
        if name == "Not":
            if not idx.options.track_existence:
                raise ExecError("Not() requires existence tracking on the index")
            if len(call.children) != 1:
                raise ExecError("Not() takes exactly one row query")
            exists = self._existence_plane(idx, shard)
            if exists is None:
                return None
            child = self.bitmap_call_shard(idx, call.children[0], shard)
            if child is None:
                return exists
            return bitplane.difference(exists, child)
        if name == "Shift":
            if len(call.children) != 1:
                raise ExecError("Shift() takes exactly one row query")
            n = int(call.args.get("n", 1))
            child = self.bitmap_call_shard(idx, call.children[0], shard)
            if child is None:
                return None
            # NOTE per-shard shift only; cross-segment carry is handled by
            # the reference the same way (Row.Shift shifts within segments).
            return bitplane.shift(child, n)
        if name == "All":
            exists = self._existence_plane(idx, shard)
            return exists
        raise ExecError(f"unknown call: {name}")

    def _existence_plane(self, idx, shard):
        field = idx.existence_field()
        if field is None:
            return None
        return self._fragment_row_plane(field, VIEW_STANDARD, shard, 0)

    def _fragment_row_plane(self, field, view_name, shard, row_id):
        view = field.view(view_name)
        if view is None:
            return None
        frag = view.fragment(shard)
        if frag is None:
            return None
        return frag.row_device(row_id)

    def _row_shard(self, idx, call, shard):
        """Row(field=rowID), Row(field=rowID, from=..., to=...), or BSI
        Row(field <op> value). Reference: executeRowShard executor.go:1441."""
        if call.has_conditions():
            return self._row_bsi_shard(idx, call, shard)

        field_name = call.field_arg()
        field = idx.field(field_name)
        if field is None:
            raise FieldNotFound(f"field not found: {field_name}")
        row_id = call.args[field_name]
        if isinstance(row_id, bool):
            row_id = 1 if row_id else 0
        if not isinstance(row_id, int):
            raise ExecError(
                f"Row(): row ID must be an integer or key: {row_id!r}")

        has_time = "from" in call.args or "to" in call.args
        if not has_time:
            return self._fragment_row_plane(field, VIEW_STANDARD, shard, row_id)

        if field.type != FIELD_TYPE_TIME:
            raise ExecError(f"field {field_name} is not a time field")
        from_t = timeq.parse_time(call.args["from"]) if "from" in call.args \
            else timeq.parse_time("1970-01-01T00:00")
        to_t = timeq.parse_time(call.args["to"]) if "to" in call.args \
            else timeq.parse_time("2100-01-01T00:00")
        from ..ops import bitplane

        out = None
        for view_name in timeq.views_by_time_range(
                VIEW_STANDARD, from_t, to_t, field.time_quantum()):
            plane = self._fragment_row_plane(field, view_name, shard, row_id)
            if plane is None:
                continue
            out = plane if out is None else bitplane.union(out, plane)
        return out

    # -- BSI row conditions --------------------------------------------------

    def _bsi_meta(self, idx, field_name):
        field = idx.field(field_name)
        if field is None:
            raise FieldNotFound(f"field not found: {field_name}")
        if field.type != FIELD_TYPE_INT:
            raise ExecError(f"field {field_name} is not an int field")
        return field

    def _bsi_planes(self, field, shard):
        """(planes [D,W], sign, exists) device arrays, or None if fragment
        absent."""
        import jax.numpy as jnp

        view = field.view(field.bsi_view_name())
        if view is None:
            return None
        frag = view.fragment(shard)
        if frag is None:
            return None
        depth = field.options.bit_depth
        exists = frag.row_device(BSI_EXISTS_BIT)
        sign = frag.row_device(BSI_SIGN_BIT)
        planes = jnp.stack([
            frag.row_device(BSI_OFFSET_BIT + i) for i in range(depth)])
        return planes, sign, exists

    def _not_null_plane(self, field, shard):
        view = field.view(field.bsi_view_name())
        if view is None:
            return None
        frag = view.fragment(shard)
        if frag is None:
            return None
        return frag.row_device(BSI_EXISTS_BIT)

    def _row_bsi_shard(self, idx, call, shard):
        """Row(field <op> value) for one shard via the shared condition
        plan (exec/bsicond.py — the same plan+kernels evaluate stacked
        [D,S,W] planes on the serving path). Reference:
        executeRowBSIGroupShard executor.go:1533."""
        from .bsicond import BsiConditionError, apply_bsi_condition, \
            bsi_condition_plan

        if len(call.args) != 1:
            raise ExecError("Row(): condition required" if not call.args
                            else "Row(): too many arguments")
        field_name, cond = next(iter(call.args.items()))
        if not isinstance(cond, Condition):
            raise ExecError("Row(): expected condition argument")
        field = self._bsi_meta(idx, field_name)
        try:
            plan = bsi_condition_plan(field.options, cond)
        except BsiConditionError as e:
            raise ExecError(str(e)) from e
        if plan[0] == "empty":
            return None
        if plan[0] == "notnull":
            return self._not_null_plane(field, shard)
        data = self._bsi_planes(field, shard)
        if data is None:
            return None
        planes, sign, exists = data
        return apply_bsi_condition(plan, planes, sign, exists)

    # ------------------------------------------------------------ aggregates

    def _exec_count(self, idx, call, shards, opt):
        """(reference: executeCount executor.go:1790)"""
        from ..ops import bitplane
        import jax.numpy as jnp

        if len(call.children) != 1:
            raise ExecError("Count() takes exactly one row query")
        self.validate_bitmap_call(idx, call.children[0])
        shard_list = self._call_shards(idx, shards)
        dec = self._adaptive_decide("Count", idx, call.children[0],
                                    shard_list, {"count": 1})
        # Fast path: linearizable Row/set-op trees evaluate over ALL shards
        # in one fused dispatch on generation-cached [S, W] stacks.
        fast = None if (dec is not None and dec.act
                        and dec.strategy == "fallback") \
            else self._stacked.try_count(idx, call.children[0], shard_list)
        if fast is not None:
            from ..utils import workload as workload_mod
            from .stacked import last_batch_size

            # how many concurrent queries shared the fused dispatch
            # (group-commit batching stamps it on this thread); feeds
            # analyze actuals + SLOW QUERY batch= attribution
            n = last_batch_size() or 1
            self._note_strategy("Count", "stacked", batch=n,
                                **self._chosen_detail(dec))
            if n > 1:
                workload_mod.note_batch(n)
            return fast
        self._note_strategy("Count", "per-shard",
                            **self._chosen_detail(dec))

        def count_shard(shard):
            plane = self.bitmap_call_shard(idx, call.children[0], shard)
            return None if plane is None else bitplane.popcount(plane)

        counts = [c for c in shard_map_reduce(shard_list, count_shard)
                  if c is not None]
        if not counts:
            return 0
        # Host int sum: per-shard counts fit int32 (<= 2^20) but the total
        # can exceed 2^31 past 2048 shards.
        import jax

        return int(np.sum(np.asarray(
            jax.device_get(jnp.stack(counts)), dtype=np.int64)))

    def _sum_filter_planes(self, idx, call, shard):
        """Returns (has_filter, plane). has_filter with plane None means the
        filter is provably empty in this shard — the shard contributes
        nothing (distinct from 'no filter given')."""
        if call.children:
            self.validate_bitmap_call(idx, call.children[0])
            return True, self.bitmap_call_shard(idx, call.children[0], shard)
        return False, None

    def _agg_field(self, idx, call):
        field_name = call.args.get("field") or call.args.get("_field")
        if field_name is None:
            field_name = call.field_arg()
        return self._bsi_meta(idx, field_name)

    def _agg_filter_call(self, idx, call):
        """The optional filter child of an aggregate call, validated."""
        if call.children:
            self.validate_bitmap_call(idx, call.children[0])
            return call.children[0]
        return None

    def _exec_sum(self, idx, call, shards, opt):
        """(reference: executeSum executor.go:331 + fragment.sum)"""
        from ..ops import bsi as bsi_ops
        import jax.numpy as jnp

        field = self._agg_field(idx, call)
        opts = field.options
        depth = opts.bit_depth
        shard_list = self._call_shards(idx, shards)
        filter_call = self._agg_filter_call(idx, call)
        kernels = {"sum": 1}
        if filter_call is not None:
            kernels["filter"] = 1
        dec = self._adaptive_decide(
            "Sum", idx, filter_call, shard_list, kernels,
            extra_missing_bytes=self._bsi_missing_bytes(
                idx, field, shard_list))
        # Fast path: one fused dispatch over stacked BSI planes for all
        # shards (falls back when the filter tree isn't stack-coverable).
        fast = None if (dec is not None and dec.act
                        and dec.strategy == "fallback") \
            else self._stacked.try_sum(idx, field, filter_call, shard_list)
        if fast is not None:
            self._note_strategy("Sum", "stacked-sum",
                                **self._chosen_detail(dec))
            total, count = fast
            return ValCount(total + opts.base * count, count)
        self._note_strategy("Sum", "per-shard",
                            **self._chosen_detail(dec))

        def sum_shard(shard):
            data = self._bsi_planes(field, shard)
            if data is None:
                return None
            planes, sign, exists = data
            has_filter, filt = self._sum_filter_planes(idx, call, shard)
            if has_filter and filt is None:
                return None  # empty filter -> shard contributes nothing
            if filt is None:
                filt = jnp.full(WORDS_PER_ROW, 0xFFFFFFFF, dtype=jnp.uint32)
            return bsi_ops.bsi_plane_counts(planes, sign, exists, filt)

        per_shard = [r for r in shard_map_reduce(shard_list, sum_shard)
                     if r is not None]
        total, count = 0, 0
        for pos, negc, cnt in per_shard:
            pos = np.asarray(pos)
            negc = np.asarray(negc)
            total += sum(int(pos[i]) << i for i in range(depth))
            total -= sum(int(negc[i]) << i for i in range(depth))
            count += int(cnt)
        # base contributes once per existing column (reference: Sum adds
        # base*count since stored values are base-adjusted)
        total += opts.base * count
        return ValCount(total, count)

    def _minmax_shard(self, field, idx, call, shard, is_max):
        from ..ops import bitplane, bsi as bsi_ops

        data = self._bsi_planes(field, shard)
        if data is None:
            return ValCount()
        planes, sign, exists = data
        consider = exists
        has_filter, filt = self._sum_filter_planes(idx, call, shard)
        if has_filter and filt is None:
            return ValCount()
        if filt is not None:
            consider = bitplane.intersect(consider, filt)
        if not bool(bitplane.any_set(consider)):
            return ValCount()
        pos = bitplane.difference(consider, sign)
        neg = bitplane.intersect(consider, sign)
        has_pos = bool(bitplane.any_set(pos))
        has_neg = bool(bitplane.any_set(neg))
        if is_max:
            # highest positive, else closest-to-zero negative (reference:
            # fragment.max fragment.go:1190)
            if has_pos:
                bits, final = bsi_ops.max_unsigned(planes, pos)
                sign_mult = 1
            else:
                bits, final = bsi_ops.min_unsigned(planes, neg)
                sign_mult = -1
        else:
            # lowest negative (largest magnitude), else lowest positive
            if has_neg:
                bits, final = bsi_ops.max_unsigned(planes, neg)
                sign_mult = -1
            else:
                bits, final = bsi_ops.min_unsigned(planes, pos)
                sign_mult = 1
        bits = np.asarray(bits)
        mag = sum(int(b) << i for i, b in enumerate(bits))
        count = int(bitplane.popcount(final))
        return ValCount(sign_mult * mag + field.options.base, count)

    def _exec_min(self, idx, call, shards, opt):
        return self._exec_minmax(idx, call, shards, is_max=False)

    def _exec_max(self, idx, call, shards, opt):
        return self._exec_minmax(idx, call, shards, is_max=True)

    def _exec_minmax(self, idx, call, shards, is_max):
        field = self._agg_field(idx, call)
        shard_list = self._call_shards(idx, shards)
        # Fast path: the narrowing bit-plane walk runs ONCE over stacked
        # [D, S, W] planes (globally — identical result to the per-shard
        # merge) instead of once per shard.
        op_name = "Max" if is_max else "Min"
        filter_call = self._agg_filter_call(idx, call)
        kernels = {"minmax": 1}
        if filter_call is not None:
            kernels["filter"] = 1
        dec = self._adaptive_decide(
            op_name, idx, filter_call, shard_list, kernels,
            extra_missing_bytes=self._bsi_missing_bytes(
                idx, field, shard_list))
        fast = None if (dec is not None and dec.act
                        and dec.strategy == "fallback") \
            else self._stacked.try_minmax(idx, field, filter_call,
                                          shard_list, is_max)
        if fast is not None:
            self._note_strategy(op_name, "stacked-minmax",
                                **self._chosen_detail(dec))
            mag, count = fast
            if mag is None:
                return ValCount()
            return ValCount(mag + field.options.base, count)
        self._note_strategy(op_name, "per-shard",
                            **self._chosen_detail(dec))
        # Ordered reduce: larger/smaller tie-breaking is order-sensitive,
        # so the pool's shard-order reduction is what keeps every worker
        # count bit-identical to the serial loop.
        return shard_map_reduce(
            shard_list,
            lambda shard: self._minmax_shard(field, idx, call, shard, is_max),
            reducer=lambda out, vc: out.larger(vc) if is_max
            else out.smaller(vc),
            initial=ValCount())

    def _set_field(self, idx, call):
        field_name = call.args.get("field") or call.args.get("_field")
        if field_name is None:
            field_name = call.field_arg()
        field = idx.field(field_name)
        if field is None:
            raise FieldNotFound(f"field not found: {field_name}")
        return field

    def _exec_min_row(self, idx, call, shards, opt):
        """(reference: executeMinRow executor.go:380 + fragment.minRow)"""
        return self._minmax_row(idx, call, shards, is_max=False)

    def _exec_max_row(self, idx, call, shards, opt):
        return self._minmax_row(idx, call, shards, is_max=True)

    def _minmax_row(self, idx, call, shards, is_max):
        from ..ops import bitplane

        field = self._set_field(idx, call)
        if call.children:
            self.validate_bitmap_call(idx, call.children[0])

        def shard_best(shard):
            """This shard's first non-empty row in direction order (the
            serial loop stopped at it regardless of the global best)."""
            view = field.view(VIEW_STANDARD)
            frag = view.fragment(shard) if view else None
            if frag is None:
                return None
            filt = None
            if call.children:
                filt = self.bitmap_call_shard(idx, call.children[0], shard)
                if filt is None:
                    return None
            for row_id in (reversed(frag.row_ids()) if is_max
                           else frag.row_ids()):
                plane = frag.row_device(row_id)
                if filt is not None:
                    plane = bitplane.intersect(plane, filt)
                cnt = int(bitplane.popcount(plane))
                if cnt > 0:
                    return (row_id, cnt)
            return None

        def merge(best, cand):
            if cand is None:
                return best
            row_id, cnt = cand
            if best is None or (is_max and row_id > best[0]) or \
                    (not is_max and row_id < best[0]):
                return (row_id, cnt)
            if row_id == best[0]:
                return (row_id, best[1] + cnt)
            return best

        best = shard_map_reduce(
            self._call_shards(idx, shards), shard_best, reducer=merge)
        if best is None:
            return Pair(0, 0)
        return Pair(best[0], best[1])

    # ---------------------------------------------------------------- TopN

    def _exec_topn(self, idx, call, shards, opt):
        """TopN via device popcounts over cache-selected candidates.

        The reference approximates with per-fragment rank caches + heap
        merge (executor.go:930, fragment.top fragment.go:1570); here the
        cache bounds which row planes get stacked, then exact counts come
        from fused popcount dispatches (O(1) in shards on the stacked
        path). Cache-less fields fall back to an exact full-row scan (a
        superset of reference behavior).

        threshold / tanimotoThreshold follow executor.go:947-995 +
        fragment.top fragment.go:1570-1700: threshold drops rows whose
        (filtered) count is below it; tanimotoThreshold T (1-100, requires
        a source row) keeps rows where ceil(100·|row ∩ src| /
        (|row| + |src| - |row ∩ src|)) > T."""
        import math

        field = self._set_field(idx, call)
        if field.type == FIELD_TYPE_INT:
            raise ExecError(
                f'cannot compute TopN() on integer field: "{field.name}"')
        if len(call.children) > 1:
            raise ExecError("TopN() can only have one input bitmap")
        if call.children:
            self.validate_bitmap_call(idx, call.children[0])
        n = uint_arg_or_none(call, "n")
        ids = call.args.get("ids")
        if ids is not None and (
                not isinstance(ids, list)
                or any(isinstance(r, bool) or not isinstance(r, int)
                       for r in ids)):
            # (reference: validateCallArgs executor.go:342-358)
            raise ExecError(f"invalid call.Args[ids]: {ids!r}")
        thr = uint_arg_or_none(call, "threshold")
        threshold = 1 if thr is None else thr
        tanimoto, _ = uint_arg(call, "tanimotoThreshold")
        if tanimoto > 100:  # negatives already rejected by uint_arg
            raise ExecError("Tanimoto Threshold is from 1 to 100 only")
        if tanimoto > 0 and not call.children:
            raise ExecError(
                "TopN(): tanimotoThreshold requires a source row query")
        counts = self._row_counts(idx, field, call, shards,
                                  restrict_ids=ids, use_cache=ids is None)
        # row-attribute filter (reference: attrName/attrValues
        # executor.go:982-1005)
        attr_name = call.args.get("attrName")
        if attr_name is not None and field.row_attr_store is not None:
            attr_values = call.args.get("attrValues")
            if not isinstance(attr_values, list):
                raise ExecError("TopN(): attrValues must be a list")
            counts = {
                r: c for r, c in counts.items()
                if field.row_attr_store.attrs(r).get(attr_name) in attr_values
            }
        src = call.children[0] if call.children else None
        # tanimoto needs each row's UNFILTERED cardinality and the source
        # row's count; both come from host container cardinalities / the
        # count fast path — no extra per-shard device work.
        if tanimoto > 0 and src is not None:
            shard_list = self._call_shards(idx, shards)
            plain = self._plain_row_counts(idx, field, counts, shard_list)
            src_count = self._count_of(idx, src, shard_list)
            kept = {}
            for row_id, cnt in counts.items():
                if cnt <= 0:
                    continue
                denom = plain[row_id] + src_count - cnt
                coeff = math.ceil(cnt * 100 / denom) if denom else 100
                if coeff > tanimoto:
                    kept[row_id] = cnt
            counts = kept
        # threshold and tanimoto are either/or (fragment.top:1610-1620).
        min_count = 1 if (tanimoto > 0 and src is not None) \
            else max(threshold, 1)
        pairs = [Pair(row_id, cnt) for row_id, cnt in counts.items()
                 if cnt >= min_count]
        pairs.sort(key=lambda p: (-p.count, p.id))
        # remote shards return untrimmed pairs so the coordinator's merge
        # stays exact (reference: executeTopN trims only when !opt.Remote)
        if n is not None and ids is None and not opt.remote:
            pairs = pairs[:int(n)]
        return pairs

    def _plain_row_counts(self, idx, field, row_ids, shard_list):
        """row -> UNFILTERED global cardinality, from host container
        cardinalities (no device work; reference: fragment.rowCount)."""
        totals = {int(r): 0 for r in row_ids}
        view = field.view(VIEW_STANDARD)
        if view is None:
            return totals
        keys = list(totals)

        def shard_counts(shard):
            frag = view.fragment(shard)
            if frag is None:
                return None
            return [frag.row_count(r) for r in keys]

        for counts in shard_map_reduce(shard_list, shard_counts):
            if counts is None:
                continue
            for r, c in zip(keys, counts):
                totals[r] += c
        return totals

    def _count_of(self, idx, call, shard_list):
        """Count of a bitmap call over shards (stacked fast path, else
        per-shard popcount sum)."""
        from ..ops import bitplane

        fast = self._stacked.try_count(idx, call, shard_list)
        if fast is not None:
            return fast

        def count_one(shard):
            plane = self.bitmap_call_shard(idx, call, shard)
            if plane is None:
                return 0
            return int(bitplane.popcount(plane))

        return shard_map_reduce(
            shard_list, count_one,
            reducer=lambda acc, c: acc + c, initial=0)

    def _candidate_rows(self, field, shard_list, restrict_ids, use_cache,
                        view_name):
        """Global candidate row set: union over fragments of their TopN
        cache ids (when populated) or all present rows."""
        view = field.view(view_name)
        if view is None:
            return []

        def shard_rows(shard):
            frag = view.fragment(shard)
            if frag is None:
                return None
            return fragment_topn_candidates(frag, use_cache)

        rows = set()
        for cand in shard_map_reduce(shard_list, shard_rows):
            if cand is not None:
                rows.update(cand)
        if restrict_ids is not None:
            wanted = {int(r) for r in restrict_ids}
            rows &= wanted
        return sorted(rows)

    def _row_counts(self, idx, field, call, shards, restrict_ids=None,
                    view_name=VIEW_STANDARD, use_cache=False):
        """row -> total count across shards, optionally intersected with the
        call's first child as filter. With use_cache, candidate rows come
        from the fragment's TopN cache when one is populated (the
        reference's approximation: only cached rows compete).

        Fast path: candidate rows stack into [R, S, W] chunks and ALL
        shards count in O(rows/chunk) fused dispatches — dispatch count
        independent of the shard count (vs. the reference's per-shard
        fragment.top scans). Falls back per-shard when the filter tree
        isn't stack-coverable (conditions, time ranges, ...)."""
        from ..ops import bitplane
        import jax.numpy as jnp

        shard_list = self._call_shards(idx, shards)
        filter_call = call.children[0] \
            if (call is not None and call.children) else None

        from .stacked import MIN_SHARDS

        dec = None
        if len(shard_list) >= MIN_SHARDS:
            covered, filt = self._stacked.filter_stack(
                idx, filter_call, tuple(shard_list))
            if covered:
                candidates = self._candidate_rows(
                    field, shard_list, restrict_ids, use_cache, view_name)
                dec = self._row_counts_decision(
                    idx, field, call, candidates, filter_call,
                    shard_list, view_name)
                totals = None \
                    if (dec is not None and dec.act
                        and dec.strategy == "fallback") \
                    else self._stacked.row_counts(
                        idx, field.name, candidates, filt, shard_list,
                        view_name)
                if totals is not None:
                    if call is not None:
                        self._note_strategy(call.name,
                                            "stacked-row-counts",
                                            **self._chosen_detail(dec))
                    if restrict_ids is not None:
                        for r in restrict_ids:
                            totals.setdefault(int(r), 0)
                    return totals
        if call is not None:
            self._note_strategy(call.name, "per-shard-chunked",
                                **self._chosen_detail(dec))

        # Fallback: per-shard chains, but over the SAME global candidate
        # set as the fast path (union across fragments), so both paths
        # return identical counts for identical data.
        candidates = self._candidate_rows(
            field, shard_list, restrict_ids, use_cache, view_name)
        totals = {}

        def shard_chunks(shard):
            """Per-shard chunked device popcounts (single-device ops only;
            safe to issue concurrently from pool workers)."""
            view = field.view(view_name)
            frag = view.fragment(shard) if view else None
            if frag is None:
                return []
            filt = None
            if filter_call is not None:
                filt = self.bitmap_call_shard(idx, filter_call, shard)
                if filt is None:
                    return []  # empty filter -> zero counts in this shard
            present = set(frag.row_ids())
            row_ids = [r for r in candidates if r in present]
            out = []
            for i in range(0, len(row_ids), _TOPN_STACK_CHUNK):
                chunk = row_ids[i:i + _TOPN_STACK_CHUNK]
                stack = jnp.stack([frag.row_device(r) for r in chunk])
                if filt is not None:
                    stack = stack & filt[None, :]
                out.append((chunk, bitplane.popcount_rows(stack)))
            return out

        pending = [pc for per_shard in
                   shard_map_reduce(shard_list, shard_chunks)
                   for pc in per_shard]
        for chunk, dev_counts in pending:
            host = np.asarray(dev_counts)
            for r, c in zip(chunk, host):
                totals[r] = totals.get(r, 0) + int(c)
        if restrict_ids is not None:
            for r in restrict_ids:
                totals.setdefault(int(r), 0)
        return totals

    # ---------------------------------------------------------------- Rows

    def _rows_views(self, field, call):
        """View names Rows() inspects: the standard view, or for a time
        field with from/to (or noStandardView) the minimal quantum-view
        cover of the range, clamped to the views that actually exist
        (reference: executeRowsShard executor.go:1338-1400 +
        minMaxViews/timeOfView time.go:240-340)."""
        if field.type != FIELD_TYPE_TIME:
            return [VIEW_STANDARD]
        from_t = timeq.parse_time(call.args["from"]) \
            if "from" in call.args else None
        to_t = timeq.parse_time(call.args["to"]) \
            if "to" in call.args else None
        if from_t is None and to_t is None \
                and not field.options.no_standard_view:
            return [VIEW_STANDARD]
        quantum = field.time_quantum()
        if not quantum:
            return []
        vmin, vmax = timeq.min_max_views(
            list(field.views), quantum, VIEW_STANDARD)
        if vmin is None:
            return []
        min_t = timeq.time_of_view(vmin, VIEW_STANDARD)
        max_t = timeq.time_of_view(vmax, VIEW_STANDARD, adj=True)
        if from_t is None or from_t < min_t:
            from_t = min_t
        if to_t is None or to_t > max_t:
            to_t = max_t
        return timeq.views_by_time_range(
            VIEW_STANDARD, from_t, to_t, quantum)

    def _exec_rows(self, idx, call, shards, opt):
        """(reference: executeRows executor.go:1280)"""
        field = self._set_field(idx, call)
        limit = uint_arg_or_none(call, "limit")
        previous = uint_arg_or_none(call, "previous")
        column = uint_arg_or_none(call, "column")

        rows = set()
        shard_list = self._call_shards(idx, shards)
        for view_name in self._rows_views(field, call):
            view = field.view(view_name)
            if view is None:
                continue

            def shard_rows(shard, view=view):
                frag = view.fragment(shard)
                if frag is None:
                    return None
                if column is not None:
                    if column // SHARD_WIDTH != shard:
                        return None
                    return {r for r in frag.row_ids()
                            if frag.contains(r, column)}
                return set(frag.row_ids())

            for found in shard_map_reduce(shard_list, shard_rows):
                if found is not None:
                    rows.update(found)
        out = sorted(rows)
        if previous is not None:
            out = [r for r in out if r > previous]
        if limit is not None and not opt.remote:
            out = out[:limit]
        return RowIdentifiers(rows=out)

    # -------------------------------------------------------------- GroupBy

    def _exec_group_by(self, idx, call, shards, opt):
        """(reference: executeGroupBy executor.go:1098)"""
        from ..ops import bitplane
        import jax.numpy as jnp

        if not call.children:
            raise ExecError("GroupBy requires at least one Rows() child")
        for child in call.children:
            if child.name != "Rows":
                raise ExecError("GroupBy children must be Rows() calls")
        limit = uint_arg_or_none(call, "limit")
        offset = uint_arg_or_none(call, "offset")
        previous = groupby_previous(call, len(call.children))
        filter_call = call.args.get("filter")
        if filter_call is not None:
            if not isinstance(filter_call, Call):
                raise ExecError("GroupBy filter must be a row query")
            self.validate_bitmap_call(idx, filter_call)

        fields = [self._set_field(idx, child) for child in call.children]
        shard_list = self._call_shards(idx, shards)

        # Child Rows() limit/previous/column apply to the GLOBAL merged row
        # set (exactly Rows() semantics, reused).
        child_rows = [
            self._exec_rows(idx, child, shards, opt).rows
            for child in call.children
        ]
        if previous is not None:
            # Seed the outermost child's row start (the reference seeks
            # each row iterator, executor.go:1403-1406; later iterators
            # cycle back to their full row sets, so only the outermost —
            # which never wraps — prunes soundly). Groups at or before
            # the cursor are dropped lexicographically below.
            lo = previous[0] + (1 if len(child_rows) == 1 else 0)
            child_rows[0] = [r for r in child_rows[0] if r >= lo]

        dec, tile_dec, tile = self._group_by_decision(
            idx, fields, child_rows, filter_call, shard_list)
        totals = None if (dec is not None and dec.act
                          and dec.strategy == "fallback") \
            else self._group_by_stacked(
                idx, fields, child_rows, filter_call, shard_list,
                tile=tile)
        if totals is None:
            self._note_strategy("GroupBy", "per-shard",
                                **self._chosen_detail(dec))
            totals = self._group_by_per_shard(
                idx, fields, child_rows, filter_call, shard_list)
        elif len(fields) == 1:
            self._note_strategy("GroupBy", "stacked-row-counts",
                                **self._chosen_detail(dec))
        else:
            shown = tile if tile is not None \
                else self._stacked.row_chunk_size(tuple(shard_list))
            detail = self._chosen_detail(dec)
            if tile_dec is not None:
                detail["tile_chosen_by"] = tile_dec.chosen_by
            self._note_strategy("GroupBy", "stacked-pairwise",
                                tile=[shown, shown], **detail)
        if previous is not None:
            prev_t = tuple(previous)
            totals = {g: c for g, c in totals.items() if g > prev_t}

        out = [
            GroupCount(
                [FieldRow(f.name, rid) for f, rid in zip(fields, group)],
                cnt)
            for group, cnt in sorted(totals.items())
        ]
        if limit is not None and not opt.remote:
            out = out[:limit]
        # offset applies after the limit-bounded merge, and is a NO-OP
        # when it reaches past the result set (reference guards
        # `offset < len(results)`: executeGroupBy executor.go:1134-1143)
        if offset is not None and not opt.remote and offset < len(out):
            out = out[offset:]
        return out

    def _group_by_decision(self, idx, fields, child_rows, filter_call,
                           shard_list):
        """(strategy decision, tile decision, tile override) for one
        GroupBy — the planner's _plan_group_by kernel map. The tile
        override is None unless the engine is acting AND chose a
        non-static shape."""
        from . import adaptive

        if not adaptive.enabled():
            return None, None, None
        st = tuple(shard_list)
        chunk = self._stacked.row_chunk_size(st)
        plane = self._stacked._padded_len(st) * WORDS_PER_ROW * 4
        # the planner prices cold row-chunk uploads the same way
        # (_plan_group_by's _missing_row_chunks loop) — keep the two
        # sides' est_stacked in agreement
        missing = 0
        for field, rows in zip(fields, child_rows):
            for i in range(0, len(rows), chunk):
                part = tuple(rows[i:i + chunk])
                if not self._stacked.rows_chunk_resident(
                        idx, field.name, part, st, VIEW_STANDARD):
                    missing += len(part) * plane
        if len(fields) == 1:
            n = -(-len(child_rows[0]) // chunk) if child_rows[0] else 0
            dec = self._adaptive_decide(
                "GroupBy", idx, filter_call, shard_list,
                {"row_counts": n} if n else {},
                extra_missing_bytes=missing)
            return dec, None, None
        a_rows, b_rows = child_rows[-2], child_rows[-1]
        outer = 1
        for rows in child_rows[:-2]:
            outer *= max(1, len(rows))
        tile_dec = adaptive.decide_tile(
            chunk, len(a_rows), len(b_rows), outer=outer) \
            if a_rows and b_rows else None
        tile = tile_dec.tile if (tile_dec is not None and tile_dec.act
                                 and tile_dec.tile != chunk) else None
        t = tile if tile is not None else chunk
        pairwise = (-(-len(a_rows) // t)) * (-(-len(b_rows) // t)) \
            * outer if a_rows and b_rows else 0
        dec = self._adaptive_decide(
            "GroupBy", idx, filter_call, shard_list,
            {"pairwise": pairwise} if pairwise else {},
            extra_missing_bytes=missing)
        return dec, tile_dec, tile

    def _group_by_stacked(self, idx, fields, child_rows, filter_call,
                          shard_list, tile=None):
        """Thin driver over the stacked pairwise kernel: the innermost TWO
        levels are one tiled cross-product count matrix
        (StackedEvaluator.pairwise_counts — O(⌈R1/tile⌉·⌈R2/tile⌉) fused
        dispatches + host syncs, vs one `row_counts` round trip per outer
        row combination before); outer levels walk row combinations as
        [S, W] device intersections in chunks; a single-field GroupBy
        batch-counts its rows directly. Returns None to fall back (too
        few shards, a filter the stacked path can't express, or a
        field/view vanishing mid-query — the per-shard path is
        untouched)."""
        from .stacked import MIN_SHARDS

        if len(shard_list) < MIN_SHARDS:
            return None
        shards = tuple(shard_list)
        covered, filt = self._stacked.filter_stack(idx, filter_call, shards)
        if not covered:
            return None

        if len(fields) == 1:
            counts = self._stacked.row_counts(
                idx, fields[0].name, child_rows[0], filt, shards)
            if counts is None:
                return None
            return {(r,): c for r, c in counts.items() if c > 0}

        totals = {}
        a_field, b_field = fields[-2], fields[-1]
        a_rows, b_rows = child_rows[-2], child_rows[-1]
        chunk_size = self._stacked.row_chunk_size(shards)

        def recurse(level, plane, prefix):
            """plane: accumulated [S, W] restriction (None = everything).
            Returns False to abort (stack construction failed; caller
            falls back to the per-shard path)."""
            if level == len(fields) - 2:
                groups = self._stacked.pairwise_counts(
                    idx, a_field.name, a_rows, b_field.name, b_rows,
                    plane, shards, tile=tile)
                if groups is None:
                    return False
                for pair, c in groups.items():
                    key = prefix + pair
                    totals[key] = totals.get(key, 0) + c
                return True
            # Outer-level row planes come from the rows pool in chunks (not
            # the leaf pool: a wide outer field must not evict the hot
            # Count/Sum serving stacks), sliced per combination.
            rows = child_rows[level]
            for i in range(0, len(rows), chunk_size):
                chunk = tuple(rows[i:i + chunk_size])
                stack = self._stacked.rows_stack(
                    idx, fields[level].name, chunk, shards)
                if stack is None:
                    return False
                for j, row_id in enumerate(chunk):
                    combined = stack[j] if plane is None \
                        else plane & stack[j]
                    if not recurse(level + 1, combined, prefix + (row_id,)):
                        return False
            return True

        if not recurse(0, filt, ()):
            return None
        return totals

    def _group_by_per_shard(self, idx, fields, child_rows, filter_call,
                            shard_list):
        from ..ops import bitplane
        import jax.numpy as jnp

        def shard_totals(shard):
            """This shard's group -> count map (single-device intersect
            chains + one host sync; independent across shards)."""
            frag_rows = []
            for field, rows in zip(fields, child_rows):
                view = field.view(VIEW_STANDARD)
                frag = view.fragment(shard) if view else None
                if frag is None:
                    return None
                present = set(frag.row_ids())
                frag_rows.append((frag, [r for r in rows if r in present]))
            filt = None
            if filter_call is not None:
                filt = self.bitmap_call_shard(idx, filter_call, shard)
                if filt is None:
                    return None

            # depth-first cross product with early pruning on empty planes
            pending = []

            def recurse(level, plane, prefix):
                frag, row_ids = frag_rows[level]
                for row_id in row_ids:
                    p = frag.row_device(row_id)
                    combined = p if plane is None else bitplane.intersect(plane, p)
                    if level + 1 == len(frag_rows):
                        pending.append((prefix + (row_id,),
                                        bitplane.popcount(combined)))
                    else:
                        recurse(level + 1, combined, prefix + (row_id,))

            recurse(0, filt, ())
            out = {}
            if pending:
                groups, dev_counts = zip(*pending)
                host = np.asarray(jnp.stack(list(dev_counts)))  # one sync
                for group, c in zip(groups, host):
                    if int(c) > 0:
                        out[group] = out.get(group, 0) + int(c)
            return out

        totals = {}
        for shard_counts in shard_map_reduce(shard_list, shard_totals):
            if not shard_counts:
                continue
            for group, c in shard_counts.items():
                totals[group] = totals.get(group, 0) + c
        return totals

    # -------------------------------------------------------------- Options

    def _exec_options(self, idx, call, shards, opt):
        """(reference: executeOptionsCall executor.go:244)"""
        if len(call.children) != 1:
            raise ExecError("Options() takes exactly one query")
        new_opt = ExecOptions(
            shards=opt.shards, exclude_columns=opt.exclude_columns,
            column_attrs=opt.column_attrs,
            exclude_row_attrs=opt.exclude_row_attrs,
            remote=opt.remote, profile=opt.profile,
            explain=getattr(opt, "explain", None),
            deadline=getattr(opt, "deadline", None))
        for key, value in call.args.items():
            if key == "shards":
                if not isinstance(value, list):
                    raise ExecError("Options(): shards must be a list")
                shards = [int(s) for s in value]
            elif key == "excludeColumns":
                new_opt.exclude_columns = bool(value)
            elif key == "columnAttrs":
                new_opt.column_attrs = bool(value)
            elif key == "excludeRowAttrs":
                new_opt.exclude_row_attrs = bool(value)
            else:
                raise ExecError(f"Options(): unknown arg {key!r}")
        return self.execute_call(idx, call.children[0], shards, new_opt)

    # ---------------------------------------------------------------- writes

    def _exec_set(self, idx, call, shards, opt):
        """(reference: executeSet executor.go:2067)"""
        col = self._require_col(call)
        field_name = call.field_arg()
        field = idx.field(field_name)
        if field is None:
            raise FieldNotFound(f"field not found: {field_name}")
        value = call.args[field_name]

        if field.type == FIELD_TYPE_INT:
            if not isinstance(value, int) or isinstance(value, bool):
                raise ExecError("Set(): int field requires an integer value")
            changed = field.set_value(col, value)
        else:
            timestamp = None
            if "_timestamp" in call.args:
                timestamp = timeq.parse_time(call.args["_timestamp"])
            if isinstance(value, bool):
                row_id = 1 if value else 0
            elif isinstance(value, int):
                row_id = value
            else:
                raise ExecError(
                    f"Set(): row must be an integer or key: {value!r}")
            changed = field.set_bit(row_id, col, timestamp=timestamp)
        idx.add_existence([col])
        return bool(changed)

    def _exec_clear(self, idx, call, shards, opt):
        col = self._require_col(call)
        field_name = call.field_arg()
        field = idx.field(field_name)
        if field is None:
            raise FieldNotFound(f"field not found: {field_name}")
        value = call.args[field_name]
        if field.type == FIELD_TYPE_INT:
            return bool(field.clear_value(col))
        if isinstance(value, bool):
            row_id = 1 if value else 0
        else:
            row_id = int(value)
        return bool(field.clear_bit(row_id, col))

    def _exec_clear_row(self, idx, call, shards, opt):
        """(reference: executeClearRow executor.go:1825)"""
        field_name = call.field_arg()
        field = idx.field(field_name)
        if field is None:
            raise FieldNotFound(f"field not found: {field_name}")
        row_id = int(call.args[field_name])
        zeros = np.zeros(WORDS_PER_ROW, dtype=np.uint32)
        changed = False
        shard_list = self._call_shards(idx, shards)
        # Clear across every non-BSI view so time views stay consistent with
        # the standard view (reference: executeClearRowShard walks f.views()).
        for view_name, view in list(field.views.items()):
            if view_name.startswith("bsig_"):
                continue

            def clear_shard(shard, view=view):
                frag = view.fragment(shard)
                if frag is None:
                    return False
                return bool(frag.set_row_plane(row_id, zeros))

            changed |= any(shard_map_reduce(shard_list, clear_shard))
        return changed

    def _exec_store(self, idx, call, shards, opt):
        """(reference: executeSetRow executor.go:1900) Store(child, f=row)"""
        if len(call.children) != 1:
            raise ExecError("Store() takes exactly one row query")
        field_name = call.field_arg()
        field = idx.field(field_name)
        if field is None:
            # reference creates the field on demand for Store
            from ..core.field import FieldOptions

            field = idx.create_field(field_name, FieldOptions())
        row_id = int(call.args[field_name])
        view = field.create_view_if_not_exists(VIEW_STANDARD)

        def gather_shard(shard):
            plane = self.bitmap_call_shard(idx, call.children[0], shard)
            return (np.zeros(WORDS_PER_ROW, dtype=np.uint32)
                    if plane is None else np.asarray(plane))

        # Parallel read phase, then writes applied serially in shard
        # order: create_fragment_if_not_exists mutates the view's
        # fragment dict, which must not race.
        shard_list = self._call_shards(idx, shards)
        planes = shard_map_reduce(shard_list, gather_shard)
        changed = False
        for shard, host in zip(shard_list, planes):
            frag = view.create_fragment_if_not_exists(shard)
            changed |= bool(frag.set_row_plane(row_id, host))
        return changed

    def _exec_set_row_attrs(self, idx, call, shards, opt):
        field = idx.field(call.args["_field"])
        if field is None:
            raise FieldNotFound(f"field not found: {call.args['_field']}")
        if field.row_attr_store is None:
            raise ExecError("row attributes not configured")
        row_id = int(call.args["_row"])
        attrs = {k: v for k, v in call.args.items() if not k.startswith("_")}
        field.row_attr_store.set_attrs(row_id, attrs)
        return None

    def _exec_set_column_attrs(self, idx, call, shards, opt):
        if idx.column_attr_store is None:
            raise ExecError("column attributes not configured")
        col = self._require_col(call)
        attrs = {k: v for k, v in call.args.items() if not k.startswith("_")}
        idx.column_attr_store.set_attrs(col, attrs)
        return None

    def _require_col(self, call):
        col = call.args.get("_col")
        if col is None:
            raise ExecError(f"{call.name}() requires a column argument")
        if not isinstance(col, int) or isinstance(col, bool):
            raise ExecError(f"column must be an integer or key: {col!r}")
        return col
