"""Stacked serving fast paths: whole-index evaluation in O(1) dispatches.

The general executor evaluates call trees shard by shard — correct for
every call, but each shard costs device dispatches. For the serving-critical
calls — Count, TopN, Sum, Min, Max, GroupBy (executor.go:930,1790,331,1098)
— this module evaluates ALL shards in a constant number of fused XLA
dispatches: fragment rows become [shards, words] stacked planes resident on
device, call trees become jitted elementwise+popcount+reduce programs, and
per-query work is a handful of dispatches and ONE host sync, independent of
the shard count.

Stacks are cached per (kind, index, field, rows, shard-set) and invalidated
by the fragments' write-generation counters (fragment.generation — bumped by
every mutation), so a stale stack can never serve a query. LRU-bounded: at
SHARD_WIDTH=2^20 a 954-shard stack is ~120 MB of HBM, so only the hottest
rows stay resident (the device analog of fragment.rowCache fragment.go:367).

On a multi-device host the stacks are placed sharded over a 1-D "shards"
mesh (zero-padded to a device multiple — zero rows are count-neutral for
every supported op), so the SAME jitted programs are GSPMD partitioned by
XLA: per-device popcounts reduce over ICI instead of one chip doing all the
work (SURVEY §2 parallelism: the shard axis is the one SPMD axis).

Overflow discipline: per-(row,shard) popcounts fit int32 (≤ 2^20), but
totals over shards can exceed 2^31 (a >2048-shard index). TPUs run JAX with
x64 disabled, so instead of int64 accumulators every cross-shard reduce
returns a (hi, lo) int32 pair — hi = Σ(count >> 16), lo = Σ(count & 0xffff)
— combined on host as exact Python ints. Safe to 2^15 shards (32768 shards
≈ 34 trillion columns per node).
"""

import threading
from collections import OrderedDict

import numpy as np

from ..core.fragment import BSI_EXISTS_BIT, BSI_OFFSET_BIT, BSI_SIGN_BIT
from ..core.index import EXISTENCE_FIELD_NAME
from ..core.view import VIEW_STANDARD
from ..shardwidth import WORDS_PER_ROW

# Device-byte budget for cached stacks; excess evicts least-recently-used.
# (Entry size scales with shard count — ~120 MB per 954-shard stack — so a
# count bound alone could pin several GB of HBM.)
MAX_STACK_BYTES = 512 * 1024 * 1024
# Separate budget for TopN/GroupBy row-chunk stacks ([rows, shards, words]
# keyed by the exact candidate tuple): they are large and churn with any
# candidate-set change, so they must not be able to evict the long-lived
# leaf/BSI stacks the Count/Sum serving paths depend on.
MAX_ROWS_STACK_BYTES = 256 * 1024 * 1024
# Compiled tree programs are tiny but unbounded shapes would accumulate.
MAX_FNS = 128
# Below this many shards the per-shard path's dispatch count is too small
# to matter.
MIN_SHARDS = 2
# Transient row-chunk stacks ([rows, shards, words]) are built at most this
# large, so TopN/GroupBy dispatch count is O(rows/chunk) — independent of
# the shard count.
CHUNK_BYTES = 128 * 1024 * 1024

_OPS = {"Intersect": "&", "Union": "|", "Difference": "-", "Xor": "^"}

_UNSET = object()

from ..ops import bitplane  # noqa: E402
from ..ops.bitplane import combine_hi_lo  # noqa: E402  (canonical helper)


def tree_signature(idx, call, leaves, leaf, bsi_leaf=None):
    """THE coverage walk for stacked/SPMD fast paths: turns a bitmap call
    tree into an operator signature over leaf slots, or None when any
    shape isn't expressible (time ranges, Shift, keys, ...).
    `leaf(idx, field_name, row_id, leaves)` decides row-leaf eligibility —
    the stacked evaluator requires a local standard view; the SPMD plane
    checks replicated schema only (cluster/spmd.py).
    `bsi_leaf(idx, field_name, cond, leaves)` (optional) covers BSI
    condition leaves like Row(v > 10) the same way (reference algorithm:
    fragment.go:1357-1470); None declines conditions entirely."""
    name = call.name
    if name in ("Row", "Range"):
        if "from" in call.args or "to" in call.args:
            return None
        if call.has_conditions():
            if bsi_leaf is None or len(call.args) != 1:
                return None
            from ..pql import Condition

            field_name, cond = next(iter(call.args.items()))
            if not isinstance(cond, Condition):
                return None
            return bsi_leaf(idx, field_name, cond, leaves)
        field_name = call.field_arg()
        if field_name is None:
            return None
        row_id = call.args.get(field_name)
        if isinstance(row_id, bool):
            row_id = int(row_id)
        if not isinstance(row_id, int):
            return None
        return leaf(idx, field_name, row_id, leaves)
    if name in _OPS and call.children:
        subs = tuple(tree_signature(idx, c, leaves, leaf, bsi_leaf)
                     for c in call.children)
        if any(s is None for s in subs):
            return None
        return (_OPS[name], subs)
    if name == "Not" and len(call.children) == 1 \
            and idx.options.track_existence \
            and idx.field(EXISTENCE_FIELD_NAME) is not None:
        child = tree_signature(idx, call.children[0], leaves, leaf,
                               bsi_leaf)
        if child is None:
            return None
        exists = leaf(idx, EXISTENCE_FIELD_NAME, 0, leaves)
        if exists is None:
            return None
        return ("-", (exists, child))
    return None


class StackedEvaluator:
    def __init__(self):
        self._stacks = OrderedDict()  # key -> (gens, device arrays, nbytes)
        self._stack_bytes = 0
        self._rows_stacks = OrderedDict()  # row-chunk pool (own budget)
        self._rows_stack_bytes = 0
        self._fns = OrderedDict()     # kernel signature -> jitted fn
        self._lock = threading.Lock()
        self._sharding = _UNSET
        # Kernel-dispatch counter: tests assert serving dispatch counts are
        # independent of the shard count.
        self.dispatches = 0
        # Cache observability (exported at /debug/vars "stacked"): without
        # these, budget thrash (VERDICT r2) is invisible in production.
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        # Incremental-maintenance observability: a patch re-uploads only
        # the drifted shards' planes instead of the whole stack; tests
        # assert planes_uploaded stays O(changed shards) under writes.
        self.patches = 0
        self.planes_uploaded = 0

    def _stack_sharding(self):
        """NamedSharding over all local devices (None on a single device),
        resolved lazily so importing this module never touches the
        backend."""
        if self._sharding is _UNSET:
            import jax

            # local_devices: host-local numpy stacks can't be placed onto
            # other processes' chips; cross-host scale-out is the cluster
            # layer's job (shards_by_node), not this cache's.
            devices = jax.local_devices()
            if len(devices) < 2:
                self._sharding = None
            else:
                mesh = jax.sharding.Mesh(np.array(devices), ("shards",))
                self._sharding = jax.sharding.NamedSharding(
                    mesh, jax.sharding.PartitionSpec("shards"))
        return self._sharding

    def _n_pad_devices(self):
        sharding = self._stack_sharding()
        return 1 if sharding is None else len(sharding.device_set)

    def _padded_len(self, shards):
        """Shard-axis length zero-padded to a device multiple. Load-bearing
        agreement: filter [S_pad, W] and rows [R, S_pad, W] stacks must use
        the SAME padding or their elementwise combine misaligns."""
        n_dev = self._n_pad_devices()
        return ((len(shards) + n_dev - 1) // n_dev) * n_dev

    def _place(self, host_stack, shard_axis):
        """Upload a host stack, sharded over the device mesh along
        `shard_axis` (already zero-padded by the caller)."""
        import jax

        sharding = self._stack_sharding()
        if sharding is None:
            return jax.device_put(host_stack)
        spec = [None] * host_stack.ndim
        spec[shard_axis] = "shards"
        return jax.device_put(host_stack, jax.sharding.NamedSharding(
            sharding.mesh, jax.sharding.PartitionSpec(*spec)))

    # -- tree analysis -------------------------------------------------------

    def _leaf(self, idx, field_name, row_id, leaves):
        field = idx.field(field_name)
        if field is None or field.view(VIEW_STANDARD) is None:
            return None
        # tagged key: a field literally named "bsicond" must not collide
        # with condition-leaf keys in the shared leaves dict
        key = ("row", field_name, int(row_id))
        if key not in leaves:
            leaves[key] = len(leaves)
        return ("leaf", leaves[key])

    def _bsi_leaf(self, idx, field_name, cond, leaves):
        """Condition-leaf eligibility: an int field with a local BSI view
        and a normalizable condition. The leaf key carries (op, values) so
        identical conditions share one slot."""
        from .bsicond import normalize_bsi_condition

        field = idx.field(field_name)
        if field is None or field.options.type != "int" \
                or field.view(field.bsi_view_name()) is None:
            return None
        norm = normalize_bsi_condition(cond)
        if norm is None:
            return None
        op, vals = norm
        key = ("bsicond", field_name, op, vals)
        if key not in leaves:
            leaves[key] = len(leaves)
        return ("leaf", leaves[key])

    def signature(self, idx, call, leaves):
        """Tree signature with leaf slots, or None when the tree has any
        shape the fast path doesn't cover (time ranges, Shift, keys...).
        None means: use the general per-shard path."""
        return tree_signature(idx, call, leaves, self._leaf, self._bsi_leaf)

    # -- stack cache ---------------------------------------------------------

    def _fragment_gens(self, idx, field_name, shards,
                       view_name=VIEW_STANDARD):
        """Cache-validation fingerprint: per-shard (fragment uid,
        generation). The uid makes a recreated fragment (field dropped and
        re-made at the same path) distinct from its predecessor even when
        the generation counters collide. None when the field vanished
        (concurrent DDL) — caller falls back to the general path."""
        field = idx.field(field_name)
        view = field.view(view_name) if field is not None else None
        if view is None:
            return None
        gens = []
        for shard in shards:
            frag = view.fragment(shard)
            gens.append((-1, -1) if frag is None
                        else (frag.uid, frag.generation))
        return tuple(gens)

    def _pool(self, key):
        """Row-chunk stacks live in their own LRU pool (see
        MAX_ROWS_STACK_BYTES)."""
        if key[0] == "rows":
            return self._rows_stacks, MAX_ROWS_STACK_BYTES
        return self._stacks, MAX_STACK_BYTES

    def _cache_get(self, key, gens):
        pool, _ = self._pool(key)
        with self._lock:
            hit = pool.get(key)
            if hit is not None and hit[0] == gens:
                pool.move_to_end(key)
                self.hits += 1
                return hit[1]
            self.misses += 1
        return None

    def _cache_put(self, key, gens, arrays, nbytes):
        pool, budget = self._pool(key)
        rows = pool is self._rows_stacks
        with self._lock:
            old = pool.pop(key, None)
            if old is not None:
                if rows:
                    self._rows_stack_bytes -= old[2]
                else:
                    self._stack_bytes -= old[2]
            pool[key] = (gens, arrays, nbytes)
            if rows:
                self._rows_stack_bytes += nbytes
                while self._rows_stack_bytes > budget and len(pool) > 1:
                    _, evicted = pool.popitem(last=False)
                    self._rows_stack_bytes -= evicted[2]
                    self.evictions += 1
            else:
                self._stack_bytes += nbytes
                while self._stack_bytes > budget and len(pool) > 1:
                    _, evicted = pool.popitem(last=False)
                    self._stack_bytes -= evicted[2]
                    self.evictions += 1

    def leaf_stack(self, idx, field_name, row_id, shards):
        """Cached [S, W] device stack of one row over `shards`."""
        key = ("leaf", idx.name, field_name, row_id, shards)
        gens = self._fragment_gens(idx, field_name, shards)
        if gens is None:
            return None
        hit = self._cache_get(key, gens)
        if hit is not None:
            return hit
        field = idx.field(field_name)
        view = field.view(VIEW_STANDARD) if field is not None else None
        if view is None:
            return None
        # Incremental maintenance: when k << S shards drifted (a write
        # bumps only its fragment's generation), gather + upload ONLY
        # those planes and scatter them into the cached device stack —
        # the device analog of the reference's op-log-over-snapshot delta
        # (roaring.go:228-249) — instead of re-uploading the whole [S, W]
        # stack for a single set_bit.
        stale = self._stale_entry(key, gens)
        if stale is not None:
            changed = self._changed_shards(stale[0], gens, shards)
            if changed is not None:
                import jax.numpy as jnp

                block = self._host_rows(
                    view, [row_id], [shards[j] for j in changed],
                    pad=False)
                stack = self._place(
                    stale[1].at[np.asarray(changed)].set(
                        jnp.asarray(block[0])), shard_axis=0)
                self.patches += 1
                self._cache_put(key, gens, stack, stack.size * 4)
                return stack
        host = self._host_rows(view, [row_id], shards)
        stack = self._place(host[0], shard_axis=0)
        self._cache_put(key, gens, stack, stack.size * 4)
        return stack

    def _host_rows(self, view, row_ids, shards, pad=True):
        """Host [R, S_padded, W] uint32 gather of rows over shards
        (pad=False skips the device-multiple padding — patch gathers
        address existing stack rows directly)."""
        n = self._padded_len(shards) if pad else len(shards)
        out = np.zeros((len(row_ids), n, WORDS_PER_ROW), dtype=np.uint32)
        for j, shard in enumerate(shards):
            frag = view.fragment(shard)
            if frag is None:
                continue
            for i, row_id in enumerate(row_ids):
                plane = frag.row_plane(row_id)
                if plane is not None:
                    out[i, j] = np.asarray(plane)
        self.planes_uploaded += len(row_ids) * len(shards)
        return out

    def _stale_entry(self, key, gens):
        """(old_gens, arrays, nbytes) of a cached entry whose generations
        drifted, or None. Read under the lock; the returned arrays are
        immutable device buffers so using them outside the lock is safe."""
        pool, _ = self._pool(key)
        with self._lock:
            entry = pool.get(key)
            if entry is None or len(entry[0]) != len(gens):
                return None
            return entry

    def _changed_shards(self, old_gens, gens, shards):
        """Stack row indices whose (uid, generation) drifted, or None when
        a device patch isn't worthwhile (more than half the shards moved —
        the scatter would cost about as much as a rebuild)."""
        changed = [j for j, (o, n) in enumerate(zip(old_gens, gens))
                   if o != n]
        if not changed or len(changed) * 2 > len(shards):
            return None
        return changed

    def rows_stack(self, idx, field_name, row_chunk, shards,
                   view_name=VIEW_STANDARD, cache=True):
        """Cached [R, S, W] device stack of a chunk of rows (TopN/GroupBy
        candidates). `row_chunk` must be a tuple (cache key). cache=False
        builds a transient stack (freed after use) — callers pass it when
        the full candidate set exceeds the rows pool, so oversized scans
        don't churn out every reusable chunk."""
        key = ("rows", idx.name, field_name, view_name, row_chunk, shards)
        gens = self._fragment_gens(idx, field_name, shards, view_name)
        if gens is None:
            return None
        hit = self._cache_get(key, gens)
        if hit is not None:
            return hit
        field = idx.field(field_name)
        view = field.view(view_name) if field is not None else None
        if view is None:
            return None
        if cache:
            stale = self._stale_entry(key, gens)
            if stale is not None:
                changed = self._changed_shards(stale[0], gens, shards)
                if changed is not None:
                    import jax.numpy as jnp

                    block = self._host_rows(
                        view, list(row_chunk),
                        [shards[j] for j in changed], pad=False)
                    stack = self._place(
                        stale[1].at[:, np.asarray(changed)].set(
                            jnp.asarray(block)), shard_axis=1)
                    self.patches += 1
                    self._cache_put(key, gens, stack, stack.size * 4)
                    return stack
        host = self._host_rows(view, list(row_chunk), shards)
        stack = self._place(host, shard_axis=1)
        if cache:
            self._cache_put(key, gens, stack, stack.size * 4)
        return stack

    def bsi_stack(self, idx, field_name, shards):
        """Cached (planes [D,S,W], sign [S,W], exists [S,W]) device stacks
        of a BSI field's bit-plane rows (reference layout fragment.go:91-93).
        None when the field/view vanished."""
        field = idx.field(field_name)
        if field is None:
            return None
        view_name = field.bsi_view_name()
        depth = field.options.bit_depth
        key = ("bsi", idx.name, field_name, depth, shards)
        gens = self._fragment_gens(idx, field_name, shards, view_name)
        if gens is None:
            return None
        hit = self._cache_get(key, gens)
        if hit is not None:
            return hit
        view = field.view(view_name)
        if view is None:
            return None
        rows = [BSI_EXISTS_BIT, BSI_SIGN_BIT] + [
            BSI_OFFSET_BIT + i for i in range(depth)]
        stale = self._stale_entry(key, gens)
        if stale is not None:
            changed = self._changed_shards(stale[0], gens, shards)
            if changed is not None:
                import jax.numpy as jnp

                planes, sign, exists = stale[1]
                block = jnp.asarray(self._host_rows(
                    view, rows, [shards[j] for j in changed], pad=False))
                jdx = np.asarray(changed)
                arrays = (
                    self._place(planes.at[:, jdx].set(block[2:]),
                                shard_axis=1),
                    self._place(sign.at[jdx].set(block[1]), shard_axis=0),
                    self._place(exists.at[jdx].set(block[0]),
                                shard_axis=0),
                )
                self.patches += 1
                self._cache_put(key, gens, arrays, stale[2])
                return arrays
        host = self._host_rows(view, rows, shards)
        arr = self._place(host, shard_axis=1)
        arrays = (arr[2:], arr[1], arr[0])  # planes, sign, exists
        self._cache_put(key, gens, arrays, arr.size * 4)
        return arrays

    def bsi_condition_stack(self, idx, key, shards):
        """[S, W] mask of a BSI condition leaf evaluated over the cached
        (and incrementally patched) [D, S, W] plane stack in ONE extra
        dispatch — Count(Row(v > 10)) stays O(1)-in-shards (VERDICT r4
        item 4; reference per-shard algorithm fragment.go:1357-1470)."""
        from .bsicond import (
            BsiConditionError,
            apply_bsi_condition,
            bsi_condition_plan,
            condition_from_key,
        )

        _, field_name, op, vals = key
        field = idx.field(field_name)
        if field is None or field.options.type != "int":
            return None
        try:
            plan = bsi_condition_plan(
                field.options, condition_from_key(op, vals))
        except BsiConditionError:
            return None
        # the empty/notnull plans need no magnitude planes (bsicond.py
        # contract) — don't gather+upload the whole [D+2, S, W] stack
        if plan[0] == "empty":
            import jax.numpy as jnp

            return jnp.zeros((self._padded_len(tuple(shards)),
                              WORDS_PER_ROW), dtype=jnp.uint32)
        if plan[0] == "notnull":
            stack = self.rows_stack(idx, field_name, (BSI_EXISTS_BIT,),
                                    tuple(shards),
                                    view_name=field.bsi_view_name())
            return None if stack is None else stack[0]
        data = self.bsi_stack(idx, field_name, shards)
        if data is None:
            return None
        planes, sign, exists = data
        self.dispatches += 1
        return apply_bsi_condition(plan, planes, sign, exists)

    def row_chunk_size(self, shards):
        """Rows per [R, S, W] chunk under the CHUNK_BYTES budget."""
        return max(
            1, CHUNK_BYTES // (self._padded_len(shards) * WORDS_PER_ROW * 4))

    # -- compiled kernels ----------------------------------------------------

    def _get_fn(self, key, build):
        with self._lock:
            fn = self._fns.get(key)
            if fn is not None:
                self._fns.move_to_end(key)
                return fn
        fn = build()
        with self._lock:
            self._fns[key] = fn
            while len(self._fns) > MAX_FNS:
                self._fns.popitem(last=False)
        return fn

    @staticmethod
    def _tree_eval(sig, stacks):
        if sig[0] == "leaf":
            return stacks[sig[1]]
        op, subs = sig
        acc = StackedEvaluator._tree_eval(subs[0], stacks)
        for s in subs[1:]:
            p = StackedEvaluator._tree_eval(s, stacks)
            if op == "&":
                acc = acc & p
            elif op == "|":
                acc = acc | p
            elif op == "^":
                acc = acc ^ p
            else:
                acc = acc & ~p
        return acc

    def _count_fn(self, sig, arity):
        """Tree -> (hi, lo) int32 popcount totals over all shards."""
        import jax
        import jax.numpy as jnp

        def build():
            @jax.jit
            def fn(*stacks):
                acc = self._tree_eval(sig, stacks)
                per_shard = jnp.sum(
                    jax.lax.population_count(acc).astype(jnp.int32),
                    axis=-1)
                return bitplane.hi_lo(per_shard)

            return fn

        return self._get_fn(("count", sig, arity), build)

    def _plane_fn(self, sig, arity):
        """Tree -> combined [S, W] plane stack (filter materialization)."""
        import jax

        def build():
            @jax.jit
            def fn(*stacks):
                return self._tree_eval(sig, stacks)

            return fn

        return self._get_fn(("plane", sig, arity), build)

    def _row_counts_fn(self, has_filt):
        """(rows [R,S,W], filt [S,W]?) -> (hi [R], lo [R]) counts of
        rows ∩ filter over all shards."""
        import jax
        import jax.numpy as jnp

        def build():
            def counts(rows, filt):
                x = rows & filt[None] if has_filt else rows
                per_shard = jnp.sum(
                    jax.lax.population_count(x).astype(jnp.int32), axis=-1)
                return bitplane.hi_lo(per_shard, axis=-1)

            if has_filt:
                return jax.jit(lambda rows, filt: counts(rows, filt))
            return jax.jit(lambda rows: counts(rows, None))

        return self._get_fn(("row_counts", has_filt), build)

    def _sum_fn(self, has_filt):
        """(planes [D,S,W], sign, exists, filt?) -> per-plane positive and
        negative popcounts + consider count, all as (hi, lo) pairs
        (reference: fragment.sum fragment.go:1068)."""
        import jax
        import jax.numpy as jnp

        def build():
            def kernel(planes, sign, exists, filt):
                consider = exists & filt if has_filt else exists
                pos = consider & ~sign
                neg = consider & sign
                pc = jnp.sum(jax.lax.population_count(
                    planes & pos[None]).astype(jnp.int32), axis=-1)  # [D,S]
                nc = jnp.sum(jax.lax.population_count(
                    planes & neg[None]).astype(jnp.int32), axis=-1)
                cc = jnp.sum(jax.lax.population_count(
                    consider).astype(jnp.int32), axis=-1)            # [S]
                return (*bitplane.hi_lo(pc, axis=-1),
                        *bitplane.hi_lo(nc, axis=-1),
                        *bitplane.hi_lo(cc))

            if has_filt:
                return jax.jit(kernel)
            return jax.jit(
                lambda planes, sign, exists: kernel(
                    planes, sign, exists, None))

        return self._get_fn(("sum", has_filt), build)

    def _minmax_fn(self, has_filt, is_max):
        """One-dispatch global Min/Max over stacked BSI planes.

        Computes both the positive-branch and negative-branch narrowing
        walks (ops.bsi min/max_unsigned work unchanged on [D,S,W] planes
        with [S,W] filters — the scans are elementwise with global any())
        and selects per the reference's sign rules (fragment.go:1110-1227):
        Max: highest positive else closest-to-zero negative; Min: most
        negative else lowest positive. Returns (empty, use_neg, bits [D],
        cnt_hi, cnt_lo)."""
        import jax
        import jax.numpy as jnp

        from ..ops import bsi as bsi_ops

        def build():
            def kernel(planes, sign, exists, filt):
                consider = exists & filt if has_filt else exists
                pos = consider & ~sign
                neg = consider & sign
                has_pos = jnp.any(pos != 0)
                has_neg = jnp.any(neg != 0)
                empty = ~(has_pos | has_neg)
                if is_max:
                    # highest positive, else closest-to-zero negative
                    b_pos, f_pos = bsi_ops.max_unsigned(planes, pos)
                    b_neg, f_neg = bsi_ops.min_unsigned(planes, neg)
                    use_neg = ~has_pos
                else:
                    # most negative, else lowest positive
                    b_neg, f_neg = bsi_ops.max_unsigned(planes, neg)
                    b_pos, f_pos = bsi_ops.min_unsigned(planes, pos)
                    use_neg = has_neg
                bits = jnp.where(use_neg, b_neg, b_pos)
                final = jnp.where(use_neg, f_neg, f_pos)
                per_shard = jnp.sum(
                    jax.lax.population_count(final).astype(jnp.int32),
                    axis=-1)
                return (empty, use_neg, bits, *bitplane.hi_lo(per_shard))

            if has_filt:
                return jax.jit(kernel)
            return jax.jit(
                lambda planes, sign, exists: kernel(
                    planes, sign, exists, None))

        return self._get_fn(("minmax", has_filt, is_max), build)

    # -- public entry points -------------------------------------------------

    def _gather(self, idx, call, shards):
        """Shared tree-coverage + leaf-stack gather: (sig, stacks) or None
        when the tree isn't stack-coverable or a leaf's field vanished
        (concurrent DDL) — callers fall back to the per-shard path."""
        leaves = {}
        sig = self.signature(idx, call, leaves)
        if sig is None or not leaves:
            return None
        ordered = sorted(leaves.items(), key=lambda kv: kv[1])
        stacks = []
        for key, _ in ordered:
            if key[0] == "bsicond":
                stacks.append(self.bsi_condition_stack(idx, key, shards))
            else:
                _, field_name, row_id = key
                stacks.append(
                    self.leaf_stack(idx, field_name, row_id, shards))
        if any(s is None for s in stacks):
            return None
        return sig, stacks

    def try_count(self, idx, call_child, shards):
        """Count(call_child) over `shards` in one dispatch, or None when
        the tree isn't coverable (caller falls back)."""
        shards = tuple(shards)
        if len(shards) < MIN_SHARDS:
            return None
        gathered = self._gather(idx, call_child, shards)
        if gathered is None:
            return None
        sig, stacks = gathered
        self.dispatches += 1
        hi, lo = self._count_fn(sig, len(stacks))(*stacks)
        return combine_hi_lo(hi, lo)

    def filter_stack(self, idx, call, shards):
        """Materialize a bitmap call tree as one [S, W] device stack.
        Returns (covered, stack): covered=False means the tree has shapes
        the stacked path can't express (fall back to per-shard);
        stack=None with covered=True means "no filter given"."""
        if call is None:
            return True, None
        shards = tuple(shards)
        gathered = self._gather(idx, call, shards)
        if gathered is None:
            return False, None
        sig, stacks = gathered
        self.dispatches += 1
        return True, self._plane_fn(sig, len(stacks))(*stacks)

    def row_counts(self, idx, field_name, row_ids, filt, shards,
                   view_name=VIEW_STANDARD):
        """{row_id: exact count of row ∩ filt summed over shards}, in
        O(rows/chunk) dispatches independent of the shard count. `filt` is
        a [S, W] device stack from filter_stack (or None). Returns None
        when the field/view vanished mid-query."""
        shards = tuple(shards)
        out = {}
        chunk_size = self.row_chunk_size(shards)
        # Oversized candidate sets can't all stay resident: build those
        # chunks transiently instead of churning out every cached chunk.
        total_bytes = (len(row_ids) * self._padded_len(shards)
                       * WORDS_PER_ROW * 4)
        cache = total_bytes <= MAX_ROWS_STACK_BYTES
        fn = self._row_counts_fn(filt is not None)
        pending = []
        import jax

        for i in range(0, len(row_ids), chunk_size):
            chunk = tuple(row_ids[i:i + chunk_size])
            stack = self.rows_stack(idx, field_name, chunk, shards,
                                    view_name, cache=cache)
            if stack is None:
                return None
            self.dispatches += 1
            hi_lo = fn(stack, filt) if filt is not None else fn(stack)
            if not cache:
                # Transient chunks: block before building the next one so
                # peak HBM stays ~CHUNK_BYTES instead of the whole
                # candidate set queued in flight.
                jax.block_until_ready(hi_lo)
            pending.append((chunk, hi_lo))
        for chunk, (hi, lo) in pending:
            totals = combine_hi_lo(hi, lo)
            for j, row_id in enumerate(chunk):
                out[row_id] = int(totals[j])
        return out

    def try_sum(self, idx, field, filter_call, shards):
        """(signed magnitude total, count) for Sum over stacked BSI planes,
        or None to fall back. The caller adds base*count (field.go:1583)."""
        shards = tuple(shards)
        if len(shards) < MIN_SHARDS:
            return None
        covered, filt = self.filter_stack(idx, filter_call, shards)
        if not covered:
            return None
        data = self.bsi_stack(idx, field.name, shards)
        if data is None:
            return None
        planes, sign, exists = data
        fn = self._sum_fn(filt is not None)
        self.dispatches += 1
        if filt is not None:
            res = fn(planes, sign, exists, filt)
        else:
            res = fn(planes, sign, exists)
        p_hi, p_lo, n_hi, n_lo, c_hi, c_lo = [np.asarray(r) for r in res]
        pos = combine_hi_lo(p_hi, p_lo)
        neg = combine_hi_lo(n_hi, n_lo)
        total = 0
        for i in range(planes.shape[0]):
            total += (int(pos[i]) - int(neg[i])) << i
        return total, combine_hi_lo(c_hi, c_lo)

    def try_minmax(self, idx, field, filter_call, shards, is_max):
        """(signed magnitude, count) of the Min/Max value over stacked BSI
        planes, or None to fall back; (None, 0) when no column qualifies.
        The caller adds base (reference: fragment.go:1110-1227)."""
        shards = tuple(shards)
        if len(shards) < MIN_SHARDS:
            return None
        covered, filt = self.filter_stack(idx, filter_call, shards)
        if not covered:
            return None
        data = self.bsi_stack(idx, field.name, shards)
        if data is None:
            return None
        planes, sign, exists = data
        fn = self._minmax_fn(filt is not None, is_max)
        self.dispatches += 1
        if filt is not None:
            empty, use_neg, bits, c_hi, c_lo = fn(planes, sign, exists, filt)
        else:
            empty, use_neg, bits, c_hi, c_lo = fn(planes, sign, exists)
        if bool(empty):
            return None, 0
        bits = np.asarray(bits)
        mag = sum(int(b) << i for i, b in enumerate(bits))
        if bool(use_neg):
            mag = -mag
        return mag, combine_hi_lo(c_hi, c_lo)

    def cache_stats(self):
        """Snapshot for /debug/vars: hit rate and byte pressure reveal
        whether the HBM budgets (MAX_STACK_BYTES / MAX_ROWS_STACK_BYTES)
        are thrashing under the live workload."""
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "patches": self.patches,
                "planes_uploaded": self.planes_uploaded,
                "dispatches": self.dispatches,
                "stack_bytes": self._stack_bytes,
                "stack_entries": len(self._stacks),
                "rows_stack_bytes": self._rows_stack_bytes,
                "rows_stack_entries": len(self._rows_stacks),
            }

    def invalidate(self):
        with self._lock:
            self._stacks.clear()
            self._stack_bytes = 0
            self._rows_stacks.clear()
            self._rows_stack_bytes = 0


# Backwards-compatible name (the evaluator originally covered Count only).
StackedCountEvaluator = StackedEvaluator
