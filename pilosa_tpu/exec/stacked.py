"""Stacked serving fast paths: whole-index evaluation in O(1) dispatches.

The general executor evaluates call trees shard by shard — correct for
every call, but each shard costs device dispatches. For the serving-critical
calls — Count, TopN, Sum, Min, Max, GroupBy (executor.go:930,1790,331,1098)
— this module evaluates ALL shards in a constant number of fused XLA
dispatches: fragment rows become [shards, words] stacked planes resident on
device, call trees become jitted elementwise+popcount+reduce programs, and
per-query work is a handful of dispatches and ONE host sync, independent of
the shard count.

Stacks are cached per (kind, index, field, rows, shard-set) and invalidated
by the fragments' write-generation counters (fragment.generation — bumped by
every mutation), so a stale stack can never serve a query. LRU-bounded: at
SHARD_WIDTH=2^20 a 954-shard stack is ~120 MB of HBM, so only the hottest
rows stay resident (the device analog of fragment.rowCache fragment.go:367).

On a multi-device host the stacks are placed sharded over a 1-D "shards"
mesh (zero-padded to a device multiple — zero rows are count-neutral for
every supported op), so the SAME jitted programs are GSPMD partitioned by
XLA: per-device popcounts reduce over ICI instead of one chip doing all the
work (SURVEY §2 parallelism: the shard axis is the one SPMD axis).

Overflow discipline: per-(row,shard) popcounts fit int32 (≤ 2^20), but
totals over shards can exceed 2^31 (a >2048-shard index). TPUs run JAX with
x64 disabled, so instead of int64 accumulators every cross-shard reduce
returns a (hi, lo) int32 pair — hi = Σ(count >> 16), lo = Σ(count & 0xffff)
— combined on host as exact Python ints. Safe to 2^15 shards (32768 shards
≈ 34 trillion columns per node).
"""

import contextlib
import threading
import time
from collections import OrderedDict

import numpy as np

from ..utils import flightrec as _flightrec
from ..utils import profile as _profile
from ..utils import tracing as _tracing
from ..utils import workload as _workload
from ..utils.stats import global_stats
from . import adaptive as _adaptive
from . import ingest as _ingest


class GroupCommit:
    """Group-commit batching for the serving path (cross-query batching,
    VERDICT r4 item 5 productionizing bench.py's batching trick).

    Per-query device work is already async — XLA queues each fused
    program without blocking — but resolving a result costs one full
    dispatch round trip, and over a remote-device tunnel that RTT (~66 ms
    measured, BENCH r3) dwarfs device compute (~0.34 ms/query). Serving
    threads therefore amortize: the first thread to arrive becomes the
    LEADER and drains everything queued, processing the WHOLE batch with
    one `process` call (one device_get — or one fused multi-query program
    + one device_get); threads that arrive while the leader works queue
    up for the next leader. Leadership transfers by the emptiness rule:
    whoever appends to an EMPTY queue leads. Zero added latency for a
    lone query (its leader drains immediately); under concurrency, batch
    size grows to the natural arrival rate — classic group commit.

    A leader failure (compile error, device OOM, tunnel loss) propagates
    to EVERY waiter in its batch — events always fire, so no HTTP thread
    can hang on a dead leader."""

    #: a batch slower than this is RTT-dominated (remote-device tunnel);
    #: batching windows only engage then
    RTT_DOMINATED_S = 0.02
    #: leader pause before draining on RTT-dominated transports — lets
    #: concurrent queries pile into the batch; small vs the ~66 ms RTT it
    #: amortizes, and NEVER applied on fast local transports
    WINDOW_S = 0.005

    def __init__(self):
        self._lock = threading.Lock()
        self._queue = []
        self._window_s = 0.0  # adaptive: engages once batches measure slow
        # Minimum observed batch latency ~ the transport's round-trip
        # floor: a large batch is slow everywhere, but only a transport
        # whose FASTEST batch is still slow is RTT-dominated. Keying the
        # window on the min keeps it off local devices even under bursts.
        self._min_elapsed_s = float("inf")
        # observability: batches/batched expose the achieved batching
        # factor (batched/batches ≈ queries per round trip)
        self.batches = 0
        self.batched = 0

    def submit(self, payload, process):
        """Enqueue `payload`; the batch leader calls
        `process([payloads...]) -> [results...]` once for everything it
        drained. Returns this payload's result; re-raises the leader's
        exception if its batch failed."""
        import time as _time

        entry = [payload, None, None, threading.Event()]
        with self._lock:
            self._queue.append(entry)
            leader = len(self._queue) == 1
        if not leader:
            entry[3].wait()
            if entry[2] is not None:
                raise entry[2]
            return entry[1]
        if self._window_s > 0.0:
            _time.sleep(self._window_s)
        with self._lock:
            batch = self._queue
            self._queue = []
        try:
            t0 = _time.perf_counter()
            results = process([e[0] for e in batch])
            elapsed = _time.perf_counter() - t0
            # adapt: on an RTT-dominated transport a small leader pause
            # turns the round trip into a shared cost; on a local device
            # it would only add latency, so keep it off there
            self._min_elapsed_s = min(self._min_elapsed_s, elapsed)
            self._window_s = self.WINDOW_S \
                if self._min_elapsed_s > self.RTT_DOMINATED_S else 0.0
            self.batches += 1
            self.batched += len(batch)
            for e, r in zip(batch, results):
                e[1] = r
        except BaseException as exc:
            for e in batch:
                e[2] = exc
            raise
        finally:
            for e in batch:
                if e is not entry:
                    e[3].set()
        return entry[1]


#: serializes every multi-device launch in this process (see
#: StackedEvaluator.__init__ for the rendezvous-starvation rationale)
_DISPATCH_LOCK = threading.Lock()


class DeadlineExceededError(Exception):
    """The request's deadline lapsed mid-query — raised at the dispatch
    boundary BEFORE the device launch, so expired work never holds the
    dispatch lock. Defined here (not exec/executor.py) so the per-
    dispatch check needs no circular import; server/api.py maps it to
    504."""


_deadline_tls = threading.local()


def set_thread_deadline(at):
    """Arm (or with None, clear) this thread's request deadline — an
    absolute time.monotonic() instant. Checked by _locked_dispatch
    before each lock acquisition; the executor sets it around each
    query's call loop."""
    _deadline_tls.at = at


def _check_thread_deadline():
    at = getattr(_deadline_tls, "at", None)
    if at is not None and time.monotonic() >= at:
        raise DeadlineExceededError(
            "request deadline expired before dispatch")

_SERIAL_EXECUTION = None


def _serial_execution():
    """True when multi-device programs must be held to COMPLETION (not
    just enqueued) one at a time. The CPU backend runs the per-device
    executions of a GSPMD program on a shared thread pool, and the
    in-program cross-shard reduces rendezvous across them — two programs
    in flight can each hold part of the pool at their rendezvous and
    starve each other permanently (observed wedging concurrent serving
    threads on the 8-virtual-device test mesh). Accelerator backends
    execute streams FIFO per device, so enqueue order alone already
    prevents interleaving and overlap stays safe (and async)."""
    global _SERIAL_EXECUTION
    if _SERIAL_EXECUTION is None:
        import jax

        _SERIAL_EXECUTION = jax.default_backend() == "cpu"
    return _SERIAL_EXECUTION


def _launch_barrier(out):
    """Block the locked dispatch until `out` is resident when the
    backend requires serial execution (see _serial_execution)."""
    if _serial_execution():
        import jax

        jax.block_until_ready(out)
    return out


#: dispatch-phase taxonomy (GET /debug/dispatch): lock_wait is measured
#: by _locked_dispatch itself; the others are marked by the dispatch
#: sites between the operations they time. transfer_in exists for sites
#: that explicitly stage host data under the lock — on the current
#: paths dense plane uploads happen on the upload path OUTSIDE the
#: dispatch lock (attributed via planes_uploaded / hbm ledger), so the
#: phase is normally absent. dispatch_ack is relabeled "compile" on a
#: program's first call (detected via the kernel arg-spec cache) because
#: trace+compile dominates that call's fn() wall.
DISPATCH_PHASES = ("lock_wait", "transfer_in", "compile", "dispatch_ack",
                   "sync")


class _PhaseClock:
    """Phase marks within one locked dispatch. `mark(phase)` attributes
    the time since the previous mark (or lock acquisition) to `phase`;
    _locked_dispatch folds any residual into the last mark on exit so
    the per-phase seconds sum EXACTLY to the dispatch wall (the
    bench_suite devhealth leg asserts the 5% version of this)."""

    __slots__ = ("_t", "compiling", "phases")

    def __init__(self, t1, compiling=False):
        self._t = t1
        self.compiling = compiling
        self.phases = []

    def mark(self, phase):
        now = time.perf_counter()
        if phase == "dispatch_ack" and self.compiling:
            phase = "compile"
        self.phases.append([phase, now - self._t])
        self._t = now


def _device_get_batch(payloads):
    """GroupCommit `process` for plain result fetches: payloads are
    tuples of device values; ONE device_get resolves them all."""
    import jax

    flat = [a for arrays in payloads for a in arrays]
    vals = jax.device_get(flat)
    out = []
    i = 0
    for arrays in payloads:
        out.append(vals[i:i + len(arrays)])
        i += len(arrays)
    return out

from ..core.fragment import BSI_EXISTS_BIT, BSI_OFFSET_BIT, BSI_SIGN_BIT
from ..core.index import EXISTENCE_FIELD_NAME
from ..core.view import VIEW_STANDARD
from ..shardwidth import WORDS_PER_ROW

# Device-byte budget for cached stacks; excess evicts least-recently-used.
# (Entry size scales with shard count — ~120 MB per 954-shard stack — so a
# count bound alone could pin several GB of HBM.)
MAX_STACK_BYTES = 512 * 1024 * 1024
# Separate budget for TopN/GroupBy row-chunk stacks ([rows, shards, words]
# keyed by the exact candidate tuple): they are large and churn with any
# candidate-set change, so they must not be able to evict the long-lived
# leaf/BSI stacks the Count/Sum serving paths depend on.
MAX_ROWS_STACK_BYTES = 256 * 1024 * 1024
# Compiled tree programs are tiny but unbounded shapes would accumulate.
MAX_FNS = 128
# Below this many shards the per-shard path's dispatch count is too small
# to matter.
MIN_SHARDS = 2
# Transient row-chunk stacks ([rows, shards, words]) are built at most this
# large, so TopN/GroupBy dispatch count is O(rows/chunk) — independent of
# the shard count.
CHUNK_BYTES = 128 * 1024 * 1024
# Time-range leaves union one cached stack per quantum view in the range
# cover; wider covers (a years-long hourly span) use the per-shard path.
MAX_TIME_VIEWS = 64

_OPS = {"Intersect": "&", "Union": "|", "Difference": "-", "Xor": "^"}

#: vmapped-batch padding buckets: a coalesced batch is padded up to the
#: next bucket (repeating query 0) so at most len(BATCH_BUCKETS) programs
#: compile per (kind, signature) while any concurrency level still fuses
#: into one dispatch. 64 caps per-dispatch device time near the tunnel
#: RTT it amortizes (same reasoning as MAX_COUNT_BATCH).
BATCH_BUCKETS = (1, 4, 16, 64)


def batch_bucket(n):
    """Smallest padding bucket holding `n` queries."""
    for b in BATCH_BUCKETS:
        if n <= b:
            return b
    return BATCH_BUCKETS[-1]


#: process-wide dispatch-phase aggregate, folded by _note_phases in
#: lockstep with each evaluator's own table: the bare flightrec debug
#: server (bench children run no PilosaHTTPServer) serves it at
#: GET /debug/dispatch without a handle on any evaluator, so a killed
#: bench attempt still carries which phase its dispatches wedged in.
_GLOBAL_PHASES = {}
_GLOBAL_PHASES_LOCK = threading.Lock()


def global_dispatch_phases():
    """{kernel: {phase: {count, seconds}}} across every evaluator in the
    process (utils/flightrec._DebugHandler, bench.py kill-path fetch)."""
    with _GLOBAL_PHASES_LOCK:
        return {k: {p: dict(v) for p, v in fam.items()}
                for k, fam in _GLOBAL_PHASES.items()}


def reset_global_dispatch_phases():
    """Pristine module aggregate (tests)."""
    with _GLOBAL_PHASES_LOCK:
        _GLOBAL_PHASES.clear()


#: thread-local batch attribution: the batch paths stamp how many
#: queries shared the thread's last fused dispatch, the executor reads
#: it back for strategy notes / SLOW QUERY `batch=` attribution.
_BATCH_TLS = threading.local()


def note_batch_size(n):
    """Record the fused-batch size the current thread's query rode
    (0 resets; 1 = solo dispatch)."""
    _BATCH_TLS.size = int(n)


def last_batch_size():
    """Fused-batch size stamped by the last batched dispatch on THIS
    thread (0 when the thread never rode one)."""
    return getattr(_BATCH_TLS, "size", 0)


_UNSET = object()

from ..ops import bitplane  # noqa: E402
from ..ops import containers as _containers  # noqa: E402
from ..ops.bitplane import combine_hi_lo  # noqa: E402  (canonical helper)


def time_range_views(idx, field_name, args):
    """Quantum-view name cover for a time-range Row, or None when the
    field isn't a time field / has no quantum. Pure function of the
    REPLICATED schema + call args (both stacked and SPMD leaves use it;
    semantics identical to the executor's per-shard _row_shard)."""
    from ..core import timeq
    from ..core.field import FIELD_TYPE_TIME

    field = idx.field(field_name)
    if field is None or field.type != FIELD_TYPE_TIME:
        return None
    quantum = field.time_quantum()
    if not quantum:
        return None
    try:
        from_t = timeq.parse_time(args["from"]) if "from" in args \
            else timeq.parse_time("1970-01-01T00:00")
        to_t = timeq.parse_time(args["to"]) if "to" in args \
            else timeq.parse_time("2100-01-01T00:00")
    except Exception:
        return None  # malformed timestamps: per-shard path raises cleanly
    views = tuple(timeq.views_by_time_range(
        VIEW_STANDARD, from_t, to_t, quantum))
    if len(views) > MAX_TIME_VIEWS:
        return None  # a huge hourly span: per-shard path handles it
    return views


def intern_time_leaf(idx, field_name, row_id, args, leaves):
    '''THE ("timerow", field, row, views) leaf interner, shared by the
    stacked and SPMD signature walks so the leaf key shape lives in one
    place (both sides consult only replicated schema).'''
    views = time_range_views(idx, field_name, args)
    if views is None:
        return None
    key = ("timerow", field_name, int(row_id), views)
    if key not in leaves:
        leaves[key] = len(leaves)
    return ("leaf", leaves[key])


def tree_signature(idx, call, leaves, leaf, bsi_leaf=None, time_leaf=None):
    """THE coverage walk for stacked/SPMD fast paths: turns a bitmap call
    tree into an operator signature over leaf slots, or None when any
    shape isn't expressible (Shift, keys, ...).
    `leaf(idx, field_name, row_id, leaves)` decides row-leaf eligibility —
    the stacked evaluator requires a local standard view; the SPMD plane
    checks replicated schema only (cluster/spmd.py).
    `bsi_leaf(idx, field_name, cond, leaves)` (optional) covers BSI
    condition leaves like Row(v > 10) the same way (reference algorithm:
    fragment.go:1357-1470); None declines conditions entirely.
    `time_leaf(idx, field_name, row_id, args, leaves)` (optional) covers
    time-range rows Row(t=1, from=..., to=...) as a union over the
    quantum-view cover (reference: viewsByTimeRange time.go:91); None
    declines time ranges entirely."""
    name = call.name
    if name in ("Row", "Range"):
        if "from" in call.args or "to" in call.args:
            if time_leaf is None or call.has_conditions():
                return None
            field_name = call.field_arg()
            if field_name is None:
                return None
            row_id = call.args.get(field_name)
            if isinstance(row_id, bool):
                row_id = int(row_id)
            if not isinstance(row_id, int):
                return None
            return time_leaf(idx, field_name, row_id, call.args, leaves)
        if call.has_conditions():
            if bsi_leaf is None or len(call.args) != 1:
                return None
            from ..pql import Condition

            field_name, cond = next(iter(call.args.items()))
            if not isinstance(cond, Condition):
                return None
            return bsi_leaf(idx, field_name, cond, leaves)
        field_name = call.field_arg()
        if field_name is None:
            return None
        row_id = call.args.get(field_name)
        if isinstance(row_id, bool):
            row_id = int(row_id)
        if not isinstance(row_id, int):
            return None
        return leaf(idx, field_name, row_id, leaves)
    if name in _OPS and call.children:
        subs = tuple(
            tree_signature(idx, c, leaves, leaf, bsi_leaf, time_leaf)
            for c in call.children)
        if any(s is None for s in subs):
            return None
        return (_OPS[name], subs)
    if name == "Not" and len(call.children) == 1 \
            and idx.options.track_existence \
            and idx.field(EXISTENCE_FIELD_NAME) is not None:
        child = tree_signature(idx, call.children[0], leaves, leaf,
                               bsi_leaf, time_leaf)
        if child is None:
            return None
        exists = leaf(idx, EXISTENCE_FIELD_NAME, 0, leaves)
        if exists is None:
            return None
        return ("-", (exists, child))
    return None


def tree_eval(sig, stacks):
    """THE traced operator-tree evaluator over aligned leaf stacks —
    module-level entry so the SPMD collective programs (cluster/spmd.py)
    share the exact expression semantics of the local serving kernels
    instead of reaching into StackedEvaluator internals."""
    return StackedEvaluator._tree_eval(sig, stacks)


class StackedEvaluator:
    def __init__(self):
        self._stacks = OrderedDict()  # key -> (gens, device arrays, nbytes)
        self._stack_bytes = 0
        self._rows_stacks = OrderedDict()  # row-chunk pool (own budget)
        self._rows_stack_bytes = 0
        self._fns = OrderedDict()     # kernel signature -> jitted fn
        # Cross-query batching (GroupCommit): result-fetch amortization
        # for Sum, and full dispatch batching for Count (queued queries
        # fuse into ONE program per signature bucket + ONE fetch).
        self._fetch_commit = GroupCommit()
        self._count_commit = GroupCommit()
        self._lock = threading.Lock()
        # Multi-device dispatches must not interleave: stacks are
        # mesh-sharded, so every serving program is a GSPMD launch across
        # all local devices whose cross-shard reduces rendezvous between
        # the per-device executions. Concurrent serving threads wedge
        # that rendezvous on backends without per-device FIFO streams,
        # so each launch holds this lock — for the enqueue everywhere,
        # and through completion where _serial_execution() says overlap
        # is unsafe (the CPU thread-pool backend). Result fetches stay
        # outside it. The lock is PROCESS-wide, not per-evaluator: the
        # devices are a process-level resource, and the in-process
        # cluster harness runs several evaluators over the same mesh.
        self._dispatch_lock = _DISPATCH_LOCK
        self._sharding = _UNSET
        # Kernel-dispatch counter: tests assert serving dispatch counts are
        # independent of the shard count.
        self.dispatches = 0
        # Cache observability (exported at /debug/vars "stacked"): without
        # these, budget thrash (VERDICT r2) is invisible in production.
        self.hits = 0
        self.misses = 0
        self.evictions = 0
        # Per-pool eviction counters tagged by cause ("budget" LRU
        # pressure vs "invalidate" full flushes), mirrored to /metrics as
        # stacked_evictions_total{pool,cause}; the untagged total above
        # stays for back-compat with older dashboards.
        self.pool_evictions = {}
        # HBM ledger: resident stack-cache bytes attributed per
        # (index, field, pool), maintained exactly in lockstep with
        # _stack_bytes/_rows_stack_bytes by _cache_put/invalidate and
        # exported as hbm_stack_bytes{index,field,pool} gauges +
        # GET /debug/hbm. Answers "what is resident in HBM and for whom".
        self._hbm_ledger = {}
        # Per-kernel attribution: kind -> {count, seconds, bytes_in,
        # bytes_out} fed by _locked_dispatch; arg shape specs captured on
        # each compiled fn's first call so /debug/kernels can compute
        # jax cost_analysis() lazily (never on the serving path).
        self._kernels = {}
        self._fn_specs = {}
        self._kernel_costs = {}
        # Dispatch-phase decomposition: kind -> {phase: {count, seconds}}
        # fed by _locked_dispatch's phase clock (GET /debug/dispatch) —
        # splits the per-dispatch RTT into lock_wait / transfer_in /
        # compile / dispatch_ack / sync so "65ms RTT" is attributable.
        self._dispatch_phases = {}
        # Incremental-maintenance observability: a patch re-uploads only
        # the drifted shards' planes instead of the whole stack; tests
        # assert planes_uploaded stays O(changed shards) under writes.
        self.patches = 0
        self.planes_uploaded = 0
        # Streaming-ingest observability: reads served from a stale
        # stack whose drift is fully covered by pending ingest deltas
        # (the merge folds them off the read path; exec/ingest.py).
        self.stale_serves = 0
        # Pairwise GroupBy observability: dispatches and host syncs must
        # stay O(⌈R1/tile⌉·⌈R2/tile⌉) for a two-field cross product —
        # tests assert these, not wall time (which is noisy on CPU).
        self.pairwise_dispatches = 0
        self.pairwise_syncs = 0
        # Batched-pipeline observability (GET /debug/batching): fused
        # launch_query_batch dispatches vs the queries that rode them.
        self.batch_dispatches = 0
        self.batched_queries = 0
        # Whole-plan fusion observability (GET /debug/fusion): queries
        # whose every top-level Count rode ONE fused device program.
        self.fused_dispatches = 0

    def _stack_sharding(self):
        """NamedSharding over all local devices (None on a single device),
        resolved lazily so importing this module never touches the
        backend."""
        if self._sharding is _UNSET:
            import jax

            # local_devices: host-local numpy stacks can't be placed onto
            # other processes' chips; cross-host scale-out is the cluster
            # layer's job (shards_by_node), not this cache's.
            devices = jax.local_devices()
            if len(devices) < 2:
                self._sharding = None
            else:
                mesh = jax.sharding.Mesh(np.array(devices), ("shards",))
                self._sharding = jax.sharding.NamedSharding(
                    mesh, jax.sharding.PartitionSpec("shards"))
        return self._sharding

    def _n_pad_devices(self):
        sharding = self._stack_sharding()
        return 1 if sharding is None else len(sharding.device_set)

    def _padded_len(self, shards):
        """Shard-axis length zero-padded to a device multiple. Load-bearing
        agreement: filter [S_pad, W] and rows [R, S_pad, W] stacks must use
        the SAME padding or their elementwise combine misaligns."""
        n_dev = self._n_pad_devices()
        return ((len(shards) + n_dev - 1) // n_dev) * n_dev

    def _place(self, host_stack, shard_axis):
        """Upload a host stack, sharded over the device mesh along
        `shard_axis` (already zero-padded by the caller)."""
        import jax

        sharding = self._stack_sharding()
        if sharding is None:
            return jax.device_put(host_stack)
        spec = [None] * host_stack.ndim
        spec[shard_axis] = "shards"
        return jax.device_put(host_stack, jax.sharding.NamedSharding(
            sharding.mesh, jax.sharding.PartitionSpec(*spec)))

    def _place_replicated(self, host_array):
        """Upload a compressed container component replicated across the
        mesh: compressed arrays have no shard axis to partition, and an
        explicitly replicated operand keeps the serving program a valid
        GSPMD launch next to mesh-sharded dense stacks (XLA reshards as
        needed). On a single device this is a plain device_put."""
        import jax

        sharding = self._stack_sharding()
        if sharding is None:
            return jax.device_put(host_array)
        return jax.device_put(host_array, jax.sharding.NamedSharding(
            sharding.mesh, jax.sharding.PartitionSpec()))

    # -- tree analysis -------------------------------------------------------

    def _leaf(self, idx, field_name, row_id, leaves):
        field = idx.field(field_name)
        if field is None or field.view(VIEW_STANDARD) is None:
            return None
        # tagged key: a field literally named "bsicond" must not collide
        # with condition-leaf keys in the shared leaves dict
        key = ("row", field_name, int(row_id))
        if key not in leaves:
            leaves[key] = len(leaves)
        return ("leaf", leaves[key])

    def _bsi_leaf(self, idx, field_name, cond, leaves):
        """Condition-leaf eligibility: an int field with a local BSI view
        and a normalizable condition. The leaf key carries (op, values) so
        identical conditions share one slot."""
        from .bsicond import normalize_bsi_condition

        field = idx.field(field_name)
        if field is None or field.options.type != "int" \
                or field.view(field.bsi_view_name()) is None:
            return None
        norm = normalize_bsi_condition(cond)
        if norm is None:
            return None
        op, vals = norm
        key = ("bsicond", field_name, op, vals)
        if key not in leaves:
            leaves[key] = len(leaves)
        return ("leaf", leaves[key])

    def signature(self, idx, call, leaves):
        """Tree signature with leaf slots, or None when the tree has any
        shape the fast path doesn't cover (Shift, keys...). None means:
        use the general per-shard path."""
        return tree_signature(idx, call, leaves, self._leaf, self._bsi_leaf,
                              intern_time_leaf)

    # -- stack cache ---------------------------------------------------------

    def _fragment_gens(self, idx, field_name, shards,
                       view_name=VIEW_STANDARD, view=None):
        """Cache-validation fingerprint: per-shard (fragment uid,
        generation). The uid makes a recreated fragment (field dropped and
        re-made at the same path) distinct from its predecessor even when
        the generation counters collide. None when the field vanished
        (concurrent DDL) — caller falls back to the general path. Callers
        that already resolved the view pass it to skip the double
        field/view lookup on the serving path."""
        if view is None:
            field = idx.field(field_name)
            view = field.view(view_name) if field is not None else None
            if view is None:
                return None
        gens = []
        for shard in shards:
            frag = view.fragment(shard)
            gens.append((-1, -1) if frag is None
                        else (frag.uid, frag.generation))
        return tuple(gens)

    def _pool(self, key):
        """Row-chunk stacks live in their own LRU pool (see
        MAX_ROWS_STACK_BYTES)."""
        if key[0] == "rows":
            return self._rows_stacks, MAX_ROWS_STACK_BYTES
        return self._stacks, MAX_STACK_BYTES

    @staticmethod
    def _heat_key(key):
        """(index, field, view) for the fragment heat ledger. Leaf and
        rows stacks cache the standard view (rows keys carry the actual
        view name at key[3] — time-quantum views differ); BSI stacks
        cache the field's BSI bit planes."""
        if key[0] == "rows":
            return key[1], key[2], key[3]
        if key[0] == "bsi":
            return key[1], key[2], "bsi"
        return key[1], key[2], VIEW_STANDARD

    def _cache_get_fast(self, key, stamp):
        """O(1) hit check via the view-level (uid, mutations) stamp — the
        first level of the two-level fingerprint. A stamp match proves no
        fragment in the view changed since the entry was stored, so the
        per-shard generation walk (954 iterations at 1B columns — the
        dominant per-query Python cost) is skipped entirely on the hot
        serving path."""
        pool, _ = self._pool(key)
        with self._lock:
            hit = pool.get(key)
            if hit is not None and hit[3] == stamp:
                pool.move_to_end(key)
                hit[4] = time.time()  # last-hit age for /debug/hbm
                self.hits += 1
                hit = hit[1]
            else:
                hit = None
        if hit is not None:
            # heat rides every probe that RESOLVED here (outside the
            # evaluator lock: the ledger has its own)
            _workload.heat_bump(*self._heat_key(key))
        return hit

    def _cache_get(self, key, gens, stamp=None):
        """Second-level check: exact per-shard generations. On a hit the
        entry's stamp refreshes — a mutation elsewhere in the view (e.g.
        a new fragment outside this stack's shard set) bumps the counter
        without changing these gens, and without the refresh every later
        query would pay the slow walk again."""
        pool, _ = self._pool(key)
        with self._lock:
            hit = pool.get(key)
            if hit is not None and hit[0] == gens:
                pool.move_to_end(key)
                if stamp is not None:
                    hit[3] = stamp
                hit[4] = time.time()
                self.hits += 1
                hit = hit[1]
            else:
                self.misses += 1
                hit = None
        # misses bump too: demand for an absent fragment is precisely
        # what makes it an admission candidate in /debug/heat
        _workload.heat_bump(*self._heat_key(key))
        return hit

    def _ledger_key(self, key, repr_kind):
        """Every cache key carries (kind, index, field, ...) at positions
        0-2; the ledger attributes bytes per (index, field, pool, repr) —
        the repr dimension is what makes /debug/hbm answer "how much of
        the residency is compressed" (rows/BSI pools are always dense)."""
        pool_name = "rows" if key[0] == "rows" else "stack"
        return (key[1], key[2], pool_name, repr_kind)

    def _ledger_add(self, key, delta, repr_kind="dense"):
        """Move the HBM ledger in lockstep with the pool byte counters
        (caller holds self._lock). Gauges update here too: puts/evicts
        are cache-fill events, not per-query hot path."""
        lkey = self._ledger_key(key, repr_kind)
        new = self._hbm_ledger.get(lkey, 0) + delta
        if new <= 0:
            self._hbm_ledger.pop(lkey, None)
            new = 0
        else:
            self._hbm_ledger[lkey] = new
        index, field, pool_name, repr_kind = lkey
        global_stats.gauge("hbm_stack_bytes", new, {
            "index": index, "field": field, "pool": pool_name,
            "repr": repr_kind})

    def _count_eviction(self, pool_name, cause, n=1):
        """Per-pool, cause-tagged eviction counters (caller holds
        self._lock); exported as stacked_evictions_total{pool,cause}."""
        k = (pool_name, cause)
        self.pool_evictions[k] = self.pool_evictions.get(k, 0) + n
        global_stats.count("stacked_evictions", n,
                           {"pool": pool_name, "cause": cause})

    def _pop_victim(self, pool):
        """One over-budget victim (caller holds self._lock). Legacy LRU
        (FIFO position) when the adaptive engine is off; lowest
        heat×cost benefit score when on — which may be the entry just
        inserted, making the score an admission filter too; shadow
        scores, counts the divergence, and still evicts LRU. Heat reads
        are decayed point lookups in the workload ledger (its own lock —
        the ledger never calls back into this module, so the ordering is
        one-way)."""
        amode = _adaptive.cache_mode()
        lru_key = next(iter(pool))
        if amode == "off":
            ekey = lru_key
        else:
            heat = _workload.heat()
            best = _adaptive.select_victim(
                [(k, heat.value(*self._heat_key(k)), e[2])
                 for k, e in pool.items()])
            if amode == "on":
                ekey = best
                _adaptive.note_eviction("benefit")
            else:
                ekey = lru_key
                _adaptive.note_eviction("lru", diverged=best != lru_key)
        return ekey, pool.pop(ekey)

    def _cache_put(self, key, gens, arrays, nbytes, stamp=None):
        pool, budget = self._pool(key)
        rows = pool is self._rows_stacks
        pool_name = "rows" if rows else "stack"
        repr_kind = _containers.kind_of(arrays)
        evicted_keys = []
        with self._lock:
            old = pool.pop(key, None)
            if old is not None:
                if rows:
                    self._rows_stack_bytes -= old[2]
                else:
                    self._stack_bytes -= old[2]
                self._ledger_add(key, -old[2],
                                 _containers.kind_of(old[1]))
            pool[key] = [gens, arrays, nbytes, stamp, time.time()]
            self._ledger_add(key, nbytes, repr_kind)
            if rows:
                self._rows_stack_bytes += nbytes
                while self._rows_stack_bytes > budget and len(pool) > 1:
                    ekey, evicted = self._pop_victim(pool)
                    self._rows_stack_bytes -= evicted[2]
                    self.evictions += 1
                    self._ledger_add(ekey, -evicted[2],
                                     _containers.kind_of(evicted[1]))
                    self._count_eviction(pool_name, "budget")
                    evicted_keys.append((ekey, evicted[2]))
            else:
                self._stack_bytes += nbytes
                while self._stack_bytes > budget and len(pool) > 1:
                    ekey, evicted = self._pop_victim(pool)
                    self._stack_bytes -= evicted[2]
                    self.evictions += 1
                    self._ledger_add(ekey, -evicted[2],
                                     _containers.kind_of(evicted[1]))
                    self._count_eviction(pool_name, "budget")
                    evicted_keys.append((ekey, evicted[2]))
        _flightrec.record("cache.put", pool=pool_name, index=key[1],
                          field=key[2], bytes=nbytes, repr=repr_kind)
        for ekey, ebytes in evicted_keys:
            _flightrec.record("cache.evict", pool=pool_name, index=ekey[1],
                              field=ekey[2], bytes=ebytes, cause="budget")

    def merge_swap(self, key, old_entry, gens, arrays, nbytes):
        """Install an ingest merge's result over the exact entry it was
        planned from (identity compare — a concurrent rebuild or
        eviction wins and the merge result is dropped). The entry
        updates IN PLACE under the lock; the stamp resets to None so the
        next read revalidates with one gens walk instead of trusting a
        view-stamp that predates the merge. Returns True on install."""
        pool, _ = self._pool(key)
        rows_pool = pool is self._rows_stacks
        with self._lock:
            cur = pool.get(key)
            if cur is not old_entry:
                return False
            old_bytes = cur[2]
            old_kind = _containers.kind_of(cur[1])
            cur[0] = gens
            cur[1] = arrays
            cur[2] = nbytes
            cur[3] = None
            cur[4] = time.time()
            if rows_pool:
                self._rows_stack_bytes += nbytes - old_bytes
            else:
                self._stack_bytes += nbytes - old_bytes
            self._ledger_add(key, -old_bytes, old_kind)
            self._ledger_add(key, nbytes, _containers.kind_of(arrays))
        self._note_patch("merge")
        return True

    def merge_drop(self, key, old_entry):
        """Evict an entry the ingest merge decided not to fold (too
        drifted, vanished field): the next read rebuilds cold. Identity
        compare like merge_swap. Returns True when dropped."""
        pool, _ = self._pool(key)
        rows_pool = pool is self._rows_stacks
        with self._lock:
            cur = pool.get(key)
            if cur is not old_entry:
                return False
            pool.pop(key)
            if rows_pool:
                self._rows_stack_bytes -= cur[2]
            else:
                self._stack_bytes -= cur[2]
            self.evictions += 1
            self._ledger_add(key, -cur[2], _containers.kind_of(cur[1]))
            self._count_eviction("rows" if rows_pool else "stack",
                                 "ingest")
        return True

    def leaf_stack(self, idx, field_name, row_id, shards):
        """Cached Container of one row's [S, W] plane stack over
        `shards` — the per-fragment representation chooser's call site:
        a cold build analyzes the host stack's measured density and
        picks dense / block-sparse / run-length per the configured
        --container-repr mode (ops/containers.choose)."""
        key = ("leaf", idx.name, field_name, row_id, shards)
        field = idx.field(field_name)
        view = field.view(VIEW_STANDARD) if field is not None else None
        if view is None:
            return None
        hit = self._cache_get_fast(key, (view.uid, view.mutations))
        if hit is not None:
            return hit
        stamp = (view.uid, view.mutations)
        gens = self._fragment_gens(idx, field_name, shards, view=view)
        if gens is None:
            return None
        hit = self._cache_get(key, gens, stamp)
        if hit is not None:
            return hit
        # Incremental maintenance: when k << S shards drifted (a write
        # bumps only its fragment's generation), gather + upload ONLY
        # those planes and scatter them into the cached device stack —
        # the device analog of the reference's op-log-over-snapshot delta
        # (roaring.go:228-249) — instead of re-uploading the whole [S, W]
        # stack for a single set_bit. A compressed container has no
        # per-shard planes to scatter into, so it decompresses ON
        # DEVICE once and the fragment decays to dense under write
        # churn — the same convert-on-mutation policy as the
        # reference's roaring containers; the chooser re-compresses at
        # the next full rebuild/readmission, when the density is known
        # again.
        stale = self._stale_entry(key, gens)
        if stale is not None:
            if self._serve_stale(key, idx.name, field_name, VIEW_STANDARD,
                                 shards, stale, gens):
                return stale[1]
            changed = self._changed_shards(stale[0], gens, shards)
            if changed is not None:
                import jax.numpy as jnp

                block = self._host_rows(
                    view, [row_id], [shards[j] for j in changed],
                    pad=False)
                ent = stale[1]
                if isinstance(ent, _containers.Container) \
                        and ent.kind != "dense":
                    old = _containers.container_to_dense(ent)
                elif isinstance(ent, _containers.Container):
                    old = ent.arrays[0]
                else:
                    old = ent
                stack = self._place(
                    old.at[np.asarray(changed)].set(
                        jnp.asarray(block[0])), shard_axis=0)
                self._note_patch("read")
                cont = _containers.dense_container(stack)
                self._cache_put(key, gens, cont, cont.nbytes, stamp)
                return cont
        host = self._host_rows(view, [row_id], shards)
        cont = _containers.build(
            host[0],
            place_sharded=lambda a: self._place(a, shard_axis=0),
            place_replicated=self._place_replicated,
            fragment=(idx.name, field_name, VIEW_STANDARD, row_id))
        self._cache_put(key, gens, cont, cont.nbytes, stamp)
        return cont

    def _host_rows(self, view, row_ids, shards, pad=True):
        """Host [R, S_padded, W] uint32 gather of rows over shards
        (pad=False skips the device-multiple padding — patch gathers
        address existing stack rows directly).

        The per-shard gathers fan out over the shared worker pool: each
        task fills its own out[:, j] column (disjoint slices, so the
        writes need no lock) and the numpy copies release the GIL. This
        is the cold-build hot path — 954 shards × rows of one-at-a-time
        copies before."""
        from ..utils.workpool import get_pool

        n = self._padded_len(shards) if pad else len(shards)
        out = np.zeros((len(row_ids), n, WORDS_PER_ROW), dtype=np.uint32)

        def gather_column(j):
            frag = view.fragment(shards[j])
            if frag is None:
                return
            for i, row_id in enumerate(row_ids):
                plane = frag.row_plane(row_id)
                if plane is not None:
                    out[i, j] = np.asarray(plane)

        get_pool().map_ordered(gather_column, range(len(shards)))
        self.planes_uploaded += len(row_ids) * len(shards)
        return out

    def _stale_entry(self, key, gens):
        """(old_gens, arrays, nbytes) of a cached entry whose generations
        drifted, or None. Read under the lock; the returned arrays are
        immutable device buffers so using them outside the lock is safe."""
        pool, _ = self._pool(key)
        with self._lock:
            entry = pool.get(key)
            if entry is None or len(entry[0]) != len(gens):
                return None
            return entry

    def _note_patch(self, path):
        """Count one incremental stack patch, tagged by where it ran:
        "read" = legacy in-query repair, "merge" = the ingest engine's
        interval fold. Exported as stacked_patches_total{path} so the two
        are distinguishable on /metrics (the ingest tests assert the
        read-path count stays flat while deltas are pending)."""
        self.patches += 1
        global_stats.count("stacked_patches", 1, {"path": path})

    def _serve_stale(self, key, index_name, field_name, view_name, shards,
                     stale, gens):
        """True when a stale entry may serve AS-IS because every drifted
        shard is covered by a pending ingest delta (exec/ingest.py) — the
        interval merge folds the drift off the read path; staleness is
        bounded by the merge interval. One list check when no ingest
        engine is active (the default)."""
        if not _ingest.covers_pending(index_name, field_name, view_name,
                                      shards, stale[0], gens):
            return False
        self.stale_serves += 1
        global_stats.count("stacked_stale_serves", 1)
        return True

    def _changed_shards(self, old_gens, gens, shards, rows=1):
        """Stack row indices whose (uid, generation) drifted, or None
        when a device patch isn't worthwhile. The cutoff is the static
        half-the-shards rule (a scatter past it costs about as much as a
        rebuild) — except under --adaptive on, where the cost model
        prices upload vs on-device copy bytes (exec/adaptive.decide_patch)
        and typically patches up to ~7/8 drift."""
        changed = [j for j, (o, n) in enumerate(zip(old_gens, gens))
                   if o != n]
        if not changed:
            return None
        if _adaptive.acting():
            if not _adaptive.decide_patch(len(changed), len(shards), rows,
                                          WORDS_PER_ROW * 4):
                return None
        elif len(changed) * 2 > len(shards):
            return None
        return changed

    def rows_stack(self, idx, field_name, row_chunk, shards,
                   view_name=VIEW_STANDARD, cache=True):
        """Cached [R, S, W] device stack of a chunk of rows (TopN/GroupBy
        candidates). `row_chunk` must be a tuple (cache key). cache=False
        builds a transient stack (freed after use) — callers pass it when
        the full candidate set exceeds the rows pool, so oversized scans
        don't churn out every reusable chunk."""
        key = ("rows", idx.name, field_name, view_name, row_chunk, shards)
        field = idx.field(field_name)
        view = field.view(view_name) if field is not None else None
        if view is None:
            return None
        if cache:
            hit = self._cache_get_fast(key, (view.uid, view.mutations))
            if hit is not None:
                return hit
        stamp = (view.uid, view.mutations)
        gens = self._fragment_gens(idx, field_name, shards, view_name,
                                   view=view)
        if gens is None:
            return None
        hit = self._cache_get(key, gens, stamp if cache else None)
        if hit is not None:
            return hit
        if cache:
            stale = self._stale_entry(key, gens)
            if stale is not None:
                if self._serve_stale(key, idx.name, field_name, view_name,
                                     shards, stale, gens):
                    return stale[1]
                changed = self._changed_shards(stale[0], gens, shards,
                                               rows=len(row_chunk))
                if changed is not None:
                    import jax.numpy as jnp

                    block = self._host_rows(
                        view, list(row_chunk),
                        [shards[j] for j in changed], pad=False)
                    stack = self._place(
                        stale[1].at[:, np.asarray(changed)].set(
                            jnp.asarray(block)), shard_axis=1)
                    self._note_patch("read")
                    self._cache_put(key, gens, stack, stack.size * 4,
                                    stamp)
                    return stack
        host = self._host_rows(view, list(row_chunk), shards)
        stack = self._place(host, shard_axis=1)
        if cache:
            self._cache_put(key, gens, stack, stack.size * 4, stamp)
        return stack

    def bsi_stack(self, idx, field_name, shards):
        """Cached (planes [D,S,W], sign [S,W], exists [S,W]) device stacks
        of a BSI field's bit-plane rows (reference layout fragment.go:91-93).
        None when the field/view vanished."""
        field = idx.field(field_name)
        if field is None:
            return None
        view_name = field.bsi_view_name()
        depth = field.options.bit_depth
        key = ("bsi", idx.name, field_name, depth, shards)
        view = field.view(view_name)
        if view is None:
            return None
        hit = self._cache_get_fast(key, (view.uid, view.mutations))
        if hit is not None:
            return hit
        stamp = (view.uid, view.mutations)
        gens = self._fragment_gens(idx, field_name, shards, view_name,
                                   view=view)
        if gens is None:
            return None
        hit = self._cache_get(key, gens, stamp)
        if hit is not None:
            return hit
        rows = [BSI_EXISTS_BIT, BSI_SIGN_BIT] + [
            BSI_OFFSET_BIT + i for i in range(depth)]
        stale = self._stale_entry(key, gens)
        if stale is not None:
            if self._serve_stale(key, idx.name, field_name, view_name,
                                 shards, stale, gens):
                return stale[1]
            changed = self._changed_shards(stale[0], gens, shards,
                                           rows=len(rows))
            if changed is not None:
                import jax.numpy as jnp

                planes, sign, exists = stale[1]
                block = jnp.asarray(self._host_rows(
                    view, rows, [shards[j] for j in changed], pad=False))
                jdx = np.asarray(changed)
                arrays = (
                    self._place(planes.at[:, jdx].set(block[2:]),
                                shard_axis=1),
                    self._place(sign.at[jdx].set(block[1]), shard_axis=0),
                    self._place(exists.at[jdx].set(block[0]),
                                shard_axis=0),
                )
                self._note_patch("read")
                self._cache_put(key, gens, arrays, stale[2], stamp)
                return arrays
        host = self._host_rows(view, rows, shards)
        arr = self._place(host, shard_axis=1)
        arrays = (arr[2:], arr[1], arr[0])  # planes, sign, exists
        self._cache_put(key, gens, arrays, arr.size * 4, stamp)
        return arrays

    def bsi_condition_stack(self, idx, key, shards):
        """[S, W] mask of a BSI condition leaf evaluated over the cached
        (and incrementally patched) [D, S, W] plane stack in ONE extra
        dispatch — Count(Row(v > 10)) stays O(1)-in-shards (VERDICT r4
        item 4; reference per-shard algorithm fragment.go:1357-1470)."""
        from .bsicond import (
            BsiConditionError,
            apply_bsi_condition,
            bsi_condition_plan,
            condition_from_key,
        )

        _, field_name, op, vals = key
        field = idx.field(field_name)
        if field is None or field.options.type != "int":
            return None
        try:
            plan = bsi_condition_plan(
                field.options, condition_from_key(op, vals))
        except BsiConditionError:
            return None
        # the empty/notnull plans need no magnitude planes (bsicond.py
        # contract) — don't gather+upload the whole [D+2, S, W] stack
        if plan[0] == "empty":
            import jax.numpy as jnp

            return jnp.zeros((self._padded_len(tuple(shards)),
                              WORDS_PER_ROW), dtype=jnp.uint32)
        if plan[0] == "notnull":
            stack = self.rows_stack(idx, field_name, (BSI_EXISTS_BIT,),
                                    tuple(shards),
                                    view_name=field.bsi_view_name())
            return None if stack is None else stack[0]
        data = self.bsi_stack(idx, field_name, shards)
        if data is None:
            return None
        planes, sign, exists = data
        self.dispatches += 1
        with self._locked_dispatch(
                "bsi_condition",
                nbytes_in=(planes.size + sign.size + exists.size) * 4,
                nbytes_out=sign.size * 4) as ph:
            out = apply_bsi_condition(plan, planes, sign, exists)
            ph.mark("dispatch_ack")
            out = _launch_barrier(out)
            ph.mark("sync")
            return out

    def time_row_stack(self, idx, key, shards):
        """[S, W] union of one row across the quantum-view cover (the
        time-range leaf). Each per-view stack is cached + incrementally
        patched like any other; views absent on this holder contribute
        nothing (exactly the executor's per-shard union semantics)."""
        import jax.numpy as jnp

        _, field_name, row_id, views = key
        field = idx.field(field_name)
        if field is None:
            return None
        stacks = []
        for view_name in views:
            if field.view(view_name) is None:
                continue  # no data in this quantum bucket anywhere local
            stack = self.rows_stack(idx, field_name, (row_id,),
                                    tuple(shards), view_name=view_name)
            if stack is None:
                continue  # view vanished mid-query: zero contribution
            stacks.append(stack[0])
        if not stacks:
            return jnp.zeros((self._padded_len(tuple(shards)),
                              WORDS_PER_ROW), dtype=jnp.uint32)
        if len(stacks) == 1:
            return stacks[0]
        # the evaluator's own union fold: one fn-cache, one operator impl
        sig = ("|", tuple(("leaf", i) for i in range(len(stacks))))
        self.dispatches += 1
        fn = self._plane_fn(sig, len(stacks))
        with self._locked_dispatch(
                "time_union",
                nbytes_in=sum(s.size for s in stacks) * 4,
                nbytes_out=stacks[0].size * 4, fn=fn) as ph:
            out = fn(*stacks)
            ph.mark("dispatch_ack")
            out = _launch_barrier(out)
            ph.mark("sync")
            return out

    def row_chunk_size(self, shards):
        """Rows per [R, S, W] chunk under the CHUNK_BYTES budget."""
        return max(
            1, CHUNK_BYTES // (self._padded_len(shards) * WORDS_PER_ROW * 4))

    @contextlib.contextmanager
    def _locked_dispatch(self, kind, nbytes_in=0, nbytes_out=0, fn=None):
        """Hold the process-wide dispatch lock around one device launch.

        Always on (cheap — a few dict/deque ops vs ms-scale kernels;
        the flightrec + devhealth bench legs hold the total under 2% of
        kernel wall): per-kernel wall/bytes attribution
        (`kernel_seconds{kernel}` histograms, /debug/kernels), dispatch
        start/end flight-recorder events, and a watchdog op covering the
        lock hold — a dispatch that never returns (the r05 tunnel wedge)
        trips the stall dump instead of hanging silently. With a
        QueryProfile active it additionally measures how long THIS query
        waited on the lock vs how long its kernel held it, emits a
        `stacked.kernel` child span (op=kind), and accumulates the
        profile's lock-wait/kernel-wall totals — the two numbers that
        split "slow query" into contention vs compute.

        Yields a _PhaseClock: sites mark "dispatch_ack" after the
        program call returns and "sync" after the launch barrier, so the
        65ms dispatch RTT of BENCH r03 decomposes into where it actually
        goes (GET /debug/dispatch, phase_* profile tags, EXPLAIN ANALYZE
        actuals). `fn` — when it is a _wrap_spec_capture kernel — lets
        the clock detect a first call (its key absent from the arg-spec
        cache) and relabel dispatch_ack as compile."""
        _check_thread_deadline()
        prof = _profile.current()
        _flightrec.record("dispatch.start", kernel=kind)
        token = _flightrec.watch_begin("dispatch." + kind)
        compiling = False
        if fn is not None:
            key = getattr(fn, "_spec_key", None)
            compiling = key is not None and key not in self._fn_specs
        t0 = time.perf_counter()
        try:
            with self._dispatch_lock:
                t1 = time.perf_counter()
                ph = _PhaseClock(t1, compiling)
                if prof is None:
                    yield ph
                else:
                    with _tracing.start_span("stacked.kernel",
                                             op=kind) as span:
                        if span is not None:
                            span.set_tag("lock_wait_seconds",
                                         round(t1 - t0, 6))
                        yield ph
                        if span is not None:
                            for phase, dt in ph.phases:
                                span.set_tag(f"phase_{phase}_seconds",
                                             round(dt, 6))
                t2 = time.perf_counter()
        finally:
            _flightrec.watch_end(token)
        wait, wall = t1 - t0, t2 - t1
        # fold the residual (span bookkeeping, unmarked tails) into the
        # last phase so the phases sum exactly to the dispatch wall; a
        # site that never marked attributes its whole wall in one piece
        if ph.phases:
            ph.phases[-1][1] += t2 - ph._t
        else:
            ph.phases.append(["compile" if compiling else "dispatch_ack",
                              wall])
        phases = [("lock_wait", wait)] + [tuple(p) for p in ph.phases]
        self._note_kernel(kind, wall, nbytes_in, nbytes_out)
        self._note_phases(kind, phases)
        _flightrec.record("dispatch.end", kernel=kind,
                          lock_wait_seconds=round(wait, 6),
                          kernel_wall_seconds=round(wall, 6))
        if prof is not None:
            prof.add("dispatch_lock_wait_seconds", wait)
            prof.add("kernel_wall_seconds", wall)
            prof.add("locked_dispatches", 1)
            for phase, dt in phases:
                if phase != "lock_wait":  # already counted above
                    prof.add(f"phase_{phase}_seconds", dt)

    def _note_kernel(self, kind, wall, nbytes_in, nbytes_out):
        """Per-kernel-family attribution (see /debug/kernels)."""
        with self._lock:
            k = self._kernels.get(kind)
            if k is None:
                k = self._kernels[kind] = {
                    "count": 0, "seconds": 0.0,
                    "bytes_in": 0, "bytes_out": 0}
            k["count"] += 1
            k["seconds"] += wall
            k["bytes_in"] += nbytes_in
            k["bytes_out"] += nbytes_out
        tags = {"kernel": kind}
        global_stats.timing("kernel_seconds", wall, tags)
        if nbytes_in:
            global_stats.count("kernel_bytes_in", nbytes_in, tags)
        if nbytes_out:
            global_stats.count("kernel_bytes_out", nbytes_out, tags)

    def _note_phases(self, kind, phases):
        """Per-kernel per-phase attribution (see GET /debug/dispatch)."""
        with self._lock:
            fam = self._dispatch_phases.get(kind)
            if fam is None:
                fam = self._dispatch_phases[kind] = {}
            for phase, dt in phases:
                p = fam.get(phase)
                if p is None:
                    p = fam[phase] = {"count": 0, "seconds": 0.0}
                p["count"] += 1
                p["seconds"] += dt
        # mirror into the process-wide aggregate: the bare debug server
        # in bench children answers /debug/dispatch from it
        with _GLOBAL_PHASES_LOCK:
            gfam = _GLOBAL_PHASES.setdefault(kind, {})
            for phase, dt in phases:
                gp = gfam.setdefault(phase, {"count": 0, "seconds": 0.0})
                gp["count"] += 1
                gp["seconds"] += dt
        for phase, dt in phases:
            global_stats.timing("dispatch_phase_seconds", dt,
                                {"kernel": kind, "phase": phase})

    def dispatch_phases(self):
        """{kernel: {phase: {count, seconds}}} snapshot — the RTT
        decomposition behind GET /debug/dispatch and the analyze path's
        per-phase before/after delta basis. Phase seconds other than
        lock_wait sum to the family's kernel wall by construction."""
        with self._lock:
            return {k: {p: dict(v) for p, v in fam.items()}
                    for k, fam in self._dispatch_phases.items()}

    # -- compiled kernels ----------------------------------------------------

    def _get_fn(self, key, build):
        with self._lock:
            fn = self._fns.get(key)
            if fn is not None:
                self._fns.move_to_end(key)
                return fn
        fn = self._wrap_spec_capture(key, build())
        with self._lock:
            self._fns[key] = fn
            while len(self._fns) > MAX_FNS:
                self._fns.popitem(last=False)
        return fn

    def _wrap_spec_capture(self, key, fn):
        """Record the arg shape specs on a compiled fn's FIRST call (one
        dict-membership check afterwards), so /debug/kernels can lower +
        compile for jax cost_analysis() lazily — the flops/bytes numbers
        come from XLA, but never at serving-path cost."""
        def wrapped(*args):
            if key not in self._fn_specs:
                try:
                    import jax

                    self._fn_specs[key] = tuple(
                        jax.ShapeDtypeStruct(a.shape, a.dtype)
                        for a in args)
                except Exception:  # noqa: BLE001 — attribution only
                    self._fn_specs[key] = None
            return fn(*args)

        wrapped._jit_fn = fn
        wrapped._spec_key = key  # first-call (compile) detection
        return wrapped

    @staticmethod
    def _tree_eval(sig, stacks):
        if sig[0] == "leaf":
            return stacks[sig[1]]
        op, subs = sig
        acc = StackedEvaluator._tree_eval(subs[0], stacks)
        for s in subs[1:]:
            p = StackedEvaluator._tree_eval(s, stacks)
            if op == "&":
                acc = acc & p
            elif op == "|":
                acc = acc | p
            elif op == "^":
                acc = acc ^ p
            else:
                acc = acc & ~p
        return acc

    def _count_fn(self, sig, csig):
        """Tree -> (hi, lo) int32 popcount totals over all shards.
        `csig` is the tuple of container signatures (or a legacy arity
        int meaning that many raw dense stacks — test/back-compat call
        sites). The program itself lives in ops/containers.count_program:
        all-dense signatures trace to EXACTLY the legacy tree-eval +
        popcount program (to_dense is the identity), which is the
        forced-dense bit-identity guarantee."""
        import jax

        csig = _containers.norm_csig(csig)

        def build():
            @jax.jit
            def fn(*flat):
                return _containers.count_program(
                    sig, csig, flat, self._tree_eval)

            return fn

        return self._get_fn(("count", sig, csig), build)

    def _count_batch_fn(self, sig, csig, batch):
        """`batch` independent count trees of one signature fused into ONE
        program: args are batch*flat_arity container components, outputs
        are [batch] (hi, lo) vectors. This is bench.py's batched-serving
        trick productionized (VERDICT r3 item 5): one dispatch + one fetch
        amortize the per-query round trip across every concurrent query."""
        import jax
        import jax.numpy as jnp

        csig = _containers.norm_csig(csig)
        af = _containers.flat_arity(csig)

        def build():
            @jax.jit
            def fn(*all_flat):
                his, los = [], []
                for q in range(batch):
                    flat = all_flat[q * af:(q + 1) * af]
                    hi, lo = _containers.count_program(
                        sig, csig, flat, self._tree_eval)
                    his.append(hi)
                    los.append(lo)
                return jnp.stack(his), jnp.stack(los)

            return fn

        return self._get_fn(("countB", sig, csig, batch), build)

    def fused_count_fn(self, plans):
        """A whole query's Count trees fused into ONE program (exec/
        fusion.py). `plans` is a tuple of (sig, csig) per top-level
        call — unlike _count_batch_fn the trees need NOT share a
        signature; each call's components are sliced off the flat
        argument list by its own arity and traced through its own
        count_program, so the fused program inlines dense, sparse, RLE
        and overlay-carrying containers side by side. Outputs are
        [n_calls] (hi, lo) vectors — the same 16-bit overflow-split
        contract as every count program."""
        import jax
        import jax.numpy as jnp

        plans = tuple((sig, _containers.norm_csig(csig))
                      for sig, csig in plans)
        key = ("fused", plans)

        def build():
            @jax.jit
            def fn(*all_flat):
                his, los = [], []
                i = 0
                for sig, csig in plans:
                    af = _containers.flat_arity(csig)
                    hi, lo = _containers.count_program(
                        sig, csig, all_flat[i:i + af], self._tree_eval)
                    i += af
                    his.append(hi)
                    los.append(lo)
                return jnp.stack(his), jnp.stack(los)

            return fn

        return self._get_fn(key, build), key

    def fused_count(self, plans, stacks_per_call):
        """Execute a whole query's Count calls as ONE locked dispatch +
        one group-committed fetch. Returns (counts, fn_key, compiled):
        per-call host ints in call order, the program's fn-cache key
        (exec/fusion.py pins it so its LRU eviction can drop the
        compiled fn too), and whether THIS invocation traced+compiled
        (first call on the key — same detection _locked_dispatch uses
        to relabel dispatch_ack as compile)."""
        fn, key = self.fused_count_fn(plans)
        compiled = key not in self._fn_specs
        args, nbytes_in = [], 0
        for stacks in stacks_per_call:
            args.extend(_containers.flatten(stacks))
            nbytes_in += sum(c.nbytes for c in stacks)
        self.dispatches += 1
        with self._lock:
            self.fused_dispatches += 1
        with self._locked_dispatch("fused", nbytes_in=nbytes_in,
                                   fn=fn) as ph:
            his, los = fn(*args)
            ph.mark("dispatch_ack")
            _launch_barrier((his, los))
            ph.mark("sync")
        # amortized result fetch (group commit, like _batched_count)
        vals = self._fetch_commit.submit((his, los), _device_get_batch)
        his_h, los_h = np.atleast_1d(vals[0]), np.atleast_1d(vals[1])
        counts = [combine_hi_lo(h, l) for h, l in zip(his_h, los_h)]
        return counts, key, compiled

    #: count-batcher buckets: batch sizes are rounded up to a power of two
    #: (padding repeats the first query) so at most log2(MAX) programs
    #: compile per signature; 32 keeps device time per dispatch (~11 ms at
    #: 954 shards) under the tunnel RTT it amortizes
    MAX_COUNT_BATCH = 32

    def _batched_count(self, sig, stacks):
        """Group-commit count execution: the batch leader drains every
        queued count query, groups them by signature, runs one fused
        program per group (power-of-two bucket, padded by repeating the
        first query), fetches ALL results in one transfer, and
        distributes. Solo queries pay nothing extra; leader failures
        propagate to every waiter (GroupCommit contract).

        The per-payload return is (count, fused-batch size); the size is
        stamped into the waiter's thread-local here so SLOW QUERY lines
        and strategy notes can attribute `batch=` without threading it
        through every caller."""
        count, size = self._count_commit.submit(
            (sig, tuple(stacks)), self._process_count_batch)
        note_batch_size(size)
        return count

    def _process_count_batch(self, payloads):
        """GroupCommit `process` for count queries: payloads are
        (sig, stacks) pairs; returns (count, fused-batch size) pairs in
        order — the size is how many REAL queries shared the payload's
        dispatch (padding excluded)."""
        import jax

        groups = {}
        for pos, (sig, stacks) in enumerate(payloads):
            csig = tuple(c.csig for c in stacks)
            groups.setdefault((sig, csig), []).append(pos)
        outs = []
        for (sig_g, csig_g), positions in groups.items():
            for i in range(0, len(positions), self.MAX_COUNT_BATCH):
                chunk = positions[i:i + self.MAX_COUNT_BATCH]
                size = 1 << (len(chunk) - 1).bit_length()
                if size == 1:
                    # solo query: reuse the plain count program (shared
                    # with warm pre-batching traffic) instead of
                    # compiling an identical batch-1 variant
                    fn = self._count_fn(sig_g, csig_g)
                else:
                    fn = self._count_batch_fn(sig_g, csig_g, size)
                args = []
                nbytes_in = 0
                for pos in chunk:
                    args.extend(_containers.flatten(payloads[pos][1]))
                    nbytes_in += sum(c.nbytes for c in payloads[pos][1])
                for _ in range(size - len(chunk)):
                    args.extend(  # pad: repeat q0
                        _containers.flatten(payloads[chunk[0]][1]))
                    nbytes_in += sum(
                        c.nbytes for c in payloads[chunk[0]][1])
                with self._locked_dispatch(
                        "count", nbytes_in=nbytes_in, fn=fn) as ph:
                    his, los = fn(*args)
                    ph.mark("dispatch_ack")
                    _launch_barrier((his, los))
                    ph.mark("sync")
                outs.append((chunk, his, los))
        flat = [a for _, h, l in outs for a in (h, l)]
        vals = jax.device_get(flat)  # ONE transfer for everything
        results = [None] * len(payloads)
        i = 0
        for chunk, _, _ in outs:
            # atleast_1d: the solo path returns 0-d scalars
            his, los = np.atleast_1d(vals[i]), np.atleast_1d(vals[i + 1])
            i += 2
            for q, pos in enumerate(chunk):
                results[pos] = (combine_hi_lo(his[q], los[q]), len(chunk))
        return results

    def _plane_fn(self, sig, csig):
        """Tree -> combined [S, W] plane stack (filter materialization).
        Compressed leaves decompress in-program (exact by construction)
        so the output is always the legacy dense plane; `csig` accepts a
        legacy arity int for raw dense args (time_union fold)."""
        import jax

        csig = _containers.norm_csig(csig)

        def build():
            @jax.jit
            def fn(*flat):
                return _containers.plane_program(
                    sig, csig, flat, self._tree_eval)

            return fn

        return self._get_fn(("plane", sig, csig), build)

    # -- vmapped batch kernels (query coalescer) -----------------------------
    #
    # The coalescer's serving programs: `bucket` independent queries of
    # one tree signature evaluated with a leading query axis. Args are
    # bucket*arity separate [S, W] leaf stacks (query-major, exactly the
    # device arrays the stack cache already holds — no host restacking);
    # the program stacks each leaf slot to [B, S, W] and vmaps the tree
    # combine over axis 0, so XLA fuses the whole batch into ONE launch
    # and the 65ms dispatch RTT of BENCH r03 is paid once per batch.

    def _vmap_count_fn(self, sig, csig, bucket):
        """`bucket` count trees -> (hi [B], lo [B]) popcount totals.
        Queries in one vmapped bucket share a container signature AND
        exact component shapes (launch_query_batch groups on gsig), so
        each flat component slot stacks to a leading batch axis and the
        per-query compressed count program vmaps over it."""
        import jax
        import jax.numpy as jnp

        csig = _containers.norm_csig(csig)
        af = _containers.flat_arity(csig)

        def build():
            vprog = jax.vmap(lambda *flat: _containers.count_program(
                sig, csig, flat, self._tree_eval))

            @jax.jit
            def fn(*flat):
                # flat is query-major: flat[q*af + j] = query q's j-th
                # component, so flat[j::af] gathers slot j across the
                # batch
                slots = [jnp.stack(flat[j::af]) for j in range(af)]
                return vprog(*slots)

            return fn

        return self._get_fn(("countV", sig, csig, bucket), build)

    def _vmap_plane_fn(self, sig, csig, bucket):
        """`bucket` bitmap trees -> combined [B, S, W] plane stacks."""
        import jax
        import jax.numpy as jnp

        csig = _containers.norm_csig(csig)
        af = _containers.flat_arity(csig)

        def build():
            vprog = jax.vmap(lambda *flat: _containers.plane_program(
                sig, csig, flat, self._tree_eval))

            @jax.jit
            def fn(*flat):
                slots = [jnp.stack(flat[j::af]) for j in range(af)]
                return vprog(*slots)

            return fn

        return self._get_fn(("planeV", sig, csig, bucket), build)

    def gather_for_batch(self, idx, call, shards):
        """Batch-member coverage + leaf-stack gather: (sig, stacks) or
        None when the tree isn't batchable on the stacked path (caller
        falls back to the per-query path)."""
        shards = tuple(shards)
        if len(shards) < MIN_SHARDS:
            return None
        return self._gather(idx, call, shards)

    def launch_query_batch(self, items):
        """Launch every gathered query in `items` — (kind, sig, stacks)
        triples, kind "count" or "plane" — as bucket-padded vmapped
        programs WITHOUT fetching anything back. Returns the opaque
        handle resolve_query_batch() turns into per-item results with
        ONE device->host transfer.

        The split is the double buffer: the coalescer thread launches
        batch N+1 (enqueue-only on accelerator backends) before
        resolving batch N, overlapping batch N's host sync with batch
        N+1's device execution. On the CPU test backend
        _launch_barrier() serializes execution inside the lock, so the
        overlap degenerates to FIFO — structurally identical, just
        without the win."""
        groups = {}
        for pos, (kind, sig, stacks) in enumerate(items):
            # group on gsig (repr kinds + exact component shapes):
            # same-representation fragments keep fusing into one vmapped
            # bucket exactly as before, while a mixed-repr batch SPLITS
            # into per-representation groups — each degrades to its own
            # (possibly solo) dispatch on the legacy program shape
            # instead of failing the batch
            gsig = tuple(c.gsig for c in stacks)
            groups.setdefault((kind, sig, gsig), []).append(pos)
        launched = []
        for (kind, sig, _gsig), positions in groups.items():
            csig = tuple(c.csig for c in items[positions[0]][2])
            for i in range(0, len(positions), BATCH_BUCKETS[-1]):
                chunk = positions[i:i + BATCH_BUCKETS[-1]]
                bucket = batch_bucket(len(chunk))
                args = []
                nbytes_in = 0
                for pos in chunk:
                    args.extend(_containers.flatten(items[pos][2]))
                    nbytes_in += sum(c.nbytes for c in items[pos][2])
                for _ in range(bucket - len(chunk)):
                    args.extend(  # pad: repeat q0
                        _containers.flatten(items[chunk[0]][2]))
                    nbytes_in += sum(
                        c.nbytes for c in items[chunk[0]][2])
                if kind == "count":
                    fn = self._count_fn(sig, csig) if bucket == 1 \
                        else self._vmap_count_fn(sig, csig, bucket)
                    kname = "count_batched"
                else:
                    fn = self._plane_fn(sig, csig) if bucket == 1 \
                        else self._vmap_plane_fn(sig, csig, bucket)
                    kname = "plane_batched"
                with self._lock:
                    self.dispatches += 1
                    self.batch_dispatches += 1
                    self.batched_queries += len(chunk)
                _flightrec.record("batch.dispatch", kernel=kname,
                                  queries=len(chunk), bucket=bucket)
                global_stats.count("batch_dispatch_total", 1, {
                    "kernel": kname, "bucket": str(bucket)})
                # batch-size histogram: occupancy per fused dispatch
                global_stats.timing(
                    "coalesce_batch_size", float(len(chunk)))
                with self._locked_dispatch(
                        kname, nbytes_in=nbytes_in, fn=fn) as ph:
                    out = fn(*args)
                    ph.mark("dispatch_ack")
                    out = _launch_barrier(out)
                    ph.mark("sync")
                launched.append((kind, chunk, bucket, out))
        return launched

    def resolve_query_batch(self, launched):
        """ONE device->host transfer for everything launch_query_batch
        enqueued. Returns {item position: (result, fused-batch size,
        dispatch index)}: count results are exact Python ints, plane
        results are host [S_pad, W] uint32 arrays (row j = the j-th
        shard the stacks were gathered over; padding rows are zero).
        The dispatch index identifies which fused launch served the
        item, so the caller can attribute each dispatch exactly once
        across the members that rode it."""
        import jax

        flat = []
        for kind, _, _, out in launched:
            if kind == "count":
                flat.extend(out)  # (hi, lo)
            else:
                flat.append(out)
        vals = jax.device_get(flat)
        results = {}
        i = 0
        for di, (kind, chunk, bucket, _) in enumerate(launched):
            if kind == "count":
                # atleast_1d: the solo path returns 0-d scalars
                his = np.atleast_1d(vals[i])
                los = np.atleast_1d(vals[i + 1])
                i += 2
                for q, pos in enumerate(chunk):
                    results[pos] = (combine_hi_lo(his[q], los[q]),
                                    len(chunk), di)
            else:
                planes = vals[i]
                i += 1
                if bucket == 1:
                    planes = planes[None]  # solo program: [S, W]
                for q, pos in enumerate(chunk):
                    results[pos] = (planes[q], len(chunk), di)
        return results

    def _row_counts_fn(self, has_filt):
        """(rows [R,S,W], filt [S,W]?) -> (hi [R], lo [R]) counts of
        rows ∩ filter over all shards."""
        import jax
        import jax.numpy as jnp

        def build():
            def counts(rows, filt):
                x = rows & filt[None] if has_filt else rows
                per_shard = jnp.sum(
                    jax.lax.population_count(x).astype(jnp.int32), axis=-1)
                return bitplane.hi_lo(per_shard, axis=-1)

            if has_filt:
                return jax.jit(lambda rows, filt: counts(rows, filt))
            return jax.jit(lambda rows: counts(rows, None))

        return self._get_fn(("row_counts", has_filt), build)

    def _sum_fn(self, has_filt):
        """(planes [D,S,W], sign, exists, filt?) -> per-plane positive and
        negative popcounts + consider count, all as (hi, lo) pairs
        (reference: fragment.sum fragment.go:1068)."""
        import jax
        import jax.numpy as jnp

        def build():
            def kernel(planes, sign, exists, filt):
                consider = exists & filt if has_filt else exists
                pos = consider & ~sign
                neg = consider & sign
                pc = jnp.sum(jax.lax.population_count(
                    planes & pos[None]).astype(jnp.int32), axis=-1)  # [D,S]
                nc = jnp.sum(jax.lax.population_count(
                    planes & neg[None]).astype(jnp.int32), axis=-1)
                cc = jnp.sum(jax.lax.population_count(
                    consider).astype(jnp.int32), axis=-1)            # [S]
                return (*bitplane.hi_lo(pc, axis=-1),
                        *bitplane.hi_lo(nc, axis=-1),
                        *bitplane.hi_lo(cc))

            if has_filt:
                return jax.jit(kernel)
            return jax.jit(
                lambda planes, sign, exists: kernel(
                    planes, sign, exists, None))

        return self._get_fn(("sum", has_filt), build)

    def _minmax_fn(self, has_filt, is_max):
        """One-dispatch global Min/Max over stacked BSI planes.

        Computes both the positive-branch and negative-branch narrowing
        walks (ops.bsi min/max_unsigned work unchanged on [D,S,W] planes
        with [S,W] filters — the scans are elementwise with global any())
        and selects per the reference's sign rules (fragment.go:1110-1227):
        Max: highest positive else closest-to-zero negative; Min: most
        negative else lowest positive. Returns (empty, use_neg, bits [D],
        cnt_hi, cnt_lo)."""
        import jax
        import jax.numpy as jnp

        from ..ops import bsi as bsi_ops

        def build():
            def kernel(planes, sign, exists, filt):
                consider = exists & filt if has_filt else exists
                pos = consider & ~sign
                neg = consider & sign
                has_pos = jnp.any(pos != 0)
                has_neg = jnp.any(neg != 0)
                empty = ~(has_pos | has_neg)
                if is_max:
                    # highest positive, else closest-to-zero negative
                    b_pos, f_pos = bsi_ops.max_unsigned(planes, pos)
                    b_neg, f_neg = bsi_ops.min_unsigned(planes, neg)
                    use_neg = ~has_pos
                else:
                    # most negative, else lowest positive
                    b_neg, f_neg = bsi_ops.max_unsigned(planes, neg)
                    b_pos, f_pos = bsi_ops.min_unsigned(planes, pos)
                    use_neg = has_neg
                bits = jnp.where(use_neg, b_neg, b_pos)
                final = jnp.where(use_neg, f_neg, f_pos)
                per_shard = jnp.sum(
                    jax.lax.population_count(final).astype(jnp.int32),
                    axis=-1)
                return (empty, use_neg, bits, *bitplane.hi_lo(per_shard))

            if has_filt:
                return jax.jit(kernel)
            return jax.jit(
                lambda planes, sign, exists: kernel(
                    planes, sign, exists, None))

        return self._get_fn(("minmax", has_filt, is_max), build)

    # -- public entry points -------------------------------------------------

    def _gather(self, idx, call, shards):
        """Shared tree-coverage + leaf-stack gather: (sig, stacks) or None
        when the tree isn't stack-coverable or a leaf's field vanished
        (concurrent DDL) — callers fall back to the per-shard path."""
        leaves = {}
        sig = self.signature(idx, call, leaves)
        if sig is None or not leaves:
            return None
        ordered = sorted(leaves.items(), key=lambda kv: kv[1])
        stacks = []
        for key, _ in ordered:
            if key[0] == "bsicond":
                s = self.bsi_condition_stack(idx, key, shards)
            elif key[0] == "timerow":
                s = self.time_row_stack(idx, key, shards)
            else:
                _, field_name, row_id = key
                # leaf_stack returns a Container already
                stacks.append(
                    self.leaf_stack(idx, field_name, row_id, shards))
                continue
            # bsi-condition masks / time-union folds are freshly computed
            # dense planes: wrap without copying so downstream programs
            # see one uniform container argument shape
            stacks.append(
                None if s is None else _containers.dense_container(s))
        if any(s is None for s in stacks):
            return None
        return sig, stacks

    def try_count(self, idx, call_child, shards):
        """Count(call_child) over `shards` in one dispatch, or None when
        the tree isn't coverable (caller falls back)."""
        shards = tuple(shards)
        if len(shards) < MIN_SHARDS:
            return None
        gathered = self._gather(idx, call_child, shards)
        if gathered is None:
            return None
        sig, stacks = gathered
        self.dispatches += 1
        # group-commit execution: concurrent count queries fuse into one
        # program + one result round trip (see _batched_count)
        return self._batched_count(sig, stacks)

    def filter_stack(self, idx, call, shards):
        """Materialize a bitmap call tree as one [S, W] device stack.
        Returns (covered, stack): covered=False means the tree has shapes
        the stacked path can't express (fall back to per-shard);
        stack=None with covered=True means "no filter given"."""
        if call is None:
            return True, None
        shards = tuple(shards)
        gathered = self._gather(idx, call, shards)
        if gathered is None:
            return False, None
        sig, stacks = gathered
        self.dispatches += 1
        fn = self._plane_fn(sig, tuple(c.csig for c in stacks))
        plane_bytes = stacks[0].shape[0] * stacks[0].shape[1] * 4
        with self._locked_dispatch(
                "filter",
                nbytes_in=sum(c.nbytes for c in stacks),
                nbytes_out=plane_bytes, fn=fn) as ph:
            out = fn(*_containers.flatten(stacks))
            ph.mark("dispatch_ack")
            out = _launch_barrier(out)
            ph.mark("sync")
            return True, out

    def row_counts(self, idx, field_name, row_ids, filt, shards,
                   view_name=VIEW_STANDARD):
        """{row_id: exact count of row ∩ filt summed over shards}, in
        O(rows/chunk) dispatches independent of the shard count. `filt` is
        a [S, W] device stack from filter_stack (or None). Returns None
        when the field/view vanished mid-query."""
        shards = tuple(shards)
        out = {}
        chunk_size = self.row_chunk_size(shards)
        # Oversized candidate sets can't all stay resident: build those
        # chunks transiently instead of churning out every cached chunk.
        total_bytes = (len(row_ids) * self._padded_len(shards)
                       * WORDS_PER_ROW * 4)
        cache = total_bytes <= MAX_ROWS_STACK_BYTES
        fn = self._row_counts_fn(filt is not None)
        pending = []
        import jax

        for i in range(0, len(row_ids), chunk_size):
            chunk = tuple(row_ids[i:i + chunk_size])
            stack = self.rows_stack(idx, field_name, chunk, shards,
                                    view_name, cache=cache)
            if stack is None:
                return None
            self.dispatches += 1
            n_in = stack.size * 4 + (filt.size * 4 if filt is not None
                                     else 0)
            with self._locked_dispatch("row_counts", nbytes_in=n_in,
                                       fn=fn) as ph:
                hi_lo = fn(stack, filt) if filt is not None else fn(stack)
                ph.mark("dispatch_ack")
                _launch_barrier(hi_lo)
                if not cache:
                    # Transient chunks: block before building the next one
                    # so peak HBM stays ~CHUNK_BYTES instead of the whole
                    # candidate set queued in flight.
                    jax.block_until_ready(hi_lo)
                ph.mark("sync")
            pending.append((chunk, hi_lo))
        # ONE amortized fetch for every chunk's (hi, lo) pair — shared
        # with concurrently-serving queries via the group commit
        flat = tuple(a for _, hl in pending for a in hl)
        if flat:
            vals = self._fetch_commit.submit(flat, _device_get_batch)
            for k, (chunk, _) in enumerate(pending):
                totals = combine_hi_lo(vals[2 * k], vals[2 * k + 1])
                for j, row_id in enumerate(chunk):
                    out[row_id] = int(totals[j])
        return out

    def pairwise_counts(self, idx, a_field, a_rows, b_field, b_rows, filt,
                        shards, view_name=VIEW_STANDARD, tile=None):
        """{(a_row, b_row): count > 0} of the two-field GroupBy cross
        product: counts[i, j] = popcount(a_rows[i] & b_rows[j] & filt)
        summed over `shards`. Both fields' row stacks come from the rows
        pool ([R, S, W], incrementally patched like any chunk); the
        [tile, tile] count matrix is ONE fused dispatch and ONE host sync
        per (A-tile, B-tile) pair — O(⌈R1/tile⌉·⌈R2/tile⌉) round trips
        total, vs the recursive path's one `row_counts` sync per A row.
        The sync rides the group commit, so concurrent GroupBys (and any
        Sum/Min/Max traffic) share round trips. `tile` overrides the
        static CHUNK_BYTES-derived shape (the adaptive tile decision);
        per-dispatch walls feed back into the engine's per-tile EWMA.
        Returns None when a field/view vanished mid-query (caller falls
        back)."""
        shards = tuple(shards)
        out = {}
        if not a_rows or not b_rows:
            return out
        if tile is None or tile < 1:
            tile = self.row_chunk_size(shards)
        observe = _adaptive.enabled()
        row_bytes = self._padded_len(shards) * WORDS_PER_ROW * 4
        cache_a = len(a_rows) * row_bytes <= MAX_ROWS_STACK_BYTES
        cache_b = len(b_rows) * row_bytes <= MAX_ROWS_STACK_BYTES
        import jax

        for i in range(0, len(a_rows), tile):
            a_chunk = tuple(a_rows[i:i + tile])
            a_stack = self.rows_stack(idx, a_field, a_chunk, shards,
                                      view_name, cache=cache_a)
            if a_stack is None:
                return None
            for j in range(0, len(b_rows), tile):
                b_chunk = tuple(b_rows[j:j + tile])
                b_stack = self.rows_stack(idx, b_field, b_chunk, shards,
                                          view_name, cache=cache_b)
                if b_stack is None:
                    return None
                self.dispatches += 1
                self.pairwise_dispatches += 1
                n_in = (a_stack.size + b_stack.size
                        + (filt.size if filt is not None else 0)) * 4
                t_disp = time.perf_counter() if observe else 0.0
                with self._locked_dispatch(
                        "pairwise", nbytes_in=n_in,
                        nbytes_out=len(a_chunk) * len(b_chunk) * 8) as ph:
                    hi, lo = bitplane.pairwise_counts_hi_lo(
                        a_stack, b_stack, filt)
                    ph.mark("dispatch_ack")
                    _launch_barrier((hi, lo))
                    if not (cache_a and cache_b):
                        # Transient tiles: bound peak HBM before the next
                        # pair (same discipline as row_counts).
                        jax.block_until_ready((hi, lo))
                    ph.mark("sync")
                if observe:
                    # calibrate per-dispatch wall at the NOMINAL tile —
                    # ragged last tiles blend in, which is fine: the
                    # model prices whole shapes, not individual tiles
                    _adaptive.observe_pairwise(
                        tile, time.perf_counter() - t_disp)
                # ONE host sync for the whole [tile, tile] matrix, shared
                # with concurrent serving traffic via the group commit
                vals = self._fetch_commit.submit((hi, lo),
                                                 _device_get_batch)
                self.pairwise_syncs += 1
                totals = combine_hi_lo(vals[0], vals[1])
                for x, y in zip(*np.nonzero(totals)):
                    out[(a_chunk[x], b_chunk[y])] = int(totals[x, y])
        return out

    def try_sum(self, idx, field, filter_call, shards):
        """(signed magnitude total, count) for Sum over stacked BSI planes,
        or None to fall back. The caller adds base*count (field.go:1583)."""
        shards = tuple(shards)
        if len(shards) < MIN_SHARDS:
            return None
        covered, filt = self.filter_stack(idx, filter_call, shards)
        if not covered:
            return None
        data = self.bsi_stack(idx, field.name, shards)
        if data is None:
            return None
        planes, sign, exists = data
        fn = self._sum_fn(filt is not None)
        self.dispatches += 1
        n_in = (planes.size + sign.size + exists.size
                + (filt.size if filt is not None else 0)) * 4
        with self._locked_dispatch("sum", nbytes_in=n_in, fn=fn) as ph:
            if filt is not None:
                res = fn(planes, sign, exists, filt)
            else:
                res = fn(planes, sign, exists)
            ph.mark("dispatch_ack")
            _launch_barrier(res)
            ph.mark("sync")
        p_hi, p_lo, n_hi, n_lo, c_hi, c_lo = \
            self._fetch_commit.submit(tuple(res), _device_get_batch)
        pos = combine_hi_lo(p_hi, p_lo)
        neg = combine_hi_lo(n_hi, n_lo)
        total = 0
        for i in range(planes.shape[0]):
            total += (int(pos[i]) - int(neg[i])) << i
        return total, combine_hi_lo(c_hi, c_lo)

    def try_minmax(self, idx, field, filter_call, shards, is_max):
        """(signed magnitude, count) of the Min/Max value over stacked BSI
        planes, or None to fall back; (None, 0) when no column qualifies.
        The caller adds base (reference: fragment.go:1110-1227)."""
        shards = tuple(shards)
        if len(shards) < MIN_SHARDS:
            return None
        covered, filt = self.filter_stack(idx, filter_call, shards)
        if not covered:
            return None
        data = self.bsi_stack(idx, field.name, shards)
        if data is None:
            return None
        planes, sign, exists = data
        fn = self._minmax_fn(filt is not None, is_max)
        self.dispatches += 1
        n_in = (planes.size + sign.size + exists.size
                + (filt.size if filt is not None else 0)) * 4
        with self._locked_dispatch("minmax", nbytes_in=n_in, fn=fn) as ph:
            if filt is not None:
                res = fn(planes, sign, exists, filt)
            else:
                res = fn(planes, sign, exists)
            ph.mark("dispatch_ack")
            _launch_barrier(res)
            ph.mark("sync")
        # amortized result fetch (group commit, like try_sum)
        empty, use_neg, bits, c_hi, c_lo = \
            self._fetch_commit.submit(tuple(res), _device_get_batch)
        if bool(empty):
            return None, 0
        bits = np.asarray(bits)
        mag = sum(int(b) << i for i, b in enumerate(bits))
        if bool(use_neg):
            mag = -mag
        return mag, combine_hi_lo(c_hi, c_lo)

    def counters(self):
        """(dispatches, hits, misses, planes_uploaded) — the per-query
        delta source for the always-on workload table. A bare tuple read
        instead of the full cache_stats() dict: this runs twice per
        query, and the workload_overhead bench gates the sum at <2% of
        query wall."""
        with self._lock:
            return (self.dispatches, self.hits, self.misses,
                    self.planes_uploaded)

    def cache_stats(self):
        """Snapshot for /debug/vars: hit rate and byte pressure reveal
        whether the HBM budgets (MAX_STACK_BYTES / MAX_ROWS_STACK_BYTES)
        are thrashing under the live workload."""
        with self._lock:
            return {
                "hits": self.hits,
                "misses": self.misses,
                "evictions": self.evictions,
                "patches": self.patches,
                "stale_serves": self.stale_serves,
                "planes_uploaded": self.planes_uploaded,
                "dispatches": self.dispatches,
                "pairwise_dispatches": self.pairwise_dispatches,
                "pairwise_syncs": self.pairwise_syncs,
                "group_fetches": self._fetch_commit.batches,
                "group_fetched_queries": self._fetch_commit.batched,
                "count_batches": self._count_commit.batches,
                "count_batched_queries": self._count_commit.batched,
                "batch_dispatches": self.batch_dispatches,
                "batched_queries": self.batched_queries,
                "fused_dispatches": self.fused_dispatches,
                "stack_bytes": self._stack_bytes,
                "stack_entries": len(self._stacks),
                "rows_stack_bytes": self._rows_stack_bytes,
                "rows_stack_entries": len(self._rows_stacks),
                "evictions_by_cause": {
                    f"{p}.{c}": n
                    for (p, c), n in sorted(self.pool_evictions.items())},
            }

    def invalidate(self):
        with self._lock:
            n_stack = len(self._stacks)
            n_rows = len(self._rows_stacks)
            self._stacks.clear()
            self._stack_bytes = 0
            self._rows_stacks.clear()
            self._rows_stack_bytes = 0
            # zero (don't drop) the gauges: a scraper must see the flush
            for (index, field, pool_name, repr_kind) in list(self._hbm_ledger):
                global_stats.gauge("hbm_stack_bytes", 0, {
                    "index": index, "field": field, "pool": pool_name,
                    "repr": repr_kind})
            self._hbm_ledger.clear()
            if n_stack:
                self._count_eviction("stack", "invalidate", n_stack)
            if n_rows:
                self._count_eviction("rows", "invalidate", n_rows)
        if n_stack or n_rows:
            _flightrec.record("cache.invalidate", stack_entries=n_stack,
                              rows_entries=n_rows)

    # -- HBM / kernel attribution (GET /debug/hbm, /debug/kernels) -----------

    def hbm_snapshot(self, top=50):
        """What is resident in HBM and for whom: per-(index, field, pool)
        byte attribution, the resident entries ranked by bytes with
        last-hit age, eviction causes, and headroom vs the device's own
        memory_stats(). `total_bytes` is EXACTLY
        _stack_bytes + _rows_stack_bytes (the ledger moves in lockstep
        under the same lock — the acceptance stress test asserts it)."""
        now = time.time()
        entries = []
        with self._lock:
            for pool_name, pool in (("stack", self._stacks),
                                    ("rows", self._rows_stacks)):
                for key, entry in pool.items():
                    e = {
                        "pool": pool_name,
                        "kind": key[0],
                        "index": key[1],
                        "field": key[2],
                        "bytes": entry[2],
                        "repr": _containers.kind_of(entry[1]),
                        "last_hit_age_seconds": round(now - entry[4], 3),
                        "key": repr(key),
                    }
                    if isinstance(entry[1], _containers.Container):
                        ratio = entry[1].meta.get("ratio")
                        if ratio is not None:
                            e["compression_ratio"] = ratio
                    entries.append(e)
            # aggregate the repr-keyed ledger back to (index, field,
            # pool) for by_index_field consumers (the /debug/heat join
            # keys on index+field), and expose the repr split + the
            # per-representation totals alongside
            agg = {}
            by_repr = {}
            for (i, f, p, r), b in self._hbm_ledger.items():
                agg[(i, f, p)] = agg.get((i, f, p), 0) + b
                by_repr[r] = by_repr.get(r, 0) + b
            by_index_field = [
                {"index": i, "field": f, "pool": p, "bytes": b}
                for (i, f, p), b in sorted(
                    agg.items(), key=lambda kv: -kv[1])]
            by_index_field_repr = [
                {"index": i, "field": f, "pool": p, "repr": r, "bytes": b}
                for (i, f, p, r), b in sorted(
                    self._hbm_ledger.items(), key=lambda kv: -kv[1])]
            snap = {
                "total_bytes": self._stack_bytes + self._rows_stack_bytes,
                "stack_bytes": self._stack_bytes,
                "stack_entries": len(self._stacks),
                "stack_budget_bytes": MAX_STACK_BYTES,
                "rows_stack_bytes": self._rows_stack_bytes,
                "rows_stack_entries": len(self._rows_stacks),
                "rows_stack_budget_bytes": MAX_ROWS_STACK_BYTES,
                "by_index_field": by_index_field,
                "by_index_field_repr": by_index_field_repr,
                "by_repr": by_repr,
                "container_fragments": _containers.fragment_ledger(),
                "evictions": {
                    f"{p}.{c}": n
                    for (p, c), n in sorted(self.pool_evictions.items())},
            }
        entries.sort(key=lambda e: -e["bytes"])
        snap["entries"] = entries[:top]
        snap["device_memory"] = self._device_memory()
        return snap

    def _device_memory(self):
        """Per-device memory_stats() headroom, with the RuntimeMonitor
        guard: NEVER initializes a backend (jax absent or uninitialized
        -> None), and backends without memory_stats report nothing."""
        import sys

        jax_mod = sys.modules.get("jax")
        if jax_mod is None:
            return None
        try:
            from jax._src import xla_bridge

            if not xla_bridge.backends_are_initialized():
                return None
            out = []
            for d in jax_mod.local_devices():
                ms = getattr(d, "memory_stats", None)
                stats = ms() if callable(ms) else None
                if not stats:
                    continue
                in_use = stats.get("bytes_in_use")
                limit = stats.get("bytes_limit")
                dev = {"device": str(d.id), "platform": d.platform}
                if in_use is not None:
                    dev["bytes_in_use"] = int(in_use)
                if limit is not None:
                    dev["bytes_limit"] = int(limit)
                    if in_use is not None:
                        dev["headroom_bytes"] = int(limit) - int(in_use)
                out.append(dev)
            return out or None
        except Exception:  # noqa: BLE001 — observability must not raise
            return None

    def kernels_snapshot(self, include_costs=True):
        """Per-kernel-family attribution (counts, wall seconds, bytes
        in/out from _locked_dispatch) plus XLA cost_analysis (flops /
        bytes accessed) per compiled program — computed ONCE per fn on
        the first /debug/kernels request, never on the serving path."""
        with self._lock:
            kernels = {k: dict(v) for k, v in self._kernels.items()}
        snap = {"kernels": kernels}
        if include_costs:
            snap["compiled"] = self._kernel_cost_list()
        return snap

    def _kernel_cost_list(self):
        with self._lock:
            specs = dict(self._fn_specs)
            fns = dict(self._fns)
        out = []
        for key, spec in specs.items():
            cost = self._kernel_costs.get(key)
            if cost is None:
                cost = self._cost_analysis(fns.get(key), spec)
                with self._lock:
                    self._kernel_costs[key] = cost
            out.append({"family": str(key[0]), "key": repr(key),
                        "cost": cost})
        out.sort(key=lambda e: e["key"])
        return out

    @staticmethod
    def _cost_analysis(fn, specs):
        """XLA's own flops/bytes estimate for one compiled program, or {}
        when the backend/version doesn't expose it. Best effort by
        design: attribution must never take the serving path down."""
        if fn is None or not specs:
            return {}
        target = getattr(fn, "_jit_fn", fn)
        try:
            cost = target.lower(*specs).compile().cost_analysis()
        except Exception:  # noqa: BLE001 — backend-dependent API
            return {}
        if isinstance(cost, (list, tuple)):
            cost = cost[0] if cost else {}
        if not isinstance(cost, dict):
            return {}
        keep = {k: cost[k]
                for k in ("flops", "bytes accessed", "optimal_seconds",
                          "transcendentals")
                if isinstance(cost.get(k), (int, float))}
        if keep:
            return keep
        numeric = [(k, v) for k, v in sorted(cost.items())
                   if isinstance(v, (int, float))]
        return dict(numeric[:8])

    # -- plan-mode introspection (exec/plan.py) ------------------------------
    #
    # EXPLAIN mirrors the strategy gates WITHOUT executing: everything
    # below is host-only (schema lookups, fragment generation walks, pool
    # membership under the lock) and side-effect free — no LRU bumps, no
    # hit/miss counters, no stack builds, no dispatches. The acceptance
    # contract for ?explain=true is a dispatch-counter delta of zero.

    def _probe(self, key, idx, field_name, view_name):
        """Presence + freshness of one pool entry with NO side effects
        (see _probe_entry)."""
        return self._probe_entry(key, idx, field_name, view_name)[0]

    def _probe_entry(self, key, idx, field_name, view_name):
        """(resident, resident_bytes, repr) of one pool entry with NO
        side effects. Mirrors _cache_get_fast/_cache_get validation
        (view stamp first, per-shard generation walk second) but never
        touches LRU order, last-hit stamps, or the hit/miss counters —
        a plan must not distort the telemetry it is trying to explain.
        bytes/repr are the RESIDENT entry's (compressed container bytes
        for compressed leaf stacks); (0, "dense") when absent."""
        field = idx.field(field_name)
        view = field.view(view_name) if field is not None else None
        if view is None:
            return False, 0, "dense"
        pool, _ = self._pool(key)
        with self._lock:
            hit = pool.get(key)
            if hit is None:
                return False, 0, "dense"
            if hit[3] == (view.uid, view.mutations):
                return True, hit[2], _containers.kind_of(hit[1])
        # stamp drifted: fall back to the exact generation walk (done
        # outside the pool lock — it touches fragment containers)
        gens = self._fragment_gens(idx, field_name, key[-1], view_name,
                                   view=view)
        if gens is None:
            return False, 0, "dense"
        with self._lock:
            hit = pool.get(key)
            if hit is not None and hit[0] == gens:
                return True, hit[2], _containers.kind_of(hit[1])
            return False, 0, "dense"

    def rows_chunk_resident(self, idx, field_name, row_chunk, shards,
                            view_name=VIEW_STANDARD):
        """Would rows_stack() serve this chunk from the rows pool?"""
        key = ("rows", idx.name, field_name, view_name, tuple(row_chunk),
               tuple(shards))
        return self._probe(key, idx, field_name, view_name)

    def bsi_stack_resident(self, idx, field_name, shards):
        """Would bsi_stack() serve this field's plane stack from HBM?"""
        field = idx.field(field_name)
        if field is None:
            return False
        key = ("bsi", idx.name, field_name, field.options.bit_depth,
               tuple(shards))
        return self._probe(key, idx, field_name, field.bsi_view_name())

    def residency_probe(self, idx, call, shards):
        """Host-only coverage + HBM residency of a bitmap call tree:

        {covered, leaves, resident, resident_bytes, missing_bytes,
         extra_kernels}

        covered mirrors _gather's verdict (same signature walk); per
        interned leaf the probe reports whether its device stack(s) are
        already resident and how many bytes a cold build would upload.
        extra_kernels counts dispatches _gather itself would issue on
        top of the consumer's own kernel (bsi_condition masks,
        time_union folds) so estimates don't undercount BSI/time trees."""
        shards = tuple(shards)
        out = {"covered": False, "leaves": 0, "resident": 0,
               "resident_bytes": 0, "missing_bytes": 0,
               "extra_kernels": {}, "repr_counts": {},
               "compressed_bytes": 0}
        leaves = {}
        sig = self.signature(idx, call, leaves)
        if sig is None or not leaves:
            return out
        out["covered"] = True
        out["leaves"] = len(leaves)
        plane = self._padded_len(shards) * WORDS_PER_ROW * 4
        for key in leaves:
            # per-leaf representation + compressed-bytes estimate for
            # the cost model: actual container bytes when resident, the
            # fragment ledger's last-build record when not (the chooser
            # is deterministic in the data, so the last build predicts
            # the next), dense otherwise. resident/missing_bytes keep
            # their dense meaning — they price the HOST gather a cold
            # build pays, which is dense either way.
            ckind, cbytes = "dense", None
            if key[0] == "bsicond":
                resident, nbytes = self._probe_bsicond(idx, key, shards,
                                                       plane, out)
            elif key[0] == "timerow":
                resident, nbytes = self._probe_timerow(idx, key, shards,
                                                       plane, out)
            else:
                _, field_name, row_id = key
                leaf_key = ("leaf", idx.name, field_name, row_id, shards)
                resident, ebytes, ekind = self._probe_entry(
                    leaf_key, idx, field_name, VIEW_STANDARD)
                nbytes = plane
                if resident:
                    ckind, cbytes = ekind, ebytes
                else:
                    est = _containers.fragment_estimate(
                        idx.name, field_name, VIEW_STANDARD, row_id)
                    if est is not None:
                        ckind, cbytes = est["repr"], est["bytes"]
            rc = out["repr_counts"]
            rc[ckind] = rc.get(ckind, 0) + 1
            out["compressed_bytes"] += cbytes if cbytes is not None \
                else nbytes
            if resident:
                out["resident"] += 1
                out["resident_bytes"] += nbytes
            else:
                out["missing_bytes"] += nbytes
        return out

    def _probe_bsicond(self, idx, key, shards, plane, out):
        """(resident, cold_bytes) of one condition leaf; counts the
        bsi_condition dispatch the gather would add."""
        from .bsicond import (
            BsiConditionError,
            bsi_condition_plan,
            condition_from_key,
        )

        _, field_name, op, vals = key
        field = idx.field(field_name)
        if field is None:
            return False, 0
        try:
            plan = bsi_condition_plan(
                field.options, condition_from_key(op, vals))
        except BsiConditionError:
            return False, 0
        if plan[0] == "empty":
            return True, 0  # constant zeros, nothing uploaded
        if plan[0] == "notnull":
            return self.rows_chunk_resident(
                idx, field_name, (BSI_EXISTS_BIT,), shards,
                view_name=field.bsi_view_name()), plane
        ek = out["extra_kernels"]
        ek["bsi_condition"] = ek.get("bsi_condition", 0) + 1
        depth = field.options.bit_depth
        return (self.bsi_stack_resident(idx, field_name, shards),
                (depth + 2) * plane)

    def _probe_timerow(self, idx, key, shards, plane, out):
        """(resident, cold_bytes) of one time-range leaf: one cached
        single-row chunk per locally-present quantum view, plus a
        time_union dispatch when more than one contributes."""
        _, field_name, row_id, views = key
        field = idx.field(field_name)
        if field is None:
            return False, 0
        present = [v for v in views if field.view(v) is not None]
        if len(present) > 1:
            ek = out["extra_kernels"]
            ek["time_union"] = ek.get("time_union", 0) + 1
        resident = all(
            self.rows_chunk_resident(idx, field_name, (row_id,), shards,
                                     view_name=v)
            for v in present)
        return resident, len(present) * plane

    def kernel_profile(self):
        """Per-family dispatch counters snapshot ({family: {count,
        seconds, bytes_in, bytes_out}}) — the cost model's "measured"
        source and the analyze path's before/after delta basis."""
        with self._lock:
            return {k: dict(v) for k, v in self._kernels.items()}


# Backwards-compatible name (the evaluator originally covered Count only).
StackedCountEvaluator = StackedEvaluator
