"""Stacked Count fast path.

The general executor evaluates a bitmap call tree shard by shard — correct
for every call, but each shard costs several device dispatches. For the
serving-critical shape — Count over a tree of Row leaves combined with
Intersect/Union/Difference/Xor/Not (the north-star query,
executor.go:1665/1790) — this module evaluates ALL shards in ONE fused XLA
dispatch: each leaf row becomes a [shards, words] stacked plane resident on
device, the tree becomes a single jitted elementwise+popcount+reduce
program, and the per-query work is one dispatch and one scalar sync.

Stacks are cached per (index, field, row, shard-set) and invalidated by the
fragments' write-generation counters (fragment.generation — bumped by every
mutation), so a stale stack can never serve a query. LRU-bounded: at
SHARD_WIDTH=2^20 a 954-shard stack is ~120 MB of HBM, so only the hottest
rows stay resident (the device analog of fragment.rowCache
fragment.go:367).

On a multi-device host the stacks are placed sharded over a 1-D "shards"
mesh (zero-padded to a device multiple — zero rows are count-neutral for
every supported op chain), so the SAME jitted count program is GSPMD
partitioned by XLA: per-device popcounts reduce over ICI instead of one
chip doing all the work (SURVEY §2 parallelism: the shard axis is the one
SPMD axis).
"""

import threading
from collections import OrderedDict

import numpy as np

from ..core.index import EXISTENCE_FIELD_NAME
from ..core.view import VIEW_STANDARD
from ..shardwidth import WORDS_PER_ROW

# Device-byte budget for cached stacks; excess evicts least-recently-used.
# (Entry size scales with shard count — ~120 MB per 954-shard stack — so a
# count bound alone could pin several GB of HBM.)
MAX_STACK_BYTES = 512 * 1024 * 1024
# Compiled tree programs are tiny but unbounded shapes would accumulate.
MAX_FNS = 128
# Below this many shards the per-shard path's dispatch count is too small
# to matter.
MIN_SHARDS = 2

_OPS = {"Intersect": "&", "Union": "|", "Difference": "-", "Xor": "^"}

_UNSET = object()


class StackedCountEvaluator:
    def __init__(self):
        self._stacks = OrderedDict()  # key -> (gens, device stack, nbytes)
        self._stack_bytes = 0
        self._fns = OrderedDict()     # tree signature -> jitted fn
        self._lock = threading.Lock()
        self._sharding = _UNSET

    def _stack_sharding(self):
        """NamedSharding over all local devices (None on a single device),
        resolved lazily so importing this module never touches the
        backend."""
        if self._sharding is _UNSET:
            import jax

            # local_devices: host-local numpy stacks can't be placed onto
            # other processes' chips; cross-host scale-out is the cluster
            # layer's job (shards_by_node), not this cache's.
            devices = jax.local_devices()
            if len(devices) < 2:
                self._sharding = None
            else:
                mesh = jax.sharding.Mesh(np.array(devices), ("shards",))
                self._sharding = jax.sharding.NamedSharding(
                    mesh, jax.sharding.PartitionSpec("shards"))
        return self._sharding

    # -- tree analysis -------------------------------------------------------

    def _leaf(self, idx, field_name, row_id, leaves):
        field = idx.field(field_name)
        if field is None or field.view(VIEW_STANDARD) is None:
            return None
        key = (field_name, int(row_id))
        if key not in leaves:
            leaves[key] = len(leaves)
        return ("leaf", leaves[key])

    def signature(self, idx, call, leaves):
        """Tree signature with leaf slots, or None when the tree has any
        shape the fast path doesn't cover (conditions, time ranges, Shift,
        keys...). None means: use the general per-shard path."""
        name = call.name
        if name in ("Row", "Range"):
            if call.has_conditions() or "from" in call.args \
                    or "to" in call.args:
                return None
            field_name = call.field_arg()
            if field_name is None:
                return None
            row_id = call.args.get(field_name)
            if isinstance(row_id, bool):
                row_id = int(row_id)
            if not isinstance(row_id, int):
                return None
            return self._leaf(idx, field_name, row_id, leaves)
        if name in _OPS and call.children:
            subs = tuple(self.signature(idx, c, leaves)
                         for c in call.children)
            if any(s is None for s in subs):
                return None
            return (_OPS[name], subs)
        if name == "Not" and len(call.children) == 1 \
                and idx.options.track_existence \
                and idx.field(EXISTENCE_FIELD_NAME) is not None:
            child = self.signature(idx, call.children[0], leaves)
            if child is None:
                return None
            exists = self._leaf(idx, EXISTENCE_FIELD_NAME, 0, leaves)
            if exists is None:
                return None
            return ("-", (exists, child))
        return None

    # -- stacks --------------------------------------------------------------

    def _fragment_gens(self, idx, field_name, shards):
        """Cache-validation fingerprint: per-shard (fragment uid,
        generation). The uid makes a recreated fragment (field dropped and
        re-made at the same path) distinct from its predecessor even when
        the generation counters collide. None when the field vanished
        (concurrent DDL) — caller falls back to the general path."""
        field = idx.field(field_name)
        view = field.view(VIEW_STANDARD) if field is not None else None
        if view is None:
            return None
        gens = []
        for shard in shards:
            frag = view.fragment(shard)
            gens.append((-1, -1) if frag is None
                        else (frag.uid, frag.generation))
        return tuple(gens)

    def _stack(self, idx, field_name, row_id, shards):
        import jax.numpy as jnp

        key = (idx.name, field_name, row_id, shards)
        gens = self._fragment_gens(idx, field_name, shards)
        if gens is None:
            return None
        with self._lock:
            hit = self._stacks.get(key)
            if hit is not None and hit[0] == gens:
                self._stacks.move_to_end(key)
                return hit[1]
        field = idx.field(field_name)
        view = field.view(VIEW_STANDARD) if field is not None else None
        if view is None:
            return None
        import jax

        rows = []
        zeros = None
        for shard in shards:
            frag = view.fragment(shard)
            plane = None if frag is None else frag.row_plane(row_id)
            if plane is None:
                if zeros is None:
                    zeros = np.zeros(WORDS_PER_ROW, dtype=np.uint32)
                plane = zeros
            rows.append(np.asarray(plane))
        sharding = self._stack_sharding()
        if sharding is not None:
            # zero-pad to a device multiple; zero rows are count-neutral
            n_dev = len(sharding.device_set)
            pad = (-len(rows)) % n_dev
            if pad:
                if zeros is None:
                    zeros = np.zeros(WORDS_PER_ROW, dtype=np.uint32)
                rows.extend([zeros] * pad)
            stack = jax.device_put(np.stack(rows), sharding)
        else:
            stack = jnp.asarray(np.stack(rows))
        nbytes = len(rows) * WORDS_PER_ROW * 4
        with self._lock:
            old = self._stacks.pop(key, None)
            if old is not None:
                self._stack_bytes -= old[2]
            self._stacks[key] = (gens, stack, nbytes)
            self._stack_bytes += nbytes
            while self._stack_bytes > MAX_STACK_BYTES and len(self._stacks) > 1:
                _, evicted = self._stacks.popitem(last=False)
                self._stack_bytes -= evicted[2]
        return stack

    # -- compiled tree evaluation -------------------------------------------

    def _fn(self, sig, arity):
        import jax
        import jax.numpy as jnp

        with self._lock:
            fn = self._fns.get((sig, arity))
            if fn is not None:
                self._fns.move_to_end((sig, arity))
        if fn is None:
            def ev(node, stacks):
                if node[0] == "leaf":
                    return stacks[node[1]]
                op, subs = node
                acc = ev(subs[0], stacks)
                for s in subs[1:]:
                    p = ev(s, stacks)
                    if op == "&":
                        acc = acc & p
                    elif op == "|":
                        acc = acc | p
                    elif op == "^":
                        acc = acc ^ p
                    else:
                        acc = acc & ~p
                return acc

            @jax.jit
            def fn(*stacks):
                # int32 accumulate matches the other count kernels (safe:
                # a count never exceeds the <2^31 column universe served
                # per node; see bench.py)
                acc = ev(sig, stacks)
                return jnp.sum(
                    jax.lax.population_count(acc).astype(jnp.int32))

            with self._lock:
                self._fns[(sig, arity)] = fn
                while len(self._fns) > MAX_FNS:
                    self._fns.popitem(last=False)
        return fn

    # -- entry ---------------------------------------------------------------

    def try_count(self, idx, call_child, shards):
        """Count(call_child) over `shards` in one dispatch, or None when
        the tree isn't coverable (caller falls back)."""
        shards = tuple(shards)
        if len(shards) < MIN_SHARDS:
            return None
        leaves = {}
        sig = self.signature(idx, call_child, leaves)
        if sig is None or not leaves:
            return None
        ordered = sorted(leaves.items(), key=lambda kv: kv[1])
        stacks = [self._stack(idx, f, r, shards) for (f, r), _ in ordered]
        if any(s is None for s in stacks):
            return None  # concurrent DDL: fall back to the general path
        return int(self._fn(sig, len(stacks))(*stacks))

    def invalidate(self):
        with self._lock:
            self._stacks.clear()
            self._stack_bytes = 0
