"""PQL EXPLAIN/ANALYZE: cost-model-backed plan introspection.

The runtime observability stack (profiles, histograms, the flight
recorder, the HBM/kernel ledgers) answers "what happened"; this module
answers "what WILL happen and why" — which execution strategy the
executor will pick for each PQL call (stacked-kernel dispatch vs.
per-shard host fallback), the pairwise GroupBy tiling shape, how much of
the working set is already resident in HBM, and what each node should
cost. In the spirit of SQL `EXPLAIN ANALYZE`:

- `?explain=true|plan` builds the plan tree WITHOUT executing anything:
  the planner mirrors every strategy gate in exec/executor.py using only
  host-side work (signature walks, fragment metadata, cache-residency
  probes) — the acceptance contract is a stacked dispatch-counter delta
  of exactly zero.
- `?explain=analyze` executes the query and grafts actuals onto each
  top-level plan node: wall clock, kernel wall (from the per-family
  `_locked_dispatch` ledger), dispatch/pairwise counters, upload bytes,
  and the strategy the executor ACTUALLY took (recorded at each decision
  point). Nodes whose actual cost deviates from the estimate by more
  than `misestimate_factor()` (default 3x, either direction) are
  flagged, counted in `explain_misestimates_total{op}`, and the whole
  plan is retained in the `/debug/plans` ring alongside /debug/queries.

The cost model prices a dispatch of kernel family F from the best
available source, in order: the evaluator's own measured per-family
means (exec/stacked._kernels), the `kernel_seconds{kernel}` histograms
in the global stats registry (survive evaluator replacement), XLA
cost_analysis `optimal_seconds` for an ALREADY-compiled program of the
family (the plan path never triggers a compile), and finally a fixed
cold-process default. Every estimate carries its source so a reader
knows how much to trust it.
"""

import threading
from collections import OrderedDict

from ..shardwidth import WORDS_PER_ROW
from ..utils.stats import global_stats

#: retained (misestimated) plans, newest first on read
DEFAULT_PLAN_RING = 128
#: estimate-vs-actual deviation (either direction) that flags a node
DEFAULT_MISESTIMATE_FACTOR = 3.0
#: per-dispatch wall fallback for a cold process with no kernel history
#: and no cached cost_analysis — the order of magnitude of a small fused
#: popcount dispatch on the CPU backend; real measurements replace it
#: after the first queries.
DEFAULT_DISPATCH_SECONDS = 2e-3

#: comparison floors: below these, estimate-vs-actual ratios are noise
#: (timer jitter, a single warm-up dispatch) and must not flag
WALL_FLOOR_SECONDS = 2e-3
DISPATCH_FLOOR = 1.0
BYTES_FLOOR = 1 << 16

_lock = threading.Lock()
#: retained plans keyed by workload fingerprint (or a per-record
#: sequence number when none is known): one HOT mis-modeled shape keeps
#: ONE slot — latest plan + repeat count — instead of evicting every
#: other entry from the ring
_ring = OrderedDict()
_ring_max = DEFAULT_PLAN_RING
_anon_seq = 0
_local = threading.local()
_misestimate_factor = DEFAULT_MISESTIMATE_FACTOR
_misestimates_flagged = 0  # cumulative, for the observability roll-up
_repeats_collapsed = 0     # re-records absorbed by fingerprint dedupe
#: --coalesce-window (seconds); > 0 means serving folds concurrent
#: batchable queries into fused vmapped dispatches, and plans annotate
#: the batched strategy
_coalesce_window = 0.0


def configure(ring_size=None, misestimate_factor=None,
              coalesce_window=None):
    """Apply --plan-ring-size / --explain-misestimate-factor /
    --coalesce-window. Resizing keeps the newest entries (ring
    semantics). The coalesce window lets plans annotate the batched
    dispatch strategy (EXPLAIN shows what serving would do)."""
    global _ring_max, _misestimate_factor, _coalesce_window
    with _lock:
        if ring_size is not None:
            _ring_max = max(1, int(ring_size))
            while len(_ring) > _ring_max:
                _ring.popitem(last=False)
        if misestimate_factor is not None:
            _misestimate_factor = float(misestimate_factor)
        if coalesce_window is not None:
            _coalesce_window = float(coalesce_window)


def misestimate_factor():
    return _misestimate_factor


def coalesce_window():
    return _coalesce_window


def record(plan, fingerprint=None):
    """Retain one (misestimated) plan dict in the /debug/plans ring.
    With a fingerprint, a repeat replaces that shape's slot (latest plan
    wins, `repeat_count` accumulates); without one the entry is
    standalone."""
    global _anon_seq, _repeats_collapsed
    with _lock:
        if fingerprint is None:
            _anon_seq += 1
            key = f"#{_anon_seq}"
        else:
            key = fingerprint
        old = _ring.pop(key, None)
        entry = dict(plan)
        entry["repeat_count"] = 1 if old is None \
            else old.get("repeat_count", 1) + 1
        if old is not None:
            _repeats_collapsed += 1
        if fingerprint is not None:
            entry["fingerprint"] = fingerprint
        _ring[key] = entry
        while len(_ring) > _ring_max:
            _ring.popitem(last=False)


def recent(limit=None):
    """Retained plans, newest first (GET /debug/plans)."""
    with _lock:
        out = list(_ring.values())
    out.reverse()
    if limit is not None:
        out = out[: max(0, int(limit))]
    return out


def clear_recent():
    global _misestimates_flagged, _repeats_collapsed
    with _lock:
        _ring.clear()
        _misestimates_flagged = 0
        _repeats_collapsed = 0


def stats():
    """Roll-up summary for /status observability."""
    with _lock:
        return {"retained": len(_ring), "ring_size": _ring_max,
                "misestimates_flagged": _misestimates_flagged,
                "repeats_collapsed": _repeats_collapsed,
                "misestimate_factor": _misestimate_factor}


def _count_misestimate(op):
    global _misestimates_flagged
    from ..utils import workload

    global_stats.count("explain_misestimates", 1, {"op": op})
    workload.note_misestimate()  # attribute to the in-flight fingerprint
    with _lock:
        _misestimates_flagged += 1


def stash(plan):
    """Thread-local handoff executor -> HTTP layer (same pattern as
    utils/profile.take_last: the layers share a request thread)."""
    _local.last = plan


def take_last():
    plan = getattr(_local, "last", None)
    _local.last = None
    return plan


# ---------------------------------------------------------------- plan tree


class PlanNode:
    """One node per PQL call. `annotations` hold strategy inputs (shards,
    tile shape, views, cache residency); `estimate` the cost-model
    prediction; `actual` (analyze only) the measured counters; and
    `misestimates` the >factor deviations between the two."""

    __slots__ = ("op", "pql", "strategy", "reason", "fields", "annotations",
                 "estimate", "actual", "misestimates", "children")

    def __init__(self, op, pql="", strategy="", reason="", fields=()):
        self.op = op
        self.pql = pql
        self.strategy = strategy
        self.reason = reason
        self.fields = list(fields)
        self.annotations = {}
        self.estimate = {}
        self.actual = None
        self.misestimates = []
        self.children = []

    def walk(self):
        yield self
        for child in self.children:
            if isinstance(child, PlanNode):
                yield from child.walk()

    def to_dict(self):
        out = {"op": self.op, "strategy": self.strategy}
        if self.pql:
            out["pql"] = self.pql
        if self.reason:
            out["reason"] = self.reason
        if self.fields:
            out["fields"] = list(self.fields)
        if self.annotations:
            out["annotations"] = dict(self.annotations)
        if self.estimate:
            out["estimate"] = dict(self.estimate)
        if self.actual is not None:
            out["actual"] = dict(self.actual)
        if self.misestimates:
            out["misestimates"] = list(self.misestimates)
        # cluster sub-plans arrive as already-serialized dicts
        out["children"] = [c.to_dict() if isinstance(c, PlanNode) else c
                           for c in self.children]
        return out


def envelope(index_name, mode, nodes, shards=None, trace_id=None):
    """The wire shape of a whole plan: one entry per top-level call."""
    out = {"index": index_name, "mode": mode,
           "calls": [n.to_dict() if isinstance(n, PlanNode) else n
                     for n in nodes]}
    if shards is not None:
        out["shards"] = shards
    if trace_id is not None:
        out["traceID"] = trace_id
    mis = sum(len(n.misestimates) for n in nodes
              if isinstance(n, PlanNode))
    if mode == "analyze":
        out["misestimates"] = mis
    return out


def summary(nodes):
    """One-line `op=strategy` summary for SLOW QUERY log lines; `!` marks
    a misestimated node. Accepts PlanNodes or serialized dicts."""
    parts = []
    for n in nodes:
        if isinstance(n, PlanNode):
            op, strat, mis = n.op, n.strategy, bool(n.misestimates)
        else:
            op, strat = n.get("op", "?"), n.get("strategy", "?")
            mis = bool(n.get("misestimates"))
        parts.append(f"{op}={strat}" + ("!" if mis else ""))
    return ",".join(parts)


# ---------------------------------------------------------------- cost model


class CostModel:
    """Per-dispatch wall pricing, best source first:

    1. "measured"  — the evaluator's own per-family means
       (stacked._kernels, updated by every _locked_dispatch)
    2. "histogram" — `kernel_seconds{kernel}` means from the global
       stats registry (survive an evaluator swap / invalidate)
    3. "xla"       — cost_analysis `optimal_seconds` of an
       ALREADY-cached compiled program of the family. Never compiles:
       the explain=plan path must do zero device work.
    4. "default"   — DEFAULT_DISPATCH_SECONDS (cold process)
    """

    def __init__(self, stacked):
        self._stacked = stacked
        self._measured = {}
        if stacked is not None:
            try:
                self._measured = stacked.kernel_profile()
            except Exception:  # pragma: no cover - observability only
                self._measured = {}
        self._hist = self._histogram_means()
        self._xla = self._cached_xla_seconds(stacked)

    @staticmethod
    def _histogram_means():
        out = {}
        for (name, tags), (count, total) in \
                global_stats.timing_summary("kernel_seconds").items():
            family = dict(tags).get("kernel")
            if family and count:
                out[family] = total / count
        return out

    @staticmethod
    def _cached_xla_seconds(stacked):
        """{family: optimal_seconds} from costs ALREADY computed by a
        prior /debug/kernels request — reading must not compile."""
        if stacked is None:
            return {}
        out = {}
        try:
            with stacked._lock:
                costs = dict(stacked._kernel_costs)
        except Exception:  # pragma: no cover
            return {}
        for key, cost in costs.items():
            secs = (cost or {}).get("optimal_seconds")
            if isinstance(secs, (int, float)) and secs > 0:
                family = str(key[0])
                out[family] = max(out.get(family, 0.0), float(secs))
        return out

    def dispatch_seconds(self, family):
        """(seconds, source) for one dispatch of `family`."""
        m = self._measured.get(family)
        if m and m.get("count"):
            return m["seconds"] / m["count"], "measured"
        h = self._hist.get(family)
        if h:
            return h, "histogram"
        x = self._xla.get(family)
        if x:
            return x, "xla"
        return DEFAULT_DISPATCH_SECONDS, "default"

    def price(self, node, kernels):
        """Fill node.estimate's wall from a {family: n_dispatches} map.
        The estimate's source is the WEAKEST source used — one "default"
        family taints the whole number, and the reader should know."""
        rank = {"measured": 0, "histogram": 1, "xla": 2, "default": 3}
        wall = 0.0
        worst = "measured"
        for family, n in kernels.items():
            secs, src = self.dispatch_seconds(family)
            wall += secs * n
            if rank[src] > rank[worst]:
                worst = src
        node.estimate["kernels"] = dict(kernels)
        node.estimate["kernel_wall_seconds"] = round(wall, 6)
        node.estimate["cost_source"] = worst


# ----------------------------------------------------------------- planner


class Planner:
    """Builds the plan tree by mirroring each _exec_* strategy gate in
    exec/executor.py with HOST-ONLY work: signature walks, fragment
    metadata (row_ids / TopN caches), and lock-guarded cache-residency
    probes. It must never call filter_stack/_gather/try_* — those
    materialize device stacks. Keeping the gates in sync with the
    executor is the module's maintenance contract; tests/test_explain.py
    pins plan-vs-actual strategy agreement per op family."""

    def __init__(self, executor):
        self.ex = executor
        self.stacked = executor._stacked
        self.cost = CostModel(executor._stacked)

    # -- entry ---------------------------------------------------------------

    def plan_query(self, idx, calls, shards, opt):
        nodes = [self.plan_call(idx, call, shards, opt) for call in calls]
        self._annotate_fusion(idx, calls, nodes)
        return nodes

    def _annotate_fusion(self, idx, calls, nodes):
        """Whole-plan fusion annotation (host metadata only — the plan
        path's zero-dispatch contract holds): when fusion is enabled
        and every top-level call is a stacked-covered Count, serving
        would trace the whole query into ONE jitted program, so each
        node gains `fused: true` plus the program-cache key status for
        the query's workload fingerprint (cached = a warm program
        exists; uncompiled = the first admitted execution would pay
        the trace+compile)."""
        from ..pql.ast import Query
        from ..utils import workload
        from . import fusion

        if not fusion.enabled() or not calls:
            return
        if any(c.name != "Count" or len(c.children) != 1
               for c in calls):
            return
        if any(n.strategy != "stacked" for n in nodes):
            return
        fp, _ = workload.fingerprint(idx.name, Query(list(calls)))
        status = fusion.cache_status(fp)
        for n in nodes:
            n.annotations["fused"] = True
            n.annotations["fusion_fingerprint"] = fp
            n.annotations["fusion_program"] = status

    def plan_call(self, idx, call, shards, opt):
        handler = {
            "Count": self._plan_count,
            "Sum": self._plan_sum,
            "Min": self._plan_min,
            "Max": self._plan_max,
            "MinRow": self._plan_minmax_row,
            "MaxRow": self._plan_minmax_row,
            "TopN": self._plan_topn,
            "Rows": self._plan_rows,
            "GroupBy": self._plan_group_by,
            "Options": self._plan_options,
        }.get(call.name)
        if handler is not None:
            return handler(idx, call, shards, opt)
        if call.writes():
            return self._plan_write(idx, call, shards, opt)
        return self._plan_bitmap(idx, call, shards, opt)

    # -- shared helpers ------------------------------------------------------

    def _shards(self, idx, shards):
        return list(self.ex._call_shards(idx, shards))

    def _min_shards(self):
        from .stacked import MIN_SHARDS

        return MIN_SHARDS

    def _batch_buckets(self):
        from .stacked import BATCH_BUCKETS

        return BATCH_BUCKETS

    def _plane_bytes(self, shard_tuple):
        return self.stacked._padded_len(shard_tuple) * WORDS_PER_ROW * 4

    def _node(self, call, strategy="", reason=""):
        from ..pql import call_to_pql

        try:
            pql = call_to_pql(call)
        except Exception:
            pql = call.name
        return PlanNode(call.name, pql=pql, strategy=strategy, reason=reason)

    def _coverage(self, idx, call, shard_tuple):
        """Host-only stack-coverage + HBM residency of a bitmap tree."""
        return self.stacked.residency_probe(idx, call, shard_tuple)

    def _tree_size(self, call):
        return 1 + sum(self._tree_size(c) for c in call.children)

    def _adaptive_choice(self, node, op, kernels, shard_list,
                         fallback_strategy):
        """Price the stacked-vs-fallback decision the executor will make
        with the SAME inputs (kernel map + bytes_materialized) and
        annotate it: `chosen_by` + both priced alternatives. With the
        engine acting, a fallback-priced node mirrors the executor —
        strategy flips to the per-shard variant — so plan-vs-actual
        strategy agreement holds under --adaptive on. No-op when the
        engine is off (legacy plans are byte-identical)."""
        from . import adaptive

        if not adaptive.enabled():
            return None
        dec = adaptive.decide_strategy(
            op, kernels, len(shard_list),
            node.estimate.get("bytes_materialized", 0),
            stacked=self.stacked)
        node.annotations["chosen_by"] = dec.chosen_by
        node.annotations["alternatives"] = {
            "stacked_ms": round(dec.est_stacked * 1000, 3),
            "fallback_ms": round(dec.est_fallback * 1000, 3),
            "cost_source": dec.source,
        }
        if dec.act and dec.strategy == "fallback":
            node.strategy = fallback_strategy
            node.reason = "cost-model: fallback priced cheaper"
        return dec

    def _stacked_gate(self, node, idx, filter_call, shard_list):
        """The shared MIN_SHARDS + filter-coverage gate. Returns
        (eligible, probe) and records the blocking reason on the node."""
        if len(shard_list) < self._min_shards():
            node.reason = (f"{len(shard_list)} shard(s) < MIN_SHARDS="
                           f"{self._min_shards()}")
            return False, None
        probe = self._coverage(idx, filter_call, tuple(shard_list)) \
            if filter_call is not None else None
        if probe is not None and not probe["covered"]:
            node.reason = "filter tree is not stack-coverable"
            return False, probe
        return True, probe

    @staticmethod
    def _merge_extras(kernels, probe):
        """Fold the gather-side dispatches (bsi_condition, time_union)
        into a {family: n} kernel map; returns how many were added."""
        extra = 0
        for family, n in (probe or {}).get("extra_kernels", {}).items():
            kernels[family] = kernels.get(family, 0) + n
            extra += n
        return extra

    @staticmethod
    def _cache_state(probe):
        if probe is None or probe["leaves"] == 0:
            return "n/a"
        if probe["resident"] == probe["leaves"]:
            return "warm"
        if probe["resident"] == 0:
            return "cold"
        return "partial"

    def _annotate_probe(self, node, probe):
        if probe is None:
            return
        node.annotations["cache"] = self._cache_state(probe)
        node.annotations["leaves"] = probe["leaves"]
        node.annotations["resident_leaves"] = probe["resident"]
        # per-leaf container representation ("repr: dense|sparse|rle"
        # with leaf counts) + the compressed-bytes estimate the chooser
        # committed to — resident containers report exact bytes, cold
        # leaves fall back to the fragment ledger's last build
        rc = probe.get("repr_counts")
        if rc:
            node.annotations["repr"] = dict(rc)
        node.estimate["bytes_materialized"] = \
            node.estimate.get("bytes_materialized", 0) \
            + probe["missing_bytes"]

    # -- bitmap call trees ---------------------------------------------------

    def _plan_bitmap(self, idx, call, shards, opt, validate=True):
        """Bitmap calls always run per-shard plane chains (one device
        chain per shard, merged on host) — there is no stacked strategy
        to choose, but the node still reports shard/view touch counts and
        whether the tree WOULD be stack-coverable (a Count/filter wrapped
        around it could then go stacked)."""
        if validate:
            self.ex.validate_bitmap_call(idx, call)
        shard_list = self._shards(idx, shards)
        node = self._node(call, strategy="per-shard-planes")
        probe = self._coverage(idx, call, tuple(shard_list))
        node.annotations["shards"] = len(shard_list)
        node.annotations["stack_coverable"] = probe["covered"]
        if probe["covered"]:
            self._annotate_probe(node, probe)
            if coalesce_window() > 0:
                node.annotations["batched"] = True
                node.annotations["batch_buckets"] = \
                    list(self._batch_buckets())
            # residency bytes only matter if a stacked consumer builds
            # the stacks; the per-shard chain itself uploads nothing
            node.estimate.pop("bytes_materialized", None)
        ops = self._tree_size(call)
        node.estimate["dispatches"] = 0
        node.estimate["device_ops"] = ops * len(shard_list)
        node.estimate["bytes_touched"] = (
            probe["leaves"] * len(shard_list) * WORDS_PER_ROW * 4
            if probe["covered"] else ops * len(shard_list)
            * WORDS_PER_ROW * 4)
        node.estimate["kernel_wall_seconds"] = 0.0
        node.estimate["cost_source"] = "structural"
        for child in call.children:
            node.children.append(
                self._plan_bitmap(idx, child, shards, opt, validate=False))
        return node

    # -- aggregates ----------------------------------------------------------

    def _plan_count(self, idx, call, shards, opt):
        from .executor import ExecError

        if len(call.children) != 1:
            raise ExecError("Count() takes exactly one row query")
        self.ex.validate_bitmap_call(idx, call.children[0])
        shard_list = self._shards(idx, shards)
        node = self._node(call)
        node.annotations["shards"] = len(shard_list)
        child = self._plan_bitmap(idx, call.children[0], shards, opt,
                                  validate=False)
        node.children.append(child)

        probe = self._coverage(idx, call.children[0], tuple(shard_list))
        if len(shard_list) >= self._min_shards() and probe["covered"]:
            node.strategy = "stacked"
            self._annotate_probe(node, probe)
            if coalesce_window() > 0:
                # serving would fold this query into a fused vmapped
                # dispatch with concurrent same-shape arrivals
                node.annotations["batched"] = True
                node.annotations["batch_buckets"] = \
                    list(self._batch_buckets())
            kernels = {"count": 1}
            node.estimate["dispatches"] = \
                1 + self._merge_extras(kernels, probe)
            # bytes_touched prices what the count kernel actually reads
            # (compressed container bytes); dense_bytes_touched is the
            # plane-scan baseline the chooser competed against — analyze
            # compares the two to catch repr-misestimates
            dense_bytes = \
                probe["leaves"] * self._plane_bytes(tuple(shard_list))
            node.estimate["bytes_touched"] = \
                probe.get("compressed_bytes", dense_bytes)
            node.estimate["dense_bytes_touched"] = dense_bytes
            self.cost.price(node, kernels)
            self._adaptive_choice(node, "Count", kernels, shard_list,
                                  "per-shard")
        else:
            node.strategy = "per-shard"
            if not probe["covered"]:
                node.reason = "tree is not stack-coverable"
            else:
                node.reason = (f"{len(shard_list)} shard(s) < MIN_SHARDS="
                               f"{self._min_shards()}")
            node.estimate["dispatches"] = 0
            node.estimate["device_ops"] = \
                (self._tree_size(call.children[0]) + 1) * len(shard_list)
            node.estimate["kernel_wall_seconds"] = 0.0
            node.estimate["cost_source"] = "structural"
        return node

    def _plan_sum(self, idx, call, shards, opt):
        return self._plan_bsi_agg(idx, call, shards, opt, family="sum",
                                  strategy="stacked-sum")

    def _plan_min(self, idx, call, shards, opt):
        return self._plan_bsi_agg(idx, call, shards, opt, family="minmax",
                                  strategy="stacked-minmax")

    def _plan_max(self, idx, call, shards, opt):
        return self._plan_bsi_agg(idx, call, shards, opt, family="minmax",
                                  strategy="stacked-minmax")

    def _plan_bsi_agg(self, idx, call, shards, opt, family, strategy):
        """Sum/Min/Max share one gate chain: MIN_SHARDS -> filter
        coverage -> BSI view present (try_sum/try_minmax in stacked.py)."""
        field = self.ex._agg_field(idx, call)
        filter_call = self.ex._agg_filter_call(idx, call)
        shard_list = self._shards(idx, shards)
        node = self._node(call)
        node.fields = [field.name]
        node.annotations["shards"] = len(shard_list)
        if filter_call is not None:
            node.children.append(self._plan_bitmap(
                idx, filter_call, shards, opt, validate=False))

        eligible, probe = self._stacked_gate(node, idx, filter_call,
                                             shard_list)
        bsi_view = field.view(field.bsi_view_name())
        if eligible and bsi_view is None:
            eligible = False
            node.reason = "BSI view not present locally"
        if eligible:
            node.strategy = strategy
            depth = field.options.bit_depth
            st = tuple(shard_list)
            node.annotations["bit_depth"] = depth
            self._annotate_probe(node, probe)
            kernels = {family: 1}
            dispatches = 1
            if filter_call is not None:
                kernels["filter"] = 1
                dispatches += 1 + self._merge_extras(kernels, probe)
            if not self.stacked.bsi_stack_resident(idx, field.name, st):
                node.estimate["bytes_materialized"] = \
                    node.estimate.get("bytes_materialized", 0) \
                    + (depth + 2) * self._plane_bytes(st)
                node.annotations["bsi_cache"] = "cold"
            else:
                node.annotations["bsi_cache"] = "warm"
            node.estimate["dispatches"] = dispatches
            node.estimate["bytes_touched"] = \
                (depth + 2) * self._plane_bytes(st)
            self.cost.price(node, kernels)
            self._adaptive_choice(node, node.op, kernels, shard_list,
                                  "per-shard")
        else:
            node.strategy = "per-shard"
            node.estimate["dispatches"] = 0
            node.estimate["device_ops"] = len(shard_list)
            node.estimate["kernel_wall_seconds"] = 0.0
            node.estimate["cost_source"] = "structural"
        return node

    def _plan_minmax_row(self, idx, call, shards, opt):
        """MinRow/MaxRow only have the per-shard first-qualifying-row
        scan — annotate the scan breadth instead of a strategy choice."""
        field = self.ex._set_field(idx, call)
        shard_list = self._shards(idx, shards)
        node = self._node(call, strategy="per-shard-scan")
        node.fields = [field.name]
        node.annotations["shards"] = len(shard_list)
        if call.children:
            self.ex.validate_bitmap_call(idx, call.children[0])
            node.children.append(self._plan_bitmap(
                idx, call.children[0], shards, opt, validate=False))
        node.estimate["dispatches"] = 0
        node.estimate["device_ops"] = len(shard_list)
        node.estimate["kernel_wall_seconds"] = 0.0
        node.estimate["cost_source"] = "structural"
        return node

    # -- TopN ----------------------------------------------------------------

    def _plan_topn(self, idx, call, shards, opt):
        field = self.ex._set_field(idx, call)
        if call.children:
            self.ex.validate_bitmap_call(idx, call.children[0])
        shard_list = self._shards(idx, shards)
        ids = call.args.get("ids")
        filter_call = call.children[0] if call.children else None
        node = self._node(call)
        node.fields = [field.name]
        node.annotations["shards"] = len(shard_list)
        if filter_call is not None:
            node.children.append(self._plan_bitmap(
                idx, filter_call, shards, opt, validate=False))

        # the SAME candidate policy as _row_counts: fragment TopN caches
        # when populated, else all present rows (host containers only)
        from ..core.view import VIEW_STANDARD

        candidates = self.ex._candidate_rows(
            field, shard_list, ids, ids is None, VIEW_STANDARD)
        node.annotations["candidate_rows"] = len(candidates)

        eligible, probe = self._stacked_gate(node, idx, filter_call,
                                             shard_list)
        if eligible:
            node.strategy = "stacked-row-counts"
            st = tuple(shard_list)
            chunk = self.stacked.row_chunk_size(st)
            n_chunks = -(-len(candidates) // chunk) if candidates else 0
            node.annotations["row_chunk_size"] = chunk
            self._annotate_probe(node, probe)
            kernels = {}
            dispatches = n_chunks
            if n_chunks:
                kernels["row_counts"] = n_chunks
            if filter_call is not None:
                kernels["filter"] = 1
                dispatches += 1 + self._merge_extras(kernels, probe)
            missing_rows = self._missing_row_chunks(
                idx, field.name, candidates, chunk, st)
            node.estimate["bytes_materialized"] = \
                node.estimate.get("bytes_materialized", 0) \
                + missing_rows * self._plane_bytes(st)
            node.estimate["dispatches"] = dispatches
            node.estimate["bytes_touched"] = \
                len(candidates) * self._plane_bytes(st)
            self.cost.price(node, kernels)
            self._adaptive_choice(node, node.op, kernels, shard_list,
                                  "per-shard-chunked")
        else:
            from .executor import _TOPN_STACK_CHUNK

            node.strategy = "per-shard-chunked"
            per_shard_chunks = -(-len(candidates) // _TOPN_STACK_CHUNK) \
                if candidates else 0
            node.estimate["dispatches"] = 0
            node.estimate["device_ops"] = per_shard_chunks * len(shard_list)
            node.estimate["kernel_wall_seconds"] = 0.0
            node.estimate["cost_source"] = "structural"
        return node

    def _missing_row_chunks(self, idx, field_name, rows, chunk, shard_tuple,
                            view_name=None):
        """How many [chunk, S, W] row stacks the stacked path would have
        to build (vs. serve from the rows pool)."""
        from ..core.view import VIEW_STANDARD

        view_name = view_name or VIEW_STANDARD
        missing = 0
        for i in range(0, len(rows), chunk):
            part = tuple(rows[i:i + chunk])
            if not self.stacked.rows_chunk_resident(
                    idx, field_name, part, shard_tuple, view_name):
                missing += len(part)
        return missing

    # -- Rows ----------------------------------------------------------------

    def _plan_rows(self, idx, call, shards, opt):
        """Rows() is pure host metadata (fragment row_ids / contains) —
        no device work on any path."""
        field = self.ex._set_field(idx, call)
        shard_list = self._shards(idx, shards)
        node = self._node(call, strategy="host-metadata")
        node.fields = [field.name]
        views = self.ex._rows_views(field, call)
        node.annotations["shards"] = len(shard_list)
        node.annotations["views"] = list(views)
        node.estimate["dispatches"] = 0
        node.estimate["device_ops"] = 0
        node.estimate["kernel_wall_seconds"] = 0.0
        node.estimate["cost_source"] = "structural"
        return node

    # -- GroupBy -------------------------------------------------------------

    def _plan_group_by(self, idx, call, shards, opt):
        from ..pql import Call
        from .executor import ExecError, groupby_previous

        if not call.children:
            raise ExecError("GroupBy requires at least one Rows() child")
        for child in call.children:
            if child.name != "Rows":
                raise ExecError("GroupBy children must be Rows() calls")
        previous = groupby_previous(call, len(call.children))
        filter_call = call.args.get("filter")
        if filter_call is not None:
            if not isinstance(filter_call, Call):
                raise ExecError("GroupBy filter must be a row query")
            self.ex.validate_bitmap_call(idx, filter_call)

        fields = [self.ex._set_field(idx, child) for child in call.children]
        shard_list = self._shards(idx, shards)
        node = self._node(call)
        node.fields = [f.name for f in fields]
        node.annotations["shards"] = len(shard_list)
        for child in call.children:
            node.children.append(self._plan_rows(idx, child, shards, opt))
        if filter_call is not None:
            node.children.append(self._plan_bitmap(
                idx, filter_call, shards, opt, validate=False))

        # the executor's own (host-only) child row resolution, including
        # the cursor's outer-row pruning — the estimates below are exact
        # row counts, not guesses
        child_rows = [self.ex._exec_rows(idx, child, shards, opt).rows
                      for child in call.children]
        if previous is not None:
            lo = previous[0] + (1 if len(child_rows) == 1 else 0)
            child_rows[0] = [r for r in child_rows[0] if r >= lo]
        node.annotations["rows_per_field"] = [len(r) for r in child_rows]

        eligible, probe = self._stacked_gate(node, idx, filter_call,
                                             shard_list)
        if not eligible:
            node.strategy = "per-shard"
            combos = 1
            for rows in child_rows:
                combos *= len(rows)
            node.estimate["dispatches"] = 0
            node.estimate["device_ops"] = combos * len(shard_list)
            node.estimate["kernel_wall_seconds"] = 0.0
            node.estimate["cost_source"] = "structural"
            return node

        st = tuple(shard_list)
        self._annotate_probe(node, probe)
        kernels = {}
        dispatches = 0
        upload_bytes = node.estimate.get("bytes_materialized", 0)
        if filter_call is not None:
            kernels["filter"] = 1
            dispatches += 1 + self._merge_extras(kernels, probe)
        chunk = self.stacked.row_chunk_size(st)

        if len(fields) == 1:
            node.strategy = "stacked-row-counts"
            rows = child_rows[0]
            n_chunks = -(-len(rows) // chunk) if rows else 0
            node.annotations["row_chunk_size"] = chunk
            if n_chunks:
                kernels["row_counts"] = n_chunks
            dispatches += n_chunks
            upload_bytes += self._missing_row_chunks(
                idx, fields[0].name, rows, chunk, st) \
                * self._plane_bytes(st)
        else:
            node.strategy = "stacked-pairwise"
            a_rows, b_rows = child_rows[-2], child_rows[-1]
            outer = 1
            for rows in child_rows[:-2]:
                outer *= len(rows)
            # mirror the executor's adaptive tile so the plan's shape
            # and dispatch count match what execution will actually run
            from . import adaptive

            tile_dec = adaptive.decide_tile(
                chunk, len(a_rows), len(b_rows), outer=outer) \
                if (adaptive.enabled() and a_rows and b_rows) else None
            t = tile_dec.tile if (tile_dec is not None
                                  and tile_dec.act) else chunk
            a_tiles = -(-len(a_rows) // t) if a_rows else 0
            b_tiles = -(-len(b_rows) // t) if b_rows else 0
            pairwise = outer * a_tiles * b_tiles
            node.annotations["tile"] = [min(len(a_rows), t),
                                        min(len(b_rows), t)]
            if tile_dec is not None:
                node.annotations["tile_chosen_by"] = tile_dec.chosen_by
            node.annotations["pairwise_tiles"] = [a_tiles, b_tiles]
            node.annotations["outer_combinations"] = outer
            if pairwise:
                kernels["pairwise"] = pairwise
            dispatches += pairwise
            node.estimate["pairwise_dispatches"] = pairwise
            for field, rows in zip(fields[-2:], (a_rows, b_rows)):
                upload_bytes += self._missing_row_chunks(
                    idx, field.name, rows, chunk, st) \
                    * self._plane_bytes(st)
            for field, rows in zip(fields[:-2], child_rows[:-2]):
                upload_bytes += self._missing_row_chunks(
                    idx, field.name, rows, chunk, st) \
                    * self._plane_bytes(st)
        total_rows = sum(len(r) for r in child_rows)
        node.estimate["dispatches"] = dispatches
        node.estimate["bytes_materialized"] = upload_bytes
        node.estimate["bytes_touched"] = \
            total_rows * self._plane_bytes(st)
        self.cost.price(node, kernels)
        self._adaptive_choice(node, "GroupBy", kernels, shard_list,
                              "per-shard")
        return node

    # -- Options / writes ----------------------------------------------------

    def _plan_options(self, idx, call, shards, opt):
        # one Options() layer, exactly as _exec_options peels it (nested
        # wrappers recurse through plan_call on the child)
        from .executor import ExecError, ExecOptions

        if len(call.children) != 1:
            raise ExecError("Options() takes exactly one query")
        merged = ExecOptions(
            shards=opt.shards, exclude_columns=opt.exclude_columns,
            column_attrs=opt.column_attrs,
            exclude_row_attrs=opt.exclude_row_attrs,
            remote=opt.remote, profile=opt.profile,
            explain=getattr(opt, "explain", None))
        for key, value in call.args.items():
            if key == "shards":
                if not isinstance(value, list):
                    raise ExecError("Options(): shards must be a list")
                shards = [int(s) for s in value]
            elif key == "excludeColumns":
                merged.exclude_columns = bool(value)
            elif key == "columnAttrs":
                merged.column_attrs = bool(value)
            elif key == "excludeRowAttrs":
                merged.exclude_row_attrs = bool(value)
            else:
                raise ExecError(f"Options(): unknown arg {key!r}")
        node = self._node(call, strategy="option-wrapper")
        node.annotations["overrides"] = sorted(call.args)
        node.children.append(
            self.plan_call(idx, call.children[0], shards, merged))
        node.estimate["dispatches"] = \
            node.children[0].estimate.get("dispatches", 0)
        node.estimate["kernel_wall_seconds"] = \
            node.children[0].estimate.get("kernel_wall_seconds", 0.0)
        node.estimate["cost_source"] = \
            node.children[0].estimate.get("cost_source", "structural")
        return node

    def _plan_write(self, idx, call, shards, opt):
        node = self._node(call, strategy="write")
        node.annotations["mutates"] = True
        node.estimate["dispatches"] = 0
        node.estimate["device_ops"] = 0
        node.estimate["kernel_wall_seconds"] = 0.0
        node.estimate["cost_source"] = "structural"
        return node


# ------------------------------------------------------- analyze grafting


def graft_actual(node, wall_seconds, before, after, kernel_before,
                 kernel_after, strategies=None, phases_before=None,
                 phases_after=None):
    """Attach measured actuals (stacked cache_stats + per-family kernel
    seconds deltas) onto one TOP-LEVEL plan node, then compare against
    the estimate. Deltas are exact when queries are serialized (the
    acceptance path) and order-of-magnitude under concurrency — same
    caveat as the QueryProfile counter deltas. phases_before/after are
    StackedEvaluator.dispatch_phases() snapshots; when given, the actual
    gains a per-phase RTT decomposition (`phase_seconds`) so the cost
    model can price lock wait / compile / dispatch ack / device sync
    separately from kernel wall."""
    actual = {
        "wall_seconds": round(wall_seconds, 6),
        "dispatches": after["dispatches"] - before["dispatches"],
        "pairwise_dispatches": (after["pairwise_dispatches"]
                                - before["pairwise_dispatches"]),
        "cache_hits": after["hits"] - before["hits"],
        "cache_misses": after["misses"] - before["misses"],
        "bytes_materialized": (after["planes_uploaded"]
                               - before["planes_uploaded"])
        * WORDS_PER_ROW * 4,
    }
    k_wall = 0.0
    k_bytes = 0
    k_by_family = {}
    for family, k in kernel_after.items():
        prev = kernel_before.get(family, {"count": 0, "seconds": 0.0})
        dn = k["count"] - prev["count"]
        ds = k["seconds"] - prev["seconds"]
        db = k.get("bytes_in", 0) - prev.get("bytes_in", 0)
        if dn > 0:
            k_by_family[family] = dn
            k_wall += ds
            if db > 0:
                k_bytes += db
    actual["kernel_wall_seconds"] = round(k_wall, 6)
    # bytes the dispatched kernels actually read (compressed container
    # bytes under --container-repr auto, dense plane bytes otherwise) —
    # the analyze-side ground truth for the repr-misestimate check
    actual["bytes_touched"] = k_bytes
    if k_by_family:
        actual["kernels"] = k_by_family
    if phases_before is not None and phases_after is not None:
        phase_seconds = {}
        for family, fam in phases_after.items():
            prev_fam = phases_before.get(family, {})
            for phase, p in fam.items():
                prev = prev_fam.get(phase, {"count": 0, "seconds": 0.0})
                ds = p["seconds"] - prev["seconds"]
                if p["count"] - prev["count"] > 0:
                    phase_seconds[phase] = round(
                        phase_seconds.get(phase, 0.0) + ds, 6)
        if phase_seconds:
            actual["phase_seconds"] = phase_seconds
    if strategies:
        mine = [s for s in strategies if s.get("op") == node.op]
        if mine:
            actual["strategy"] = mine[0]["strategy"]
            # fused-dispatch occupancy this execution rode (the count
            # group-commit or the coalescer), so analyze distinguishes
            # a query slowed by batching from one slowed by the kernel
            if "batch" in mine[0]:
                actual["batch"] = mine[0]["batch"]
    node.actual = actual
    flag_misestimates(node)
    return node


def _deviation(estimated, actual, floor):
    est = max(float(estimated), floor)
    act = max(float(actual), floor)
    return act / est if act >= est else est / act


def flag_misestimates(node, factor=None):
    """Compare estimate vs. actual on the three costed metrics; flag a
    node when any deviates by more than the configured factor in EITHER
    direction (a 10x overestimate hides capacity exactly like a 10x
    underestimate hides a regression). One `explain_misestimates_total
    {op}` tick per flagged node, not per metric."""
    if node.actual is None or not node.estimate:
        return node
    if node.annotations.get("fused"):
        # the estimate priced the interpreted per-call path, but the
        # node executed inside ONE fused program — any deviation is the
        # strategy change itself, not cost-model drift, and flagging it
        # would spam the triage ring on every fused analyze
        node.misestimates = []
        return node
    factor = _misestimate_factor if factor is None else factor
    checks = (
        ("kernel_wall_seconds", WALL_FLOOR_SECONDS),
        ("dispatches", DISPATCH_FLOOR),
        ("bytes_materialized", BYTES_FLOOR),
    )
    flags = []
    for metric, floor in checks:
        if metric not in node.estimate or metric not in node.actual:
            continue
        est, act = node.estimate[metric], node.actual[metric]
        if max(float(est), float(act)) < floor:
            continue  # both below the noise floor
        dev = _deviation(est, act, floor)
        if dev > factor:
            flags.append({"metric": metric, "estimated": est,
                          "actual": act, "deviation": round(dev, 2)})
    # repr-misestimate: the chooser committed to a compressed
    # representation, but the kernels read MORE bytes than the dense
    # plane scan would have — the choice made the query worse. Rides
    # the same ring/counter as the cost misestimates.
    dense_est = node.estimate.get("dense_bytes_touched")
    act_bytes = node.actual.get("bytes_touched", 0)
    reprs = node.annotations.get("repr") or {}
    if (dense_est and act_bytes > dense_est
            and any(k != "dense" for k in reprs)):
        flags.append({"metric": "container_repr",
                      "estimated": dense_est, "actual": act_bytes,
                      "deviation": round(act_bytes / dense_est, 2)})
    node.misestimates = flags
    if flags:
        _count_misestimate(node.op)
        _adaptive_feedback(node, flags)
    return node


def _adaptive_feedback(node, flags):
    """Misestimates are the adaptive engine's correction signal (ISSUE
    13 (c)): a kernel-wall deviation re-seeds the involved families'
    EWMA calibration from the OBSERVED wall; a container_repr
    misestimate strikes the node's fragments toward a forced-dense
    rebuild. No-op when the engine is off."""
    from . import adaptive

    if not adaptive.enabled():
        return
    for f in flags:
        if f["metric"] == "kernel_wall_seconds":
            kernels = (node.actual or {}).get("kernels") \
                or node.estimate.get("kernels") or {}
            adaptive.note_wall_misestimate(
                kernels, (node.actual or {}).get(
                    "kernel_wall_seconds", 0.0))
        elif f["metric"] == "container_repr":
            from ..utils import workload

            adaptive.note_repr_misestimate(
                workload.current_index(), node.fields)
