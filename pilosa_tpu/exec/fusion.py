"""Whole-plan fusion: compile an entire PQL query into ONE jitted
device program, cached by workload fingerprint.

BENCH r03 measured 66.1ms p50 on the 1B-column Intersect+Count with
64.9ms of it dispatch RTT. Count batching (PR 9) amortizes that RTT
across *concurrent* queries; nothing removed it per query, so an
interactive client running one query at a time still pays the full
round trip per top-level call. This module removes the per-call
multiplier: an eligible multi-call query traces into one jitted
function whose arguments are the row-id/BSI container components and
whose closure is the plan *shape* — `Count(Intersect(Row(f=3),
Row(g=7)))` and `Count(Intersect(Row(f=9),Row(g=1)))` share one
compiled program.

Program identity is the workload fingerprint (PR 8's literal-free query
shape hash) refined by what the shape hash cannot see: the gathered
containers' gsig (repr kind + component array shapes — a row that went
RLE yesterday and dense today needs a different trace) and the padded
shard bucket. All-dense gsigs trace through ops/containers.count_program
exactly like the legacy per-call path (to_dense is the identity), which
is the bit-identity guarantee; sparse/RLE count programs inline into the
fused trace the same way, and PR 14 ingest overlay terms ride along in
the flattened component list.

Admission is frequency-gated: a COLD fingerprint never pays a compile.
The workload table's per-fingerprint query count is the signal — only a
shape seen >= --fusion-min-hits times (or one whose program is already
cached) may trace. When the adaptive engine is enabled it additionally
prices compile-amortized fused cost against the interpreted dispatch
count and may veto (`decide_fuse`); in shadow mode it logs the verdict
and vetoes nothing.

Escape hatch: --fusion off|on|shadow. `off` (the default) keeps every
legacy code path byte-for-byte — the executor hook is two attribute
reads. `shadow` counts what WOULD have fused but compiles nothing and
touches no cache (the A/B harness for the bench gates). Module-singleton
state with configure()/reset(), like exec/adaptive.py.
"""

import threading
import time
from collections import OrderedDict

from ..utils import flightrec as _flightrec
from ..utils.stats import global_stats

MODES = ("off", "on", "shadow")

#: bounded program-ledger size: entries are bookkeeping (the jitted
#: programs themselves live in StackedEvaluator._fns under MAX_FNS),
#: but unbounded fingerprints would leak under a shape-churning client
DEFAULT_CACHE_SIZE = 64

#: a fingerprint must have completed this many queries before its first
#: trace — the compile-admission floor (cold shapes never compile)
DEFAULT_MIN_HITS = 2

_lock = threading.Lock()
_mode = "off"
_cache_size = DEFAULT_CACHE_SIZE
_min_hits = DEFAULT_MIN_HITS

#: (fingerprint, gsigs, bucket) -> entry dict; ordered = LRU
_programs = OrderedDict()
#: fingerprint -> set of live _programs keys (plan-path status probe)
_by_fp = {}

_counters = {
    "fused": 0,              # queries served by one fused dispatch
    "interpreted_cold": 0,   # vetoed: fingerprint below min-hits
    "interpreted_priced": 0,  # vetoed: adaptive priced interpret cheaper
    "ineligible": 0,         # shape/coverage can't fuse (legacy path)
    "shadow_would_fuse": 0,  # shadow: admission passed, nothing ran
    "evictions": 0,
}

_local = threading.local()


def configure(mode=None, cache_size=None, min_hits=None):
    """Apply --fusion / --fusion-cache-size / --fusion-min-hits."""
    global _mode, _cache_size, _min_hits
    if mode is not None:
        if mode not in MODES:
            raise ValueError(
                f"fusion mode must be one of {'|'.join(MODES)}: {mode!r}")
        with _lock:
            _mode = mode
    if cache_size is not None:
        with _lock:
            _cache_size = max(1, int(cache_size))
            _evict_over_budget()
    if min_hits is not None:
        with _lock:
            _min_hits = max(0, int(min_hits))


def mode():
    return _mode


def enabled():
    """True when the fused path observes (on OR shadow)."""
    return _mode != "off"


def acting():
    """True only when eligible queries actually run fused."""
    return _mode == "on"


def min_hits():
    return _min_hits


def reset():
    """Test isolation: back to cold defaults (mode off, empty cache)."""
    global _mode, _cache_size, _min_hits
    with _lock:
        _mode = "off"
        _cache_size = DEFAULT_CACHE_SIZE
        _min_hits = DEFAULT_MIN_HITS
        _programs.clear()
        _by_fp.clear()
        for k in _counters:
            _counters[k] = 0
    _local.fused = 0


def _bump(counter):
    with _lock:
        _counters[counter] += 1


# ------------------------------------------------- per-query attribution


def note_fused(n):
    """Stamp how many top-level calls the current thread's query fused
    (0 = interpreted). The executor resets it at query start; SLOW QUERY
    reads it after the query returns — same take-last handoff as
    stacked.note_batch_size."""
    _local.fused = int(n)


def last_fused():
    """Fused-call count of the last query on THIS thread (0 when it ran
    interpreted — also the pre-PR default, so log parsing stays total)."""
    return getattr(_local, "fused", 0)


# ------------------------------------------------------- program ledger


def _evict_over_budget():
    """Caller holds _lock. Trim the LRU past the configured bound; the
    jitted fn itself is dropped from the evaluator's fn cache so an
    evicted program re-compiles (and re-counts) on re-entry."""
    while len(_programs) > _cache_size:
        key, entry = _programs.popitem(last=False)
        _counters["evictions"] += 1
        keys = _by_fp.get(key[0])
        if keys is not None:
            keys.discard(key)
            if not keys:
                _by_fp.pop(key[0], None)
        ev = entry.get("evaluator")
        fn_key = entry.get("fn_key")
        if ev is not None and fn_key is not None:
            with ev._lock:
                ev._fns.pop(fn_key, None)
        _flightrec.record("fusion.evict", fingerprint=key[0],
                          hits=entry["hits"],
                          compile_ms=entry["compile_ms"])


def has_program(fp):
    """True when any compiled program is live for this fingerprint —
    the plan path's cache-key status probe and the warm half of the
    admission gate (a cached program costs nothing to reuse, so the
    min-hits floor no longer applies)."""
    with _lock:
        return bool(_by_fp.get(fp))


def cache_status(fp):
    """"cached" | "uncompiled" for ?explain=true annotation."""
    return "cached" if has_program(fp) else "uncompiled"


def _touch_program(key, ev, fn_key, compile_ms=None):
    """Record one fused execution against `key`; returns True when the
    entry already existed (a program-cache hit). A 4-component key is a
    MESH program (cluster/spmd.py): its 4th component is the mesh shape
    (processes, devices per process), recorded on the entry so the
    ledger shows which fabric a program was traced for."""
    now = time.time()
    with _lock:
        entry = _programs.get(key)
        hit = entry is not None
        if entry is None:
            entry = _programs[key] = {
                "fingerprint": key[0], "gsigs": key[1], "bucket": key[2],
                "compile_ms": 0.0, "hits": 0, "created": now,
                "last_hit": now, "evaluator": ev, "fn_key": fn_key,
            }
            if len(key) > 3:
                entry["mesh"] = list(key[3])
            _by_fp.setdefault(key[0], set()).add(key)
            _evict_over_budget()
        else:
            _programs.move_to_end(key)
        entry["hits"] += 1
        entry["last_hit"] = now
        if compile_ms is not None:
            entry["compile_ms"] = round(compile_ms, 3)
    return hit


# ------------------------------------------------- mesh (collective) programs


def admit(fp):
    """Shared compile-admission verdict for a fingerprint: a live
    program, or enough completed queries to cross the min-hits floor.
    The SPMD fused path (cluster/spmd.maybe_execute_fused) applies the
    same cold-shape-never-compiles rule as the local fused path."""
    from ..utils import workload as workload_mod

    if has_program(fp):
        return True
    return workload_mod.fingerprint_hits(fp) >= _min_hits


def mesh_program_key(fp, sigs, bucket, mesh):
    """Ledger key for a fused COLLECTIVE program: the local key's
    (fingerprint, signatures, shard bucket) extended by the mesh shape —
    the same fingerprint traced on a different fabric is a different
    program (the all-reduce is compiled against a specific device set)."""
    return (fp, tuple(sigs), int(bucket), tuple(int(m) for m in mesh))


def touch_mesh_program(key, ev, fn_key, compile_ms=None):
    """Record one fused collective execution. `ev` duck-types the
    evaluator contract (_lock + _fns) — SpmdDataPlane qualifies, so
    eviction drops the jitted collective exactly like a local program.
    MUST be called after the data plane's step lock is released:
    eviction takes ev._lock (see _evict_over_budget).

    Returns True on a program-cache hit."""
    hit = _touch_program(key, ev, fn_key, compile_ms=compile_ms)
    if compile_ms is not None:
        _flightrec.record("fusion.compile", fingerprint=key[0],
                          calls=len(key[1]), bucket=key[2],
                          mesh=list(key[3]),
                          compile_ms=round(compile_ms, 3))
    _bump("fused")
    global_stats.count("fused_dispatches_total", 1)
    if hit:
        global_stats.count("fusion_cache_hits_total", 1)
    return hit


# ------------------------------------------------------------- execution


def _eligible_calls(query, opt):
    """The fused trace covers exactly the shapes the stacked count path
    covers: every top-level call must be Count(tree) — multi-call
    queries fuse into one program with one (hi, lo) vector output.
    Returns the calls list or None. (explain=plan never executes at
    all; explain=analyze enters through the executor's fused-analyze
    wrapper, which grafts the single dispatch onto the plan nodes.)"""
    if opt.remote:
        return None
    calls = query.calls
    if not calls:
        return None
    for call in calls:
        if call.name != "Count" or len(call.children) != 1:
            return None
    return calls


def maybe_execute(executor, idx, query, shards, opt):
    """Try to serve the whole query as ONE fused device program.
    Returns the per-call results list, or None → the caller runs the
    legacy per-call loop (which also reproduces any validation error
    this path sidestepped). Never raises: a fused-path failure falls
    back, it does not fail the query."""
    if _mode == "off":
        return None
    try:
        return _maybe_execute(executor, idx, query, shards, opt)
    except Exception:  # noqa: BLE001 — fused path must never break a query
        return None


def _maybe_execute(executor, idx, query, shards, opt):
    from ..utils import workload as workload_mod
    from . import adaptive as adaptive_mod
    from .stacked import MIN_SHARDS

    calls = _eligible_calls(query, opt)
    if calls is None:
        _bump("ineligible")
        return None
    shard_list = tuple(executor._call_shards(idx, shards))
    if len(shard_list) < MIN_SHARDS:
        _bump("ineligible")
        return None

    # -- compile admission: the workload table's frequency ranking is
    # the signal. A fingerprint below the floor with no live program
    # runs interpreted — a cold shape NEVER pays a compile.
    fp = workload_mod.current_fingerprint()
    if fp is None:
        fp, _ = workload_mod.fingerprint(idx.name, query)
    cached = has_program(fp)
    fp_hits = workload_mod.fingerprint_hits(fp)
    if not cached and fp_hits < _min_hits:
        _bump("interpreted_cold")
        return None
    if adaptive_mod.enabled():
        dec = adaptive_mod.decide_fuse(
            len(calls), fp_hits, cached,
            stacked=executor._stacked)
        if dec is not None and dec.act and not dec.fuse:
            _bump("interpreted_priced")
            return None
    if _mode == "shadow":
        # admission passed: count what WOULD fuse, touch nothing —
        # shadow must have zero cache/compile side effects
        _bump("shadow_would_fuse")
        return None

    # -- gather: same coverage walk as the per-call stacked path; any
    # non-coverable tree (or vanished field) sends the whole query back
    # to the legacy loop so per-call fallback semantics are unchanged
    ev = executor._stacked
    plans, stacks_per_call, gsigs = [], [], []
    for call in calls:
        executor.validate_bitmap_call(idx, call.children[0])
        g = ev._gather(idx, call.children[0], shard_list)
        if g is None:
            _bump("ineligible")
            return None
        sig, stacks = g
        plans.append((sig, tuple(c.csig for c in stacks)))
        stacks_per_call.append(stacks)
        gsigs.append(tuple(c.gsig for c in stacks))
    bucket = ev._padded_len(shard_list)
    key = (fp, tuple(gsigs), bucket)

    t0 = time.perf_counter()
    counts, fn_key, compiled = ev.fused_count(
        tuple(plans), stacks_per_call)
    wall = time.perf_counter() - t0

    hit = _touch_program(key, ev, fn_key,
                         compile_ms=wall * 1000 if compiled else None)
    if compiled:
        _flightrec.record("fusion.compile", fingerprint=fp,
                          calls=len(calls), bucket=bucket,
                          compile_ms=round(wall * 1000, 3))
        # calibrate the adaptive engine's compile prior from reality
        adaptive_mod.observe_fuse_compile(wall)
    _bump("fused")
    global_stats.count("fused_dispatches_total", 1)
    if hit:
        global_stats.count("fusion_cache_hits_total", 1)
    note_fused(len(calls))
    workload_mod.note_batch(len(calls))
    program = "compile" if compiled else ("hit" if hit else "warm")
    per_call = wall / len(calls)
    for _ in calls:
        executor._note_strategy("Count", "fused", batch=len(calls),
                                program=program)
        global_stats.timing("query_op_seconds", per_call,
                            {"op": "Count"})
    return counts


# ------------------------------------------------------------- /debug view


def snapshot():
    """GET /debug/fusion: mode + knobs, the program ledger (per-entry
    fingerprint/compile-ms/hits/last-hit-age), and the fuse-vs-interpret
    decision counters."""
    now = time.time()
    with _lock:
        entries = [{
            "fingerprint": e["fingerprint"],
            "bucket": e["bucket"],
            "calls": len(e["gsigs"]),
            "compile_ms": e["compile_ms"],
            "hits": e["hits"],
            "age_seconds": round(now - e["created"], 1),
            "last_hit_age_seconds": round(now - e["last_hit"], 1),
            **({"mesh": e["mesh"]} if "mesh" in e else {}),
        } for e in _programs.values()]
        return {
            "mode": _mode,
            "cache_size": _cache_size,
            "min_hits": _min_hits,
            "entries": len(entries),
            "evictions": _counters["evictions"],
            "decisions": {k: v for k, v in _counters.items()
                          if k != "evictions"},
            "programs": entries[::-1],  # most-recently used first
        }


def decision_counts():
    """Flat counters for bench attempt tagging."""
    with _lock:
        return dict(_counters)
