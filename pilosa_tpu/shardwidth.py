"""Shard geometry constants.

The reference selects shard width at build time (reference: shardwidth/20.go:19,
fragment.go:53); we fix the default exponent 20 but keep it configurable via
environment for tests (PILOSA_TPU_SHARD_EXP).

A shard covers SHARD_WIDTH consecutive columns. On device, one row of one shard
("row plane") is a dense bitset of SHARD_WIDTH bits stored as uint32 words —
the TPU-native replacement for roaring containers (reference: roaring/roaring.go).
"""

import os

# Shard width exponent. Reference default is 20 (1Mi columns per shard);
# the reference supports 16..32 via build tags. Below 16 a shard would be
# smaller than one roaring container, breaking interchange geometry.
EXPONENT: int = int(os.environ.get("PILOSA_TPU_SHARD_EXP", "20"))
if not 16 <= EXPONENT <= 32:
    raise ValueError(f"PILOSA_TPU_SHARD_EXP must be in [16, 32], got {EXPONENT}")

# Number of columns in a shard.
SHARD_WIDTH: int = 1 << EXPONENT

# Bits per storage word on device (uint32 is TPU-native).
WORD_BITS: int = 32

# uint32 words per row plane.
WORDS_PER_ROW: int = SHARD_WIDTH // WORD_BITS

# Container geometry (host roaring interchange format, reference:
# roaring/roaring.go:55 bitmapN): a container covers 2^16 bits.
CONTAINER_BITS: int = 1 << 16
WORDS_PER_CONTAINER: int = CONTAINER_BITS // WORD_BITS
CONTAINERS_PER_SHARD: int = SHARD_WIDTH // CONTAINER_BITS

# Largest container key (reference: roaring/roaring.go:60).
MAX_CONTAINER_KEY: int = (1 << 48) - 1
