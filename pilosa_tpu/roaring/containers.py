"""Roaring containers, numpy-backed.

Host-side storage only: the reference implements its entire set-algebra on
these (reference: roaring/roaring.go:3121-5196); in this framework containers
exist solely as the at-rest/interchange representation plus a mutation target
for writes. All query-time algebra happens on dense device planes
(pilosa_tpu.ops.bitplane); a container's job is to (de)serialize and to
convert to/from dense words.

Three kinds, matching the reference's on-disk type ids (roaring/roaring.go:65):
1=array (sorted uint16 values), 2=bitmap (2^16 bits), 3=run ([start,last]
uint16 intervals, inclusive).
"""

import numpy as np

TYPE_ARRAY = 1
TYPE_BITMAP = 2
TYPE_RUN = 3

# Cardinality threshold at which an array converts to a bitmap (reference:
# roaring ArrayMaxSize = 4096).
ARRAY_MAX_SIZE = 4096
# Bytes of a serialized bitmap container: 2^16 bits.
BITMAP_BYTES = 8192
WORDS = BITMAP_BYTES // 4  # uint32 words
RUN_MAX_SIZE = 2048  # reference: runMaxSize — above this a run container is never smaller


class Container:
    """One 2^16-bit chunk of a bitmap.

    Internally holds exactly one of:
      values: sorted unique uint16 ndarray          (array)
      words:  [2048] uint32 ndarray, little-endian  (bitmap)
      runs:   [R, 2] uint16 ndarray of [start,last] (run)
    """

    __slots__ = ("typ", "values", "words", "runs", "n")

    def __init__(self, typ=TYPE_ARRAY, values=None, words=None, runs=None, n=None):
        self.typ = typ
        if typ == TYPE_ARRAY and values is None:
            values = np.empty(0, dtype=np.uint16)
        self.values = values
        self.words = words
        self.runs = runs
        if n is None:
            n = self._count()
        self.n = n

    # -- constructors -------------------------------------------------------

    @classmethod
    def from_values(cls, values):
        values = np.unique(np.asarray(values, dtype=np.uint16))  # sorted+dedup
        if len(values) > ARRAY_MAX_SIZE:
            return cls.from_dense_words(values_to_words(values))
        return cls(TYPE_ARRAY, values=values)

    @classmethod
    def from_dense_words(cls, words, n=None):
        words = np.ascontiguousarray(words, dtype=np.uint32)
        if n is None:
            n = int(np.sum(popcount32(words)))
        if n <= ARRAY_MAX_SIZE:
            return cls(TYPE_ARRAY, values=words_to_values(words), n=n)
        return cls(TYPE_BITMAP, words=words, n=n)

    @classmethod
    def from_runs(cls, runs):
        runs = np.asarray(runs, dtype=np.uint16).reshape(-1, 2)
        return cls(TYPE_RUN, runs=runs)

    # -- basic ops ----------------------------------------------------------

    def _count(self):
        if self.typ == TYPE_ARRAY:
            return len(self.values) if self.values is not None else 0
        if self.typ == TYPE_BITMAP:
            return int(np.sum(popcount32(self.words)))
        runs = self.runs
        if runs is None or len(runs) == 0:
            return 0
        return int(np.sum(runs[:, 1].astype(np.int64) - runs[:, 0].astype(np.int64) + 1))

    def contains(self, v):
        v = np.uint16(v)
        if self.typ == TYPE_ARRAY:
            i = np.searchsorted(self.values, v)
            return i < len(self.values) and self.values[i] == v
        if self.typ == TYPE_BITMAP:
            return bool((self.words[int(v) >> 5] >> np.uint32(int(v) & 31)) & np.uint32(1))
        for s, l in self.runs:
            if s <= v <= l:
                return True
        return False

    def add(self, v):
        """Returns True if the bit changed. Converts representation as needed
        (reference: container add/array->bitmap conversion roaring.go:2599)."""
        if self.contains(v):
            return False
        v = np.uint16(v)
        if self.typ == TYPE_RUN:
            self._run_to_bitmap_or_array()
            return self.add(v)
        if self.typ == TYPE_ARRAY:
            if self.n >= ARRAY_MAX_SIZE:
                self._array_to_bitmap()
                return self.add(v)
            i = int(np.searchsorted(self.values, v))
            self.values = np.insert(self.values, i, v)
            self.n += 1
            return True
        self.words[int(v) >> 5] |= np.uint32(1) << np.uint32(int(v) & 31)
        self.n += 1
        return True

    def remove(self, v):
        if not self.contains(v):
            return False
        v = np.uint16(v)
        if self.typ == TYPE_RUN:
            self._run_to_bitmap_or_array()
            return self.remove(v)
        if self.typ == TYPE_ARRAY:
            i = int(np.searchsorted(self.values, v))
            self.values = np.delete(self.values, i)
            self.n -= 1
            return True
        self.words[int(v) >> 5] &= ~(np.uint32(1) << np.uint32(int(v) & 31))
        self.n -= 1
        if self.n <= ARRAY_MAX_SIZE // 2:
            # Hysteresis: convert back lazily only when well below threshold.
            self.values = words_to_values(self.words)
            self.words = None
            self.typ = TYPE_ARRAY
        return True

    def add_many(self, values):
        """Bulk union of a sorted-or-not uint16 batch; returns change count."""
        if len(values) == 0:
            return 0
        words = self.to_dense_words().copy()
        before = self.n
        add = values_to_words(np.asarray(values, dtype=np.uint16))
        words |= add
        n = int(np.sum(popcount32(words)))
        self._become_dense(words, n)
        return n - before

    def remove_many(self, values):
        if len(values) == 0:
            return 0
        words = self.to_dense_words().copy()
        before = self.n
        words &= ~values_to_words(np.asarray(values, dtype=np.uint16))
        n = int(np.sum(popcount32(words)))
        self._become_dense(words, n)
        return before - n

    def _become_dense(self, words, n):
        if n <= ARRAY_MAX_SIZE:
            self.typ, self.values, self.words, self.runs = (
                TYPE_ARRAY, words_to_values(words), None, None)
        else:
            self.typ, self.values, self.words, self.runs = (
                TYPE_BITMAP, None, words, None)
        self.n = n

    def _array_to_bitmap(self):
        self.words = values_to_words(self.values)
        self.values = None
        self.typ = TYPE_BITMAP

    def _run_to_bitmap_or_array(self):
        words = self.to_dense_words().copy()
        self._become_dense(words, self.n)

    # -- dense conversion (the TPU upload path) -----------------------------

    def to_dense_words(self):
        """[2048] uint32 dense words (shared buffer for bitmap containers)."""
        if self.typ == TYPE_BITMAP:
            return self.words
        if self.typ == TYPE_ARRAY:
            return values_to_words(self.values)
        words = np.zeros(WORDS, dtype=np.uint32)
        for s, l in self.runs:
            _fill_run(words, int(s), int(l))
        return words

    def to_values(self):
        """Sorted uint16 values."""
        if self.typ == TYPE_ARRAY:
            return self.values
        if self.typ == TYPE_RUN:
            if len(self.runs) == 0:
                return np.empty(0, dtype=np.uint16)
            return np.concatenate(
                [np.arange(int(s), int(l) + 1, dtype=np.uint16) for s, l in self.runs])
        return words_to_values(self.words)

    def to_runs(self):
        """[R,2] uint16 [start,last] inclusive intervals."""
        from .. import native

        if self.typ == TYPE_RUN:
            return self.runs
        if self.typ == TYPE_BITMAP:
            return native.extract_runs(self.words)
        values = self.to_values().astype(np.int64)
        if len(values) == 0:
            return np.empty((0, 2), dtype=np.uint16)
        breaks = np.nonzero(np.diff(values) != 1)[0]
        starts = np.concatenate([[0], breaks + 1])
        ends = np.concatenate([breaks, [len(values) - 1]])
        return np.stack([values[starts], values[ends]], axis=1).astype(np.uint16)

    def optimized(self):
        """Most compact representation, using the reference's selection rule
        (Container.optimize roaring.go:2334-2348): run when run count is both
        <= runMaxSize and <= n/2; else array when n < ArrayMaxSize; else
        bitmap."""
        if self.n == 0:
            return self
        runs = self.to_runs()
        if len(runs) <= RUN_MAX_SIZE and len(runs) <= self.n // 2:
            best = TYPE_RUN
        elif self.n < ARRAY_MAX_SIZE:
            best = TYPE_ARRAY
        else:
            best = TYPE_BITMAP
        if best == self.typ:
            return self
        if best == TYPE_RUN:
            return Container(TYPE_RUN, runs=runs, n=self.n)
        if best == TYPE_ARRAY:
            return Container(TYPE_ARRAY, values=self.to_values(), n=self.n)
        return Container(TYPE_BITMAP, words=self.to_dense_words().copy(), n=self.n)

    def serialized_size(self):
        if self.typ == TYPE_ARRAY:
            return 2 * self.n
        if self.typ == TYPE_RUN:
            return 2 + 4 * len(self.runs)
        return BITMAP_BYTES

    def clone(self):
        return Container(
            self.typ,
            values=None if self.values is None else self.values.copy(),
            words=None if self.words is None else self.words.copy(),
            runs=None if self.runs is None else self.runs.copy(),
            n=self.n,
        )


def _fill_run(words, start, last):
    from .. import native

    native.fill_range(words, start, last)


def popcount32(words):
    from .. import native

    if words.dtype != np.uint32:
        words = words.astype(np.uint32)
    return native.popcount_per_word(words)


def values_to_words(values):
    from .. import native

    words = np.zeros(WORDS, dtype=np.uint32)
    if len(values):
        native.scatter_u16(np.asarray(values, dtype=np.uint16), words)
    return words


def words_to_values(words):
    """Dense words -> sorted uint16 values."""
    from .. import native

    return native.extract_u16(words)


def container_check(c):
    """Invariant violations of one container as a list of strings
    (reference: Container.check roaring.go:3010)."""
    errors = []
    if c.typ == TYPE_ARRAY:
        if c.values is None:
            return ["array container without values"]
        if len(c.values) != c.n:
            errors.append(f"n={c.n} but {len(c.values)} values")
        if len(c.values) > 1 and not np.all(np.diff(
                c.values.astype(np.int64)) > 0):
            errors.append("array values not sorted unique")
    elif c.typ == TYPE_BITMAP:
        if c.words is None or len(c.words) != WORDS:
            return ["bitmap container with wrong word count"]
        actual = int(np.sum(popcount32(c.words)))
        if actual != c.n:
            errors.append(f"n={c.n} but {actual} bits set")
    elif c.typ == TYPE_RUN:
        runs = c.runs
        if runs is None:
            return ["run container without runs"]
        last_end = -1
        total = 0
        for s, l in runs:
            s, l = int(s), int(l)
            if s <= last_end:
                errors.append(f"run [{s},{l}] overlaps/unsorted")
            if l < s:
                errors.append(f"run [{s},{l}] inverted")
            total += l - s + 1
            last_end = l
        if total != c.n:
            errors.append(f"n={c.n} but runs cover {total}")
    else:
        errors.append(f"unknown type {c.typ}")
    return errors
