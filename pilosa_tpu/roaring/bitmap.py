"""64-bit roaring bitmap (host side).

The reference's `roaring.Bitmap` (roaring/roaring.go:145) is both the storage
format and the compute engine. Here it is storage + mutation only: a sorted
map of container-key -> Container, where key = bit >> 16. Set algebra runs on
device planes; this class feeds the dense upload path and the (de)serializer.
"""

import bisect

import numpy as np

from .containers import Container, container_check, popcount32

CONTAINER_BITS = 1 << 16
MAX_CONTAINER_KEY = (1 << 48) - 1  # reference: roaring/roaring.go:60


class Bitmap:
    """Mutable 64-bit bitmap over sorted containers."""

    __slots__ = ("containers", "_keys", "ops", "op_n")

    def __init__(self):
        self.containers = {}  # key -> Container
        self._keys = []  # sorted container keys
        # In-memory op log (WAL). The fragment layer appends serialized ops
        # to the storage file; this list only tracks unsnapshotted op count.
        self.ops = 0
        self.op_n = 0

    # -- construction -------------------------------------------------------

    @classmethod
    def from_bits(cls, bits):
        b = cls()
        b.add_many(bits)
        return b

    # -- container map ------------------------------------------------------

    def _get(self, key, create=False):
        c = self.containers.get(key)
        if c is None and create:
            c = Container()
            self.containers[key] = c
            bisect.insort(self._keys, key)
        return c

    def _drop_if_empty(self, key):
        c = self.containers.get(key)
        if c is not None and c.n == 0:
            del self.containers[key]
            self._keys.remove(key)

    def keys(self):
        return self._keys

    # -- mutation -----------------------------------------------------------

    def add(self, bit):
        """DirectAdd (reference: roaring.go:228). Returns changed."""
        bit = int(bit)
        return self._get(bit >> 16, create=True).add(bit & 0xFFFF)

    def remove(self, bit):
        bit = int(bit)
        key = bit >> 16
        c = self.containers.get(key)
        if c is None:
            return False
        changed = c.remove(bit & 0xFFFF)
        if changed:
            self._drop_if_empty(key)
        return changed

    def add_many(self, bits):
        """Vectorized bulk add; returns number of newly-set bits
        (reference: DirectAddN)."""
        bits = np.asarray(bits, dtype=np.uint64)
        if len(bits) == 0:
            return 0
        keys = bits >> np.uint64(16)
        low = (bits & np.uint64(0xFFFF)).astype(np.uint16)
        changed = 0
        order = np.argsort(keys, kind="stable")
        keys, low = keys[order], low[order]
        boundaries = np.concatenate(
            [[0], np.nonzero(np.diff(keys))[0] + 1, [len(keys)]])
        for i in range(len(boundaries) - 1):
            s, e = boundaries[i], boundaries[i + 1]
            key = int(keys[s])
            changed += self._get(key, create=True).add_many(low[s:e])
        return changed

    def remove_many(self, bits):
        bits = np.asarray(bits, dtype=np.uint64)
        if len(bits) == 0:
            return 0
        keys = bits >> np.uint64(16)
        low = (bits & np.uint64(0xFFFF)).astype(np.uint16)
        changed = 0
        order = np.argsort(keys, kind="stable")
        keys, low = keys[order], low[order]
        boundaries = np.concatenate(
            [[0], np.nonzero(np.diff(keys))[0] + 1, [len(keys)]])
        for i in range(len(boundaries) - 1):
            s, e = boundaries[i], boundaries[i + 1]
            key = int(keys[s])
            c = self.containers.get(key)
            if c is None:
                continue
            changed += c.remove_many(low[s:e])
            self._drop_if_empty(key)
        return changed

    # -- queries (host-side; only used off the hot path) --------------------

    def contains(self, bit):
        bit = int(bit)
        c = self.containers.get(bit >> 16)
        return c is not None and c.contains(bit & 0xFFFF)

    def count(self):
        return sum(c.n for c in self.containers.values())

    def count_range(self, start, end):
        """Count of set bits in [start, end) (reference: CountRange)."""
        total = 0
        for key in self._keys:
            base = key << 16
            if base >= end:
                break
            if base + CONTAINER_BITS <= start:
                continue
            c = self.containers[key]
            if start <= base and base + CONTAINER_BITS <= end:
                total += c.n
            else:
                vals = c.to_values().astype(np.int64) + base
                total += int(np.sum((vals >= start) & (vals < end)))
        return total

    def slice_range(self, start, end):
        """Sorted bit positions in [start, end) (reference: SliceRange)."""
        out = []
        for key in self._keys:
            base = key << 16
            if base >= end:
                break
            if base + CONTAINER_BITS <= start:
                continue
            vals = self.containers[key].to_values().astype(np.uint64) + np.uint64(base)
            if start > base or base + CONTAINER_BITS > end:
                vals = vals[(vals >= start) & (vals < end)]
            out.append(vals)
        if not out:
            return np.empty(0, dtype=np.uint64)
        return np.concatenate(out)

    def max(self):
        if not self._keys:
            return 0
        key = self._keys[-1]
        return (key << 16) | int(self.containers[key].to_values()[-1])

    def any(self):
        return bool(self._keys)

    # -- dense plane interface (TPU upload path) ----------------------------

    def dense_range_words(self, key_start, key_count):
        """Concatenate dense words for containers [key_start, key_start+key_count)
        into one [key_count*2048] uint32 plane. This is the reference's
        OffsetRange row-slicing (roaring.go:537) recast as densification."""
        from ..shardwidth import WORDS_PER_CONTAINER

        plane = np.zeros(key_count * WORDS_PER_CONTAINER, dtype=np.uint32)
        i = bisect.bisect_left(self._keys, key_start)
        while i < len(self._keys) and self._keys[i] < key_start + key_count:
            key = self._keys[i]
            off = (key - key_start) * WORDS_PER_CONTAINER
            plane[off:off + WORDS_PER_CONTAINER] = self.containers[key].to_dense_words()
            i += 1
        return plane

    def merge_dense_words(self, key_start, plane, clear=False):
        """Inverse of dense_range_words: fold a dense plane back into
        containers (set union, or clear when clear=True). Returns changed
        bit count. Used by snapshotting and Store/ClearRow writes."""
        from ..shardwidth import WORDS_PER_CONTAINER

        changed = 0
        n_keys = len(plane) // WORDS_PER_CONTAINER
        for k in range(n_keys):
            words = plane[k * WORDS_PER_CONTAINER:(k + 1) * WORDS_PER_CONTAINER]
            if not words.any():
                continue
            key = key_start + k
            c = self._get(key, create=not clear)
            if c is None:
                continue
            merged = c.to_dense_words().copy()
            if clear:
                merged &= ~words
            else:
                merged |= words
            n = int(np.sum(popcount32(merged)))
            delta = n - c.n
            c._become_dense(merged, n)
            changed += abs(delta)
            self._drop_if_empty(key)
        return changed

    def replace_dense_words(self, key_start, key_count, plane):
        """Overwrite containers [key_start, key_start+key_count) with plane
        contents exactly (used when writing back a fully-computed row)."""
        from ..shardwidth import WORDS_PER_CONTAINER

        for k in range(key_count):
            key = key_start + k
            words = np.ascontiguousarray(
                plane[k * WORDS_PER_CONTAINER:(k + 1) * WORDS_PER_CONTAINER])
            n = int(np.sum(popcount32(words)))
            if n == 0:
                if key in self.containers:
                    del self.containers[key]
                    self._keys.remove(key)
                continue
            c = self._get(key, create=True)
            c._become_dense(words.copy(), n)

    def clone(self):
        b = Bitmap()
        b.containers = {k: c.clone() for k, c in self.containers.items()}
        b._keys = list(self._keys)
        return b

    # -- invariants (reference: roaring_paranoia.go roaringParanoia tag,
    #    Bitmap.Check roaring.go:1664, Container.check :3010) --------------

    def check(self):
        """Validate every structural invariant; raises AssertionError with
        all violations. Enabled on hot paths by PILOSA_TPU_PARANOIA=1
        (the reference's paranoid-build analog)."""
        errors = []
        if any(a >= b for a, b in zip(self._keys, self._keys[1:])):
            errors.append("container keys not strictly increasing")
        if len(self._keys) != len(self.containers) or \
                set(self._keys) != set(self.containers):
            errors.append("key list and container map disagree")
        for key, c in self.containers.items():
            errors.extend(
                f"container {key}: {e}" for e in container_check(c))
        if errors:
            raise AssertionError("bitmap invariants violated: "
                                 + "; ".join(errors))
        return True
