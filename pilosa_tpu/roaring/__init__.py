"""Host-side roaring bitmap layer: storage + interchange format.

The reference's roaring package is its compute engine; here it is the at-rest
format feeding the dense TPU plane path (see pilosa_tpu.ops)."""

from .bitmap import Bitmap, CONTAINER_BITS, MAX_CONTAINER_KEY
from .codec import (
    FormatError,
    MAGIC_NUMBER,
    OP_ADD,
    OP_ADD_BATCH,
    OP_ADD_ROARING,
    OP_REMOVE,
    OP_REMOVE_BATCH,
    OP_REMOVE_ROARING,
    decode_op,
    deserialize,
    encode_op,
    merge_bitmaps,
    serialize,
)
from .containers import Container
