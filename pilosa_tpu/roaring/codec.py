"""Roaring file codec: Pilosa variant (read+write) and official spec (read).

Format (reference: docs/architecture.md:9-25, roaring/roaring.go:30-62,
writeToUnoptimized roaring.go:1054-1127, pilosa/official iterators
roaring.go:1174-1420):

Pilosa variant, all little-endian:
  bytes 0-1  magic 12348
  byte  2    storage version (0)
  byte  3    user flags (bit 0 = BSI v2 marker, fragment.go:97)
  bytes 4-7  container count
  then per-container descriptive header (12B): key u64, type u16 (1=array,
    2=bitmap, 3=run), cardinality-1 u16
  then per-container offset header (4B): absolute byte offset of payload
  payloads: array = n×u16; bitmap = 8192B; run = count u16 + count×[start,last] u16
  then an op log until EOF (see ops below).

Official spec (read-only import path): cookie 12346 (no runs; count u32
follows) or 12347 (count-1 in cookie high 16 bits; run-flag bitset follows);
16-bit keys; runs stored as [start, length].

Ops (reference: op.WriteTo/UnmarshalBinary roaring.go:4694-4793): 13-byte
header = type u8, value u64, fnv1a-32 checksum u32 (over bytes 0:9 plus
payload); batch ops append count×u64 values at byte 13; roaring ops append
opN u32 then an embedded roaring blob.
"""

import struct

import numpy as np

from .bitmap import Bitmap
from .containers import (
    ARRAY_MAX_SIZE,
    BITMAP_BYTES,
    Container,
    TYPE_ARRAY,
    TYPE_BITMAP,
    TYPE_RUN,
)

MAGIC_NUMBER = 12348
STORAGE_VERSION = 0
HEADER_BASE_SIZE = 8
OFFICIAL_COOKIE = 12346  # serialCookieNoRunContainer
OFFICIAL_COOKIE_RUNS = 12347  # serialCookie

OP_ADD = 0
OP_REMOVE = 1
OP_ADD_BATCH = 2
OP_REMOVE_BATCH = 3
OP_ADD_ROARING = 4
OP_REMOVE_ROARING = 5


class FormatError(Exception):
    pass


def fnv1a32(*chunks):
    from .. import native

    h = 2166136261
    for chunk in chunks:
        h = native.fnv1a32(chunk, h)
    return h


# ---------------------------------------------------------------------------
# Serialization (Pilosa format)
# ---------------------------------------------------------------------------

def serialize(bitmap, flags=0, optimize=True):
    """Bitmap -> Pilosa-format bytes (no op log — the WAL is appended by the
    fragment storage layer)."""
    items = []
    for key in bitmap.keys():
        c = bitmap.containers[key]
        if c.n == 0:
            continue
        items.append((key, c.optimized() if optimize else c))

    out = bytearray()
    out += struct.pack("<HBB", MAGIC_NUMBER, STORAGE_VERSION, flags)
    out += struct.pack("<I", len(items))
    for key, c in items:
        out += struct.pack("<QHH", key, c.typ, c.n - 1)
    offset = HEADER_BASE_SIZE + len(items) * 16
    for _, c in items:
        out += struct.pack("<I", offset)
        offset += c.serialized_size()
    for _, c in items:
        out += _container_payload(c)
    return bytes(out)


def _container_payload(c):
    if c.typ == TYPE_ARRAY:
        return np.ascontiguousarray(c.values, dtype="<u2").tobytes()
    if c.typ == TYPE_BITMAP:
        return np.ascontiguousarray(c.words, dtype="<u4").tobytes()
    runs = np.ascontiguousarray(c.runs, dtype="<u2")
    return struct.pack("<H", len(runs)) + runs.tobytes()


# ---------------------------------------------------------------------------
# Deserialization
# ---------------------------------------------------------------------------

def deserialize(data, with_ops=True):
    """Bytes -> (Bitmap, flags, op_count). Accepts both Pilosa and official
    formats; replays any trailing op log (Pilosa format only)."""
    if len(data) < 8:
        raise FormatError(f"buffer too small: {len(data)} bytes")
    magic = struct.unpack_from("<H", data, 0)[0]
    if magic == MAGIC_NUMBER:
        return _deserialize_pilosa(data, with_ops)
    cookie = struct.unpack_from("<I", data, 0)[0]
    if cookie == OFFICIAL_COOKIE or cookie & 0xFFFF == OFFICIAL_COOKIE_RUNS:
        b, pos = _deserialize_official(data)
        return b, 0, 0
    raise FormatError(f"unknown roaring magic: {magic}")


def _deserialize_pilosa(data, with_ops):
    version = data[2]
    if version != STORAGE_VERSION:
        raise FormatError(f"wrong roaring version: {version}")
    flags = data[3]
    n_keys = struct.unpack_from("<I", data, 4)[0]
    b = Bitmap()
    if n_keys == 0:
        op_count = _replay_ops(b, data, HEADER_BASE_SIZE) if with_ops and len(data) > HEADER_BASE_SIZE else 0
        return b, flags, op_count

    header_end = HEADER_BASE_SIZE + n_keys * 12
    offsets_end = header_end + n_keys * 4
    if len(data) < offsets_end:
        raise FormatError("insufficient data for headers")

    last_end = offsets_end
    for i in range(n_keys):
        key, typ, n_minus_1 = struct.unpack_from("<QHH", data, HEADER_BASE_SIZE + i * 12)
        n = n_minus_1 + 1
        offset = struct.unpack_from("<I", data, header_end + i * 4)[0]
        c, end = _read_container(data, offset, typ, n)
        b.containers[key] = c
        b._keys.append(key)
        last_end = max(last_end, end)
    b._keys.sort()

    op_count = _replay_ops(b, data, last_end) if with_ops and len(data) > last_end else 0
    return b, flags, op_count


def _read_container(data, offset, typ, n):
    try:
        if typ == TYPE_ARRAY:
            end = offset + 2 * n
            values = np.frombuffer(data, dtype="<u2", count=n, offset=offset).copy()
            return Container(TYPE_ARRAY, values=values, n=n), end
        if typ == TYPE_BITMAP:
            end = offset + BITMAP_BYTES
            words = np.frombuffer(
                data, dtype="<u4", count=BITMAP_BYTES // 4, offset=offset).copy()
            return Container(TYPE_BITMAP, words=words, n=n), end
        if typ == TYPE_RUN:
            run_count = struct.unpack_from("<H", data, offset)[0]
            end = offset + 2 + 4 * run_count
            runs = np.frombuffer(
                data, dtype="<u2", count=run_count * 2, offset=offset + 2)
            return Container(TYPE_RUN, runs=runs.reshape(-1, 2).copy(), n=n), end
    except (ValueError, struct.error) as e:
        raise FormatError(f"truncated container payload at {offset}: {e}") from e
    raise FormatError(f"unknown container type {typ}")


def _deserialize_official(data):
    cookie = struct.unpack_from("<I", data, 0)[0]
    pos = 4
    if cookie == OFFICIAL_COOKIE:
        n_keys = struct.unpack_from("<I", data, pos)[0]
        pos += 4
        run_flags = None
    else:
        n_keys = (cookie >> 16) + 1
        nbytes = (n_keys + 7) // 8
        run_flags = data[pos:pos + nbytes]
        pos += nbytes

    headers = []
    for i in range(n_keys):
        key, card_minus_1 = struct.unpack_from("<HH", data, pos)
        pos += 4
        headers.append((key, card_minus_1 + 1))

    # Offset section: always present in the no-runs variant; in the runs
    # variant the official spec writes it when there are >= 4 containers
    # (NO_OFFSET_THRESHOLD). Payloads are walked sequentially either way.
    if run_flags is None or n_keys >= 4:
        pos += 4 * n_keys

    b = Bitmap()
    try:
        _, pos = _read_official_payloads(b, data, pos, headers, run_flags)
    except (ValueError, struct.error) as e:
        raise FormatError(f"truncated official container payload: {e}") from e
    return b, pos


def _read_official_payloads(b, data, pos, headers, run_flags):
    for i, (key, n) in enumerate(headers):
        is_run = run_flags is not None and (run_flags[i // 8] >> (i % 8)) & 1
        if is_run:
            run_count = struct.unpack_from("<H", data, pos)[0]
            pos += 2
            runs = np.frombuffer(data, dtype="<u2", count=run_count * 2, offset=pos).reshape(-1, 2).astype(np.uint32)
            pos += 4 * run_count
            # Official runs are [start, length-1]; convert to [start, last].
            runs[:, 1] = runs[:, 0] + runs[:, 1]
            c = Container(TYPE_RUN, runs=runs.astype(np.uint16), n=n)
        elif n <= ARRAY_MAX_SIZE:
            values = np.frombuffer(data, dtype="<u2", count=n, offset=pos).copy()
            pos += 2 * n
            c = Container(TYPE_ARRAY, values=values, n=n)
        else:
            words = np.frombuffer(data, dtype="<u4", count=BITMAP_BYTES // 4, offset=pos).copy()
            pos += BITMAP_BYTES
            c = Container(TYPE_BITMAP, words=words, n=n)
        b.containers[key] = c
        b._keys.append(key)
    return b, pos


# ---------------------------------------------------------------------------
# Op log
# ---------------------------------------------------------------------------

def encode_op(typ, value=0, values=None, roaring=None, op_n=0):
    if typ in (OP_ADD, OP_REMOVE):
        head = struct.pack("<BQ", typ, value)
        chk = fnv1a32(head)
        return head + struct.pack("<I", chk)
    if typ in (OP_ADD_BATCH, OP_REMOVE_BATCH):
        values = np.asarray(values, dtype="<u8")
        head = struct.pack("<BQ", typ, len(values))
        payload = values.tobytes()
        chk = fnv1a32(head, payload)
        return head + struct.pack("<I", chk) + payload
    if typ in (OP_ADD_ROARING, OP_REMOVE_ROARING):
        head = struct.pack("<BQ", typ, len(roaring))
        payload = struct.pack("<I", op_n)
        chk = fnv1a32(head, payload, roaring)
        return head + struct.pack("<I", chk) + payload + roaring
    raise ValueError(f"unknown op type {typ}")


def decode_op(data, pos):
    """Decode one op at pos; returns (typ, value, values, roaring, op_n, next_pos).
    Raises FormatError on truncation/corruption (the fragment layer treats a
    bad tail as end-of-log, like the reference's op-log replay)."""
    if len(data) - pos < 13:
        raise FormatError("op truncated")
    typ = data[pos]
    value = struct.unpack_from("<Q", data, pos + 1)[0]
    chk = struct.unpack_from("<I", data, pos + 9)[0]
    head = data[pos:pos + 9]
    if typ in (OP_ADD, OP_REMOVE):
        if fnv1a32(head) != chk:
            raise FormatError("op checksum mismatch")
        return typ, value, None, None, 0, pos + 13
    if typ in (OP_ADD_BATCH, OP_REMOVE_BATCH):
        end = pos + 13 + value * 8
        if len(data) < end:
            raise FormatError("batch op truncated")
        payload = data[pos + 13:end]
        if fnv1a32(head, payload) != chk:
            raise FormatError("op checksum mismatch")
        values = np.frombuffer(payload, dtype="<u8")
        return typ, 0, values, None, 0, end
    if typ in (OP_ADD_ROARING, OP_REMOVE_ROARING):
        end = pos + 17 + value
        if len(data) < end:
            raise FormatError("roaring op truncated")
        op_n = struct.unpack_from("<I", data, pos + 13)[0]
        roaring = data[pos + 17:end]
        if fnv1a32(head, data[pos + 13:pos + 17], roaring) != chk:
            raise FormatError("op checksum mismatch")
        return typ, 0, None, roaring, op_n, end
    raise FormatError(f"unknown op type {typ}")


def _replay_ops(bitmap, data, pos):
    """Apply the op log to a freshly-loaded bitmap (reference: op.apply
    roaring.go:4671, replay in unmarshal path). Returns op count applied."""
    count = 0
    while pos < len(data):
        try:
            typ, value, values, roaring, op_n, pos = decode_op(data, pos)
        except FormatError:
            break
        if typ == OP_ADD:
            bitmap.add(value)
        elif typ == OP_REMOVE:
            bitmap.remove(value)
        elif typ == OP_ADD_BATCH:
            bitmap.add_many(values)
        elif typ == OP_REMOVE_BATCH:
            bitmap.remove_many(values)
        elif typ == OP_ADD_ROARING:
            other, _, _ = deserialize(roaring, with_ops=False)
            merge_bitmaps(bitmap, other, clear=False)
        elif typ == OP_REMOVE_ROARING:
            other, _, _ = deserialize(roaring, with_ops=False)
            merge_bitmaps(bitmap, other, clear=True)
        count += 1
    return count


def merge_bitmaps(dst, src, clear=False):
    """Union (or clear) src into dst container-by-container (reference:
    ImportRoaringBits roaring.go:1511). Returns changed bit count."""
    changed = 0
    for key in src.keys():
        words = src.containers[key].to_dense_words()
        changed += dst.merge_dense_words(key, words, clear=clear)
    return changed
