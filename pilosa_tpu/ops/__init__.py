"""Device kernel layer — the TPU-native equivalent of the reference's roaring
container kernels (reference: roaring/roaring.go:3121-5196)."""

from . import bitplane, bsi
from .bitplane import (
    any_set,
    columns_from_plane,
    count_intersect,
    difference,
    intersect,
    not_,
    plane_from_columns,
    popcount,
    popcount_rows,
    shift,
    topn_counts,
    union,
    union_rows,
    xor,
)
