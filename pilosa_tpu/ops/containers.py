"""Device-resident compressed plane containers.

Dense [S, W] uint32 plane stacks (ops/bitplane.py) make every Count scan
S * W * 4 bytes of HBM — BENCH r03 measured the serving path at 89.6% of
HBM peak, so bytes-moved is the wall (ROADMAP item 2). The reference
never pays this: roaring picks array/bitmap/run representation per 64K
block by density (reference: roaring/roaring.go container types;
PAPER.md §2.1). This module is the device analogue — per-fragment
representation choice with kernels that count compressed blocks
directly, never materializing the dense plane:

  dense   — today's format: one [S, W] uint32 stack (the escape hatch;
            forced-dense serving is bit-identical by construction
            because it IS the legacy array).
  sparse  — block-sparse: only the non-empty BLOCK_WORDS-word blocks
            survive, as (block_ids [NB] int32 sorted, blocks [NB, BW]
            uint32). Ids linearize (shard, block) row-major; padding
            uses an out-of-range sentinel id with zero blocks, so
            scatters drop it and popcounts ignore it.
  rle     — run-length: sorted disjoint [start, end) bit intervals as
            (run_shard, run_start, run_end) int32 triples with
            shard-relative offsets (the device analogue of roaring run
            containers). Padding runs are (shard=-1, 0, 0): empty and
            matching no real shard.

Counting discipline: the dense path keeps the per-shard hi_lo split
(ops/bitplane.hi_lo). Compressed direct counts reduce to ONE int32
total and split it as (t >> 16, t & 0xffff) — exact under the
combine_hi_lo contract because (hi << 16) + lo == t for any t >= 0 that
fits int32, which the chooser guarantees by refusing to compress a
stack whose bit capacity S * SHARD_WIDTH reaches 2^31 (same gate as the
Pallas pairwise kernels).

The chooser is deterministic in the host data (measured density /
non-empty blocks / run count — no sampling, no feedback loop), so a
rebuild of unchanged data always re-picks the same representation
(chooser-stability contract). The per-fragment choice is recorded in a
module ledger keyed (index, field, view) that the cost model, /debug/hbm
compression ratios, and /debug/heat admission pricing all read.

Layering: this module owns representations + kernels; exec/stacked.py
owns the cached placement, the chooser call site, and the jitted
serving programs (it passes its _tree_eval in, so expression semantics
stay defined in exactly one place).
"""

import os
import threading

import numpy as np

from ..shardwidth import SHARD_WIDTH, WORDS_PER_ROW

__all__ = [
    "BLOCK_WORDS",
    "Container",
    "OVERLAY_MAX_TERMS",
    "analyze",
    "build",
    "dense_container",
    "configure",
    "repr_mode",
    "kind_of",
    "flatten",
    "flat_arity",
    "norm_csig",
    "unflatten",
    "count_program",
    "plane_program",
    "with_overlay",
    "overlay_rows",
    "container_to_dense",
    "fragment_estimate",
    "field_estimate",
    "fragment_ledger",
    "reset_ledger",
]

#: words per block-sparse block: 128 words = 4096 bits = one VPU-friendly
#: [8, 128]-shaped tile per block on device. W is always a multiple
#: (WORDS_PER_ROW = 2^(exp-5) >= 2^11 for the supported exponent range).
BLOCK_WORDS = 128

#: sentinel block id for sparse padding: out of range for any real
#: (shard, block) by the sparse eligibility gate, so `.at[ids].set(...,
#: mode="drop")` discards padding and searchsorted matches pad-to-pad
#: only (whose blocks are zero — count-neutral either way).
SPARSE_SENTINEL = 1 << 30

#: auto-chooser caps: rle only pays off when the run count is small, and
#: the pairwise intersect kernel is O(NA * NB) — keep both bounded.
MAX_RLE_RUNS = 4096
MAX_RLE_PAIR = 1 << 22

#: a compressed representation must at least halve the bytes before auto
#: picks it — hysteresis against flapping near break-even, and it keeps
#: the (cheap, fused) dense kernels for data that barely compresses.
COMPRESS_ADVANTAGE = 0.5

#: auto only compresses fragments whose dense stack is at least this
#: big. Below the floor the dense plane is cheap anyway, while the
#: compressed forms fragment the serving jit-key space — every (tree,
#: container-signature) pair is its own compiled program, so a host
#: full of small fragments pays far more in compiles and cache pressure
#: than it saves in HBM. The floor (default 4 MiB ≈ a 32-shard stack)
#: keeps auto inert at toy scale and targets the actual bandwidth wall;
#: forced sparse/rle ignore it (differential tests and capacity
#: experiments run at CPU scale), and ops can lower it with
#: PILOSA_TPU_COMPRESS_FLOOR.
AUTO_COMPRESS_FLOOR = int(os.environ.get(
    "PILOSA_TPU_COMPRESS_FLOOR", 4 << 20))

_ARITY = {"dense": 1, "sparse": 2, "rle": 3}
_MODES = ("auto", "dense", "sparse", "rle")

#: max pending-delta overlay terms a compressed container accumulates
#: before the ingest merge forces a full rebuild (repr re-chosen from
#: the measured density). Each term adds a (kind, S, T) program variant
#: to the jit-key space, so the cap bounds compile churn too.
OVERLAY_MAX_TERMS = 4

_MODE_LOCK = threading.Lock()
_MODE = os.environ.get("PILOSA_TPU_CONTAINER_REPR", "auto")
if _MODE not in _MODES:
    _MODE = "auto"


def configure(repr_mode=None):
    """Apply --container-repr (auto|dense|sparse|rle). `dense` is the
    bit-identical escape hatch; `sparse`/`rle` force a representation
    where eligible (int32-safety gates still win) — for differential
    tests and capacity experiments."""
    global _MODE
    if repr_mode is None:
        return
    if repr_mode not in _MODES:
        raise ValueError(
            f"container repr must be one of {'|'.join(_MODES)}: "
            f"{repr_mode!r}")
    with _MODE_LOCK:
        _MODE = repr_mode


def repr_mode():
    return _MODE


# ------------------------------------------------------------------ ledger
#
# Per-leaf representation ledger: what the chooser last picked for each
# built leaf — keyed (index, field, view[, leaf]) since different rows
# of one fragment pick independently. Read by exec/plan.py (compressed
# bytes_touched estimates for non-resident leaves), /debug/hbm
# (compression ratios), and utils/workload.py (admission candidates
# priced by compressed bytes). Writes happen at stack-build time only —
# never on the per-query hot path.

_LEDGER_LOCK = threading.Lock()
_LEDGER = {}


def _ledger_note(fragment, kind, nbytes, dense_bytes, density):
    if fragment is None:
        return
    entry = {
        "repr": kind,
        "bytes": int(nbytes),
        "dense_bytes": int(dense_bytes),
        "ratio": round(dense_bytes / nbytes, 3) if nbytes else 1.0,
        "density": round(float(density), 6),
    }
    with _LEDGER_LOCK:
        _LEDGER[tuple(fragment)] = entry


def fragment_estimate(index, field, view, leaf=None):
    """Build-ledger estimate for one leaf of an (index, field, view)
    fragment: the exact record when `leaf` (e.g. a row id) was built
    before, else the per-leaf mean over every leaf of the fragment with
    the most common repr (different rows of one fragment legitimately
    pick different representations). None when never built."""
    with _LEDGER_LOCK:
        if leaf is not None:
            e = _LEDGER.get((index, field, view, leaf))
            if e is not None:
                return dict(e)
        entries = [e for k, e in _LEDGER.items()
                   if k[:3] == (index, field, view)]
    if not entries:
        return None
    n = len(entries)
    kinds = {}
    for e in entries:
        kinds[e["repr"]] = kinds.get(e["repr"], 0) + 1
    bytes_mean = sum(e["bytes"] for e in entries) // n
    dense_mean = sum(e["dense_bytes"] for e in entries) // n
    return {"repr": max(sorted(kinds), key=lambda k: kinds[k]),
            "bytes": bytes_mean,
            "dense_bytes": dense_mean,
            "ratio": round(dense_mean / bytes_mean, 3)
            if bytes_mean else 1.0,
            "density": round(
                sum(e["density"] for e in entries) / n, 6)}


def field_estimate(index, field):
    """Aggregate over every built leaf for the /debug/heat admission
    join (heat is summed at (index, field) there too — the sum prices
    re-admitting the field's whole built working set): {bytes,
    dense_bytes, ratio, reprs} or None."""
    total = dense = 0
    kinds = set()
    with _LEDGER_LOCK:
        for k, e in _LEDGER.items():
            if k[0] == index and k[1] == field:
                total += e["bytes"]
                dense += e["dense_bytes"]
                kinds.add(e["repr"])
    if not kinds:
        return None
    return {"bytes": total, "dense_bytes": dense,
            "ratio": round(dense / total, 3) if total else 1.0,
            "reprs": sorted(kinds)}


def fragment_ledger():
    """Snapshot for /debug surfaces: {"index/field/view": entry}."""
    with _LEDGER_LOCK:
        return {"/".join(map(str, k)): dict(e) for k, e in _LEDGER.items()}


def reset_ledger():
    with _LEDGER_LOCK:
        _LEDGER.clear()
        _REPR_OVERRIDES.clear()


# Per-(index, field) representation overrides from the adaptive layer's
# misestimate feedback: a fragment whose container_repr plan repeatedly
# reads MORE bytes than the dense scan it displaced gets forced dense at
# its next rebuild. Consulted in build() only under auto mode — forced
# --container-repr modes are the operator's word and win.
_REPR_OVERRIDES = {}  # (index, field) -> kind


def set_repr_override(index, field, kind):
    if kind not in _ARITY:
        raise ValueError(f"unknown container repr: {kind!r}")
    with _LEDGER_LOCK:
        _REPR_OVERRIDES[(index, field)] = kind


def repr_override(index, field):
    with _LEDGER_LOCK:
        return _REPR_OVERRIDES.get((index, field))


def repr_overrides():
    with _LEDGER_LOCK:
        return {f"{i}/{f}": k for (i, f), k in _REPR_OVERRIDES.items()}


# --------------------------------------------------------------- container


class Container:
    """One leaf fragment's device-resident plane stack in one of the
    three representations. `arrays` are the device buffers (arity by
    kind: dense 1, sparse 2, rle 3); `shape` is the logical dense
    [S, W]; `nbytes` the device bytes actually held (what the HBM
    ledger charges); `meta` the chooser's analysis (dense_bytes,
    density, ratio) for /debug/hbm.

    `overlay` counts pending-delta overlay terms parked after the base
    arrays by the streaming ingest merge (exec/ingest.py): each term is
    an (idx [K] int32, planes [K, W] uint32) pair of full replacement
    row planes, applied in append order after densifying — so a
    compressed fragment absorbs write churn without decaying to dense
    between merges. Dense containers never carry one (their writes
    scatter in place)."""

    __slots__ = ("kind", "shape", "arrays", "nbytes", "meta", "overlay")

    def __init__(self, kind, shape, arrays, nbytes, meta=None, overlay=0):
        self.kind = kind
        self.shape = tuple(shape)
        self.arrays = tuple(arrays)
        self.nbytes = int(nbytes)
        self.meta = meta or {}
        self.overlay = int(overlay)

    @property
    def csig(self):
        """Static program signature: enough for the jitted serving
        program to reconstruct the container from flat args (shapes are
        left to retracing, exactly like the dense fn cache). Dense is
        ("dense",) with no logical size — the program reads it off the
        array — so dense containers share fn-cache keys with the legacy
        raw-arity call sites; compressed kinds carry S because their
        component shapes don't determine it, plus the overlay term count
        when deltas are parked (a different flat arity is a different
        program)."""
        if self.kind == "dense":
            return ("dense",)
        if self.overlay:
            return (self.kind, self.shape[0], self.overlay)
        return (self.kind, self.shape[0])

    @property
    def gsig(self):
        """Vmapped-batch grouping signature: kind + exact component
        shapes, because stacking a leaf slot across queries requires
        identical shapes per component."""
        return (self.kind, self.shape[0],
                tuple(tuple(a.shape) for a in self.arrays))


def kind_of(arrays):
    """Representation of a cached pool entry: rows/BSI pools hold raw
    dense device arrays (never Containers)."""
    return arrays.kind if isinstance(arrays, Container) else "dense"


def dense_container(stack):
    """Wrap an existing [S, W] device stack (bsi-condition masks,
    time-union folds, legacy paths) without copying."""
    nbytes = int(stack.size) * 4
    return Container("dense", stack.shape, (stack,), nbytes,
                     {"dense_bytes": nbytes, "ratio": 1.0})


def flatten(containers):
    """Device-arg flattening for the jitted serving programs."""
    return [a for c in containers for a in c.arrays]


def flat_arity(csig):
    return sum(_ARITY[entry[0]]
               + 2 * (entry[2] if len(entry) > 2 else 0)
               for entry in csig)


def norm_csig(csig):
    """Container signature from a legacy arity int (N all-dense raw
    arrays — exec/stacked's pre-container call sites and tests) or an
    already-proper tuple."""
    if isinstance(csig, int):
        return (("dense",),) * csig
    return tuple(csig)


def unflatten(csig, flat):
    """Inverse of flatten inside a traced program: [(kind, arrays, S)],
    or [(kind, arrays, S, ((oidx, oplanes), ...))] for entries whose
    csig carries overlay terms (the 3-tuple shape is preserved for
    overlay-free entries — existing programs and tests index [0]/[2])."""
    out, i = [], 0
    for entry in csig:
        kind = entry[0]
        n = _ARITY[kind]
        cont = (kind, tuple(flat[i:i + n]),
                entry[1] if len(entry) > 1 else -1)
        i += n
        terms = entry[2] if len(entry) > 2 else 0
        if terms:
            ov = tuple((flat[i + 2 * t], flat[i + 2 * t + 1])
                       for t in range(terms))
            i += 2 * terms
            cont = cont + (ov,)
        out.append(cont)
    return out


# ---------------------------------------------------------------- analysis

# 16-bit popcount table: exact host bit counts without unpacking the
# whole stack to booleans (the cold-build path analyzes every stack).
_POP16 = np.array([bin(i).count("1") for i in range(1 << 16)],
                  dtype=np.uint8)


def _host_popcount(stack):
    return int(_POP16[stack.view(np.uint16)].sum(dtype=np.int64))


def _shifted_left(stack):
    """bit i-1 of the plane at bit i's position (little-endian words,
    cross-word carry; column 0 sees 0)."""
    carry = np.concatenate(
        [np.zeros((stack.shape[0], 1), np.uint32), stack[:, :-1] >> 31],
        axis=1)
    return (stack << np.uint32(1)) | carry


def _pow2(n):
    return 1 << max(0, int(n) - 1).bit_length()


def analyze(stack):
    """Host analysis of a [S, W] uint32 stack: exact bit count, density,
    non-empty block count, run count, and the projected device bytes of
    each representation (padded to the power-of-two component sizes the
    builders use)."""
    stack = np.ascontiguousarray(stack, dtype=np.uint32)
    s, w = stack.shape
    bits = _host_popcount(stack)
    bp = w // BLOCK_WORDS
    nonempty = int(stack.reshape(s, bp, BLOCK_WORDS).any(axis=2).sum())
    starts_mask = stack & ~_shifted_left(stack)
    runs = _host_popcount(starts_mask)
    nb_pad = _pow2(max(1, nonempty))
    nr_pad = _pow2(max(1, runs))
    return {
        "bits": bits,
        "density": bits / float(s * w * 32) if s and w else 0.0,
        "total_blocks": s * bp,
        "nonempty_blocks": nonempty,
        "runs": runs,
        "dense_bytes": s * w * 4,
        "sparse_bytes": nb_pad * (BLOCK_WORDS * 4 + 4),
        "rle_bytes": nr_pad * 12,
    }


def _sparse_eligible(s, w):
    # int32-exact totals AND sentinel strictly above every real id
    return (s * SHARD_WIDTH < 2**31
            and s * (w // BLOCK_WORDS) < SPARSE_SENTINEL)


def _rle_eligible(s, _w):
    # shard-relative [start, end] offsets go up to SHARD_WIDTH inclusive
    return s * SHARD_WIDTH < 2**31 and SHARD_WIDTH <= 2**30


def choose(info, s, w, mode=None):
    """Representation for a stack with this analysis under `mode`.
    Deterministic in (info, shape, mode) — the chooser-stability
    contract. Forced modes honor the int32-safety gates but skip the
    byte-advantage hysteresis."""
    mode = repr_mode() if mode is None else mode
    if mode == "dense":
        return "dense"
    if mode == "sparse":
        return "sparse" if _sparse_eligible(s, w) else "dense"
    if mode == "rle":
        return "rle" if _rle_eligible(s, w) else "dense"
    if info["dense_bytes"] < AUTO_COMPRESS_FLOOR:
        return "dense"
    budget = info["dense_bytes"] * COMPRESS_ADVANTAGE
    best, best_bytes = "dense", info["dense_bytes"]
    if (_sparse_eligible(s, w) and info["sparse_bytes"] <= budget
            and info["sparse_bytes"] < best_bytes):
        best, best_bytes = "sparse", info["sparse_bytes"]
    if (_rle_eligible(s, w) and info["runs"] <= MAX_RLE_RUNS
            and info["rle_bytes"] <= budget
            and info["rle_bytes"] < best_bytes):
        best, best_bytes = "rle", info["rle_bytes"]
    return best


# ------------------------------------------------------------ host builders


def _sparse_host(stack):
    """(block_ids [NBp] int32 sorted, blocks [NBp, BW] uint32), padded
    to a power of two with sentinel ids + zero blocks."""
    s, w = stack.shape
    bp = w // BLOCK_WORDS
    b3 = stack.reshape(s, bp, BLOCK_WORDS)
    ss, bb = np.nonzero(b3.any(axis=2))  # row-major: ids come out sorted
    ids = (ss.astype(np.int64) * bp + bb).astype(np.int32)
    n = len(ids)
    n_pad = _pow2(max(1, n))
    ids_p = np.full(n_pad, SPARSE_SENTINEL, dtype=np.int32)
    ids_p[:n] = ids
    blocks_p = np.zeros((n_pad, BLOCK_WORDS), dtype=np.uint32)
    blocks_p[:n] = b3[ss, bb]
    return ids_p, blocks_p


def _bit_positions(mask):
    """(shard_idx, bit_offset) of every set bit in a [S, W] mask, sorted
    by (shard, offset). Only the non-zero words are expanded — the masks
    this serves (run transitions) are sparse by construction."""
    ws, ww = np.nonzero(mask)
    if len(ws) == 0:
        return (np.empty(0, np.int32), np.empty(0, np.int32))
    bits = (mask[ws, ww][:, None] >> np.arange(32, dtype=np.uint32)) & 1
    rows, cols = np.nonzero(bits)
    return (ws[rows].astype(np.int32),
            (ww[rows] * 32 + cols).astype(np.int32))


def _rle_host(stack):
    """(run_shard, run_start, run_end) int32 triples of the maximal
    [start, end) set-bit runs per shard row, sorted by (shard, start)
    and padded to a power of two with empty (-1, 0, 0) runs."""
    s, w = stack.shape
    shifted = _shifted_left(stack)
    s_sh, s_pos = _bit_positions(stack & ~shifted)   # 0 -> 1 transitions
    e_sh, e_pos = _bit_positions(~stack & shifted)   # 1 -> 0 transitions
    # runs still open at the end of the shard close at SHARD_WIDTH
    tail = np.nonzero((stack[:, -1] >> np.uint32(31)) & 1)[0]
    if len(tail):
        e_sh = np.concatenate([e_sh, tail.astype(np.int32)])
        e_pos = np.concatenate(
            [e_pos, np.full(len(tail), w * 32, dtype=np.int32)])
        order = np.lexsort((e_pos, e_sh))
        e_sh, e_pos = e_sh[order], e_pos[order]
    if len(s_sh) != len(e_sh):  # pragma: no cover — structural invariant
        raise AssertionError("run transition mismatch")
    n = len(s_sh)
    n_pad = _pow2(max(1, n))
    run_shard = np.full(n_pad, -1, dtype=np.int32)
    run_start = np.zeros(n_pad, dtype=np.int32)
    run_end = np.zeros(n_pad, dtype=np.int32)
    run_shard[:n] = s_sh
    run_start[:n] = s_pos
    run_end[:n] = e_pos
    return run_shard, run_start, run_end


def build(host_stack, place_sharded, place_replicated, mode=None,
          fragment=None):
    """Analyze + choose + build + place one leaf stack.

    `place_sharded(arr)` places a dense [S, W] stack over the shard
    mesh (the legacy placement); `place_replicated(arr)` places a
    compressed component replicated — compressed arrays have no shard
    axis, and a replicated operand keeps the serving program a valid
    GSPMD launch next to mesh-sharded dense operands. Records the
    choice in the fragment ledger."""
    host_stack = np.ascontiguousarray(host_stack, dtype=np.uint32)
    s, w = host_stack.shape
    info = analyze(host_stack)
    kind = choose(info, s, w, mode)
    if ((mode or repr_mode()) == "auto" and fragment is not None
            and len(fragment) >= 2):
        override = repr_override(fragment[0], fragment[1])
        if override is not None:
            kind = override
    if kind == "sparse":
        ids, blocks = _sparse_host(host_stack)
        arrays = (place_replicated(ids), place_replicated(blocks))
        nbytes = int(ids.nbytes + blocks.nbytes)
    elif kind == "rle":
        arrays = tuple(place_replicated(a) for a in _rle_host(host_stack))
        nbytes = 3 * arrays[0].size * 4
    else:
        stack = place_sharded(host_stack)
        arrays = (stack,)
        nbytes = int(host_stack.nbytes)
    meta = {"dense_bytes": info["dense_bytes"],
            "density": round(info["density"], 6),
            "ratio": round(info["dense_bytes"] / nbytes, 3)
            if nbytes else 1.0}
    _ledger_note(fragment, kind, nbytes, info["dense_bytes"],
                 info["density"])
    return Container(kind, (s, w), arrays, nbytes, meta)


# ----------------------------------------------------------- traced kernels
#
# Everything below runs inside jitted serving programs (exec/stacked
# builds them) — jnp only, vmap-safe, int32 totals under the chooser's
# 2^31-bit gate.


def _split_total(t):
    """(hi, lo) of one int32 total, exact under combine_hi_lo."""
    return t >> 16, t & 0xFFFF


def _blocks_popcount_total(blocks):
    """Σ popcount over a [NB, BW] block stack (padding blocks are zero).
    Routes to the Pallas compressed-popcount kernel under the same
    opt-in gate as the dense count kernels."""
    import jax
    import jax.numpy as jnp

    from . import pallas_kernels

    if pallas_kernels.enabled():
        return pallas_kernels.count_blocks_stack(blocks)
    return jnp.sum(jax.lax.population_count(blocks).astype(jnp.int32))


def sparse_count_hi_lo(ids, blocks):  # noqa: ARG001 — ids fix the layout
    return _split_total(_blocks_popcount_total(blocks))


def sparse_intersect_blocks(ids_a, blocks_a, ids_b, blocks_b):
    """blocks_a ∩ blocks_b aligned onto a's block index: for each a
    block, binary-search b's sorted ids; unmatched blocks intersect to
    zero. Padding self-matches (sentinel == sentinel) but both sides'
    padding blocks are zero, so the result stays count-exact."""
    import jax.numpy as jnp

    pos = jnp.searchsorted(ids_b, ids_a)
    pos = jnp.clip(pos, 0, ids_b.shape[0] - 1)
    match = ids_b[pos] == ids_a
    return jnp.where(match[:, None], blocks_a & blocks_b[pos],
                     jnp.uint32(0))


def rle_count_hi_lo(run_shard, run_start, run_end):  # noqa: ARG001
    import jax.numpy as jnp

    return _split_total(jnp.sum(run_end - run_start))


def rle_intersect_hi_lo(a_sh, a_st, a_en, b_sh, b_st, b_en):
    """Pairwise [NA, NB] interval-overlap count restricted to matching
    shards; runs are disjoint within a container so the overlaps sum
    exactly. Padding runs (shard -1, empty) overlap nothing — even each
    other, because clip(0 - 0, 0) = 0."""
    import jax.numpy as jnp

    ov = jnp.clip(
        jnp.minimum(a_en[:, None], b_en[None, :])
        - jnp.maximum(a_st[:, None], b_st[None, :]), 0)
    same = a_sh[:, None] == b_sh[None, :]
    return _split_total(jnp.sum(jnp.where(same, ov, 0)))


def sparse_to_dense(ids, blocks, s, w):
    """Exact dense [S, W] stack from sparse blocks (scatter; sentinel
    padding ids drop)."""
    import jax.numpy as jnp

    nb = (s * w) // BLOCK_WORDS
    flat = jnp.zeros((nb, BLOCK_WORDS), jnp.uint32)
    flat = flat.at[ids].set(blocks, mode="drop")
    return flat.reshape(s, w)


def rle_to_dense(run_shard, run_start, run_end, s, w):
    """Exact dense [S, W] stack from runs: per shard, scatter +1/-1 run
    deltas over the bit axis, prefix-sum to coverage, pack 32 bits per
    word. lax.map keeps peak memory at one shard's bit vector instead
    of [S, SHARD_WIDTH] at once."""
    import jax
    import jax.numpy as jnp

    nbits = w * 32
    weights = jnp.uint32(1) << jnp.arange(32, dtype=jnp.uint32)

    def per_shard(shard):
        m = (run_shard == shard).astype(jnp.int32)
        delta = jnp.zeros(nbits + 1, jnp.int32)
        delta = delta.at[run_start].add(m).at[run_end].add(-m)
        bits = jnp.cumsum(delta[:-1]) > 0
        return jnp.sum(
            jnp.where(bits.reshape(w, 32), weights[None, :],
                      jnp.uint32(0)),
            axis=1, dtype=jnp.uint32)

    return jax.lax.map(per_shard, jnp.arange(s, dtype=jnp.int32))


def _has_overlay(cont):
    return len(cont) > 3 and cont[3]


def to_dense(cont):
    """Dense [S, W] view of an unflattened (kind, arrays, S) container —
    identity for dense (forced-dense programs ARE the legacy ones).
    Pending-delta overlay terms scatter in append order after the base
    densifies: each term's planes are full replacements gathered from
    the authoritative host fragment, so later terms override earlier."""
    kind, arrays, s = cont[0], cont[1], cont[2]
    if kind == "dense":
        dense = arrays[0]
    elif kind == "sparse":
        dense = sparse_to_dense(arrays[0], arrays[1], s, WORDS_PER_ROW)
    else:
        dense = rle_to_dense(arrays[0], arrays[1], arrays[2], s,
                             WORDS_PER_ROW)
    if _has_overlay(cont):
        for oidx, oplanes in cont[3]:
            dense = dense.at[oidx].set(oplanes)
    return dense


def _count_container(cont):
    import jax
    import jax.numpy as jnp

    from . import bitplane

    kind, arrays = cont[0], cont[1]
    if not _has_overlay(cont):
        if kind == "sparse":
            return sparse_count_hi_lo(*arrays)
        if kind == "rle":
            return rle_count_hi_lo(*arrays)
    # overlay terms replace whole planes, so compressed direct counts
    # can't subtract what they cover — densify (exact) and count dense
    acc = to_dense(cont)
    per_shard = jnp.sum(
        jax.lax.population_count(acc).astype(jnp.int32), axis=-1)
    return bitplane.hi_lo(per_shard)


def _pure_intersect_leaves(sig):
    """Leaf slots of an all-& tree, or None for any other shape."""
    if sig[0] == "leaf":
        return [sig[1]]
    op, subs = sig
    if op != "&":
        return None
    out = []
    for sub in subs:
        r = _pure_intersect_leaves(sub)
        if r is None:
            return None
        out.extend(r)
    return out


def count_program(sig, csig, flat, tree_eval):
    """(hi, lo) count of one tree over flattened container args — THE
    compressed counting strategy, traced inside exec/stacked's jitted
    serving programs:

    1. single compressed leaf        -> direct compressed popcount
    2. pure-& tree, all-sparse       -> block-aligned intersect chain,
                                        counted without densifying
    3. pure-& pair of small rle      -> pairwise interval overlap
    4. anything else                 -> decompress leaves in-program
                                        (exact), legacy dense tree eval

    All four produce the same exact total; the choice is purely a
    bytes/FLOPs trade. `tree_eval` is StackedEvaluator._tree_eval —
    expression semantics live there, once."""
    import jax
    import jax.numpy as jnp

    from . import bitplane, pallas_kernels

    conts = unflatten(csig, flat)
    if sig[0] == "leaf":
        return _count_container(conts[sig[1]])
    leaf_ids = _pure_intersect_leaves(sig)
    if (leaf_ids is not None and len(leaf_ids) >= 2
            and not any(_has_overlay(conts[i]) for i in leaf_ids)):
        kinds = {conts[i][0] for i in leaf_ids}
        if kinds == {"sparse"}:
            first = conts[leaf_ids[0]]
            acc_ids, acc_blocks = first[1]
            for i in leaf_ids[1:]:
                ids_b, blocks_b = conts[i][1]
                if (len(leaf_ids) == 2 and pallas_kernels.enabled()):
                    # two-operand fast path: fuse the aligned AND into
                    # the Pallas popcount (one compressed HBM pass)
                    pos = jnp.searchsorted(ids_b, acc_ids)
                    pos = jnp.clip(pos, 0, ids_b.shape[0] - 1)
                    match = ids_b[pos] == acc_ids
                    other = jnp.where(match[:, None], blocks_b[pos],
                                      jnp.uint32(0))
                    return _split_total(
                        pallas_kernels.count_and_blocks_stack(
                            acc_blocks, other))
                acc_blocks = sparse_intersect_blocks(
                    acc_ids, acc_blocks, ids_b, blocks_b)
            return _split_total(_blocks_popcount_total(acc_blocks))
        if kinds == {"rle"} and len(leaf_ids) == 2:
            a, b = conts[leaf_ids[0]], conts[leaf_ids[1]]
            if a[1][0].shape[0] * b[1][0].shape[0] <= MAX_RLE_PAIR:
                return rle_intersect_hi_lo(*a[1], *b[1])
    acc = tree_eval(sig, [to_dense(c) for c in conts])
    per_shard = jnp.sum(
        jax.lax.population_count(acc).astype(jnp.int32), axis=-1)
    return bitplane.hi_lo(per_shard)


def plane_program(sig, csig, flat, tree_eval):
    """Dense [S, W] materialization of one tree over flattened container
    args — filter stacks and Row results must come out as the exact
    legacy planes, so every leaf decompresses in-program first."""
    return tree_eval(sig, [to_dense(c) for c in unflatten(csig, flat)])


# -------------------------------------------------------- ingest overlay


def with_overlay(cont, place_replicated, oidx, oplanes):
    """New Container with one more pending-delta overlay term appended
    after `cont`'s arrays: `oidx` [K] stack-row indices (int32) and
    `oplanes` [K, W] full replacement planes (uint32), placed replicated
    like every compressed component. The base representation is
    untouched — this is how the interval merge folds writes into a
    sparse/rle fragment without decaying it to dense."""
    oidx = np.ascontiguousarray(oidx, dtype=np.int32)
    oplanes = np.ascontiguousarray(oplanes, dtype=np.uint32)
    arrays = cont.arrays + (place_replicated(oidx),
                            place_replicated(oplanes))
    nbytes = cont.nbytes + int(oidx.nbytes + oplanes.nbytes)
    return Container(cont.kind, cont.shape, arrays, nbytes,
                     dict(cont.meta), overlay=cont.overlay + 1)


def overlay_rows(cont):
    """Total stack rows covered by a Container's overlay terms (the
    merge's rebuild-threshold input; counts duplicates across terms)."""
    base = _ARITY[cont.kind]
    return sum(int(cont.arrays[base + 2 * t].shape[0])
               for t in range(cont.overlay))


def container_to_dense(cont):
    """Dense [S, W] of a Container OBJECT (overlay applied) — the
    eager-mode analogue of the traced to_dense for call sites that hold
    the Container itself (exec/stacked's read-path decay)."""
    base = cont.arrays[:_ARITY[cont.kind]]
    dense = to_dense((cont.kind, base, cont.shape[0]))
    for t in range(cont.overlay):
        oidx = cont.arrays[_ARITY[cont.kind] + 2 * t]
        oplanes = cont.arrays[_ARITY[cont.kind] + 2 * t + 1]
        dense = dense.at[oidx].set(oplanes)
    return dense
