"""Device kernels over dense row planes.

A "plane" is one row of one shard: a dense bitset of SHARD_WIDTH bits packed
little-endian into uint32 words (shape [WORDS_PER_ROW]). A "stack" is a batch
of planes (shape [R, WORDS_PER_ROW]).

These kernels are the TPU-native equivalent of the reference's hand-optimized
roaring container kernels (reference: roaring/roaring.go:3121-5196 — per
container-type intersect/union/difference/xor/popcount). Where the reference
dispatches on container representation (array/bitmap/run), we keep everything
dense in HBM and let the VPU chew through whole planes; set algebra is
elementwise and popcounts reduce with `lax.population_count`.

All functions are jitted and shape-polymorphic only through retracing; shapes
are static per compilation, which is what XLA wants.
"""

from functools import lru_cache, partial

import jax
import jax.numpy as jnp
import numpy as np

from ..shardwidth import SHARD_WIDTH, WORD_BITS, WORDS_PER_ROW

__all__ = [
    "intersect",
    "union",
    "difference",
    "xor",
    "not_",
    "popcount",
    "popcount_rows",
    "batch_popcount_hi_lo",
    "count_intersect",
    "union_rows",
    "any_set",
    "shift",
    "plane_from_columns",
    "columns_from_plane",
    "topn_counts",
    "pairwise_counts",
    "pairwise_counts_hi_lo",
    "pairwise_tile",
    "hi_lo",
    "combine_hi_lo",
]


def hi_lo(per_shard_counts, axis=None):
    """Overflow-safe cross-shard reduce: per-shard popcounts fit int32
    (<= SHARD_WIDTH = 2^20 bits/shard) but totals can exceed 2^31 past 2048
    shards, and TPU JAX runs with x64 disabled — so reduce (count >> 16) and
    (count & 0xffff) separately and recombine on host with exact Python ints
    (combine_hi_lo). Safe to 2^15 shards (~34 trillion columns/node).

    This is THE one overflow-splitting contract; every cross-shard count
    reduce in the framework routes through this pair of helpers."""
    return (jnp.sum(per_shard_counts >> 16, axis=axis),
            jnp.sum(per_shard_counts & 0xFFFF, axis=axis))


def combine_hi_lo(hi, lo):
    """Exact host total from a hi_lo() reduce pair (elementwise for array
    pairs, Python int for scalars)."""
    if np.ndim(hi):
        return (np.asarray(hi).astype(np.int64) << 16) + np.asarray(lo)
    return (int(hi) << 16) + int(lo)


@jax.jit
def intersect(a, b):
    return a & b


@jax.jit
def union(a, b):
    return a | b


@jax.jit
def difference(a, b):
    return a & ~b


@jax.jit
def xor(a, b):
    return a ^ b


@jax.jit
def not_(a):
    """Complement within the shard universe (used with an existence mask by
    the executor — reference: executor.go executeNot via index._exists)."""
    return ~a


@jax.jit
def popcount(a):
    """Number of set bits in a plane. int32 is safe: a plane holds at most
    SHARD_WIDTH (2^20) bits (reference popcount kernels: roaring.go:5291)."""
    return jnp.sum(jax.lax.population_count(a).astype(jnp.int32))


@jax.jit
def popcount_rows(stack):
    """Per-row popcount over a stack [R, W] -> [R] int32."""
    return jnp.sum(jax.lax.population_count(stack).astype(jnp.int32), axis=-1)


def batch_popcount_hi_lo(stacks):
    """Per-query popcount totals for a batched [B, S, W] plane stack ->
    (hi [B], lo [B]). The per-(query, shard) partials fit int32 like any
    single plane's; the cross-shard reduce routes through the hi_lo
    overflow-splitting contract so totals stay exact past 2^31 (see
    hi_lo). Traced inside the vmapped serving programs
    (exec/stacked._vmap_count_fn) rather than jitted standalone."""
    per_shard = jnp.sum(
        jax.lax.population_count(stacks).astype(jnp.int32), axis=-1)
    return hi_lo(per_shard, axis=-1)


@jax.jit
def count_intersect(a, b):
    """Fused intersection-count — the north-star hot loop (reference:
    intersectionCount* kernels roaring.go:3121-3480). XLA fuses the AND into
    the popcount reduce; no intermediate plane is materialized."""
    return jnp.sum(jax.lax.population_count(a & b).astype(jnp.int32))


@jax.jit
def union_rows(stack):
    """OR-reduce a stack [R, W] -> [W] (used by ClearRow/Store fan-ins and
    time-quantum view unions, reference: view union paths)."""
    return jax.lax.reduce(
        stack,
        jnp.uint32(0),
        jax.lax.bitwise_or,
        dimensions=[0],
    )


@jax.jit
def any_set(a):
    """True iff any bit is set (reference: Row.Any / Bitmap.Any)."""
    return jnp.any(a != 0)


@partial(jax.jit, static_argnames=("n",))
def _shift_static(a, n):
    """Shift the whole plane toward higher column ids by n bits (reference:
    Row.Shift row.go:241, roaring shiftArray/shiftBitmap). Bits shifted past
    the end of the shard are dropped (per-shard semantics; the executor
    carries them across segments)."""
    word_shift, bit_shift = divmod(n, WORD_BITS)
    if word_shift:
        a = jnp.roll(a, word_shift)
        a = a.at[:word_shift].set(0)
    if bit_shift:
        carry = jnp.roll(a >> jnp.uint32(WORD_BITS - bit_shift), 1).at[0].set(0)
        a = (a << jnp.uint32(bit_shift)) | carry
    return a


def shift(a, n=1):
    n = int(n)
    if n < 0:
        raise ValueError("shift supports non-negative n only (toward higher columns)")
    if n == 0:
        return a
    return _shift_static(a, n)


def plane_from_columns(cols):
    """Host helper: build a [WORDS_PER_ROW] uint32 plane from shard-relative
    column offsets (native scatter, used by import paths and tests). Offsets
    must already be shard-relative — a value >= SHARD_WIDTH means the caller
    forgot to subtract the shard base, so fail loudly rather than let the
    scatter primitive silently drop it."""
    from .. import native

    cols = np.asarray(cols, dtype=np.uint64)
    if cols.size and int(cols.max()) >= SHARD_WIDTH:
        raise ValueError(
            f"column offset {int(cols.max())} >= shard width {SHARD_WIDTH}")
    plane = np.zeros(WORDS_PER_ROW, dtype=np.uint32)
    native.scatter(cols, plane)
    return plane


def columns_from_plane(plane):
    """Host helper: shard-relative column offsets of set bits, sorted."""
    from .. import native

    return native.extract(np.asarray(plane, dtype=np.uint32))


@partial(jax.jit, static_argnames=("k",))
def _topn_counts_jnp(stack, filter_plane, k):
    counts = popcount_rows(stack & filter_plane[None, :])
    vals, idx = jax.lax.top_k(counts, k)
    return vals, idx


# Per-axis row budget for one pairwise tile ([tile, S, W] stack). Matches
# exec.stacked.CHUNK_BYTES so a tile stack never exceeds one row-chunk
# upload; the serving layer derives its tile from CHUNK_BYTES directly.
PAIRWISE_TILE_BYTES = 128 * 1024 * 1024


def pairwise_tile(n_shards):
    """Rows per pairwise tile axis under the PAIRWISE_TILE_BYTES budget."""
    return max(1, PAIRWISE_TILE_BYTES // (n_shards * WORDS_PER_ROW * 4))


@lru_cache(maxsize=4)
def _pairwise_hi_lo_fn(has_filt):
    """(A [R1,S,W], B [R2,S,W], filt [S,W]?) -> (hi [R1,R2], lo [R1,R2])
    cross-product intersect counts, reduced over shards with the hi_lo
    overflow split. The A axis folds through a lax.map so the broadcast
    intermediate stays [R2, S, W] (one B-stack's worth) instead of
    materializing the full [R1, R2, S, W] cross product."""

    @jax.jit
    def fn(a, b, *filt):
        bf = b & filt[0][None] if has_filt else b

        def per_a(a_row):
            pc = jax.lax.population_count(a_row[None] & bf).astype(jnp.int32)
            return jnp.sum(pc, axis=-1)          # [R2, S]

        per_shard = jax.lax.map(per_a, a)        # [R1, R2, S]
        return hi_lo(per_shard, axis=-1)

    return fn


def pairwise_counts_hi_lo(a, b, filt=None):
    """One-tile pairwise intersect-count matrix as a device (hi, lo) pair:
    counts[i, j] = Σ_{s,w} popcount(a[i] & b[j] & filt). a: [R1, S, W],
    b: [R2, S, W], filt: [S, W] or None. Dispatches to the Pallas backend
    under the same opt-in gate as the count kernels when the per-pair bit
    budget fits its plain-int32 accumulator and the inputs live on one
    device (pallas_call can't be GSPMD-partitioned)."""
    from . import pallas_kernels
    from ..parallel.sharded import _is_multi_device

    if a.shape[0] == 0 or b.shape[0] == 0:
        z = jnp.zeros((a.shape[0], b.shape[0]), jnp.int32)
        return z, z
    n_bits = a.shape[1] * a.shape[2] * 32
    if pallas_kernels.enabled() and n_bits < 2**31 \
            and not _is_multi_device(a) and not _is_multi_device(b):
        m = pallas_kernels.pairwise_counts_stack(a, b, filt)
        # totals < 2^31 by the gate, so the plain split satisfies the
        # combine_hi_lo contract total = (hi << 16) + lo exactly
        return m >> 16, m & 0xFFFF
    fn = _pairwise_hi_lo_fn(filt is not None)
    if filt is not None:
        return fn(jnp.asarray(a), jnp.asarray(b), jnp.asarray(filt))
    return fn(jnp.asarray(a), jnp.asarray(b))


def pairwise_counts(A, B, filt=None, tile=None):
    """Host [R1, R2] int64 matrix of pairwise intersect counts over row
    stacks A [R1, S, W] and B [R2, S, W] (filt [S, W] optional) — the
    GroupBy cross product as one tiled popcount matrix instead of R1·R2
    per-combination scans (reference: executor.go:1238 iterates fragment
    scans per group). Tiled over BOTH row axes so device memory stays
    bounded by ~2·PAIRWISE_TILE_BYTES regardless of R1·R2; each tile pair
    is one fused dispatch + one host sync."""
    R1, R2 = int(A.shape[0]), int(B.shape[0])
    out = np.zeros((R1, R2), dtype=np.int64)
    if R1 == 0 or R2 == 0:
        return out
    if tile is None:
        tile = pairwise_tile(int(A.shape[1]))
    dfilt = jnp.asarray(filt) if filt is not None else None
    for i in range(0, R1, tile):
        a = jnp.asarray(A[i:i + tile])
        for j in range(0, R2, tile):
            b = jnp.asarray(B[j:j + tile])
            hi, lo = pairwise_counts_hi_lo(a, b, dfilt)
            out[i:i + tile, j:j + tile] = combine_hi_lo(hi, lo)
    return out


def topn_counts(stack, filter_plane, k):
    """Per-row intersection counts then top-k (reference: fragment.top
    fragment.go:1570 + cache heap merge). Returns (counts [k], slots [k]).
    top_k returns real slot indices even for zero counts — callers MUST drop
    entries with count == 0 (the reference's top excludes empty rows).
    Dispatches to the Pallas backend under the same opt-in gate as
    QueryKernels.count_expr. An empty stack yields zero counts on either
    backend (top_k would reject k > 0 rows)."""
    from . import pallas_kernels

    if stack.shape[0] == 0:
        return jnp.zeros(k, jnp.int32), jnp.zeros(k, jnp.int32)
    if pallas_kernels.enabled():
        return pallas_kernels.topn_counts_stack(stack, filter_plane, k)
    return _topn_counts_jnp(stack, filter_plane, k)
