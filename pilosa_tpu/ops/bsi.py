"""BSI (bit-sliced index) kernels.

The reference stores integer values sign-magnitude across bit-plane rows of a
`bsig_<field>` view: row 0 = existence, row 1 = sign, row 2+i = magnitude bit i
(reference: fragment.go:91-93, value/setValue fragment.go:896-1000). Range
queries are bit-plane scans (reference: rangeEQ/rangeLT/rangeGT/rangeLTUnsigned
fragment.go:1292-1470); Sum/Min/Max walk planes with a narrowing filter
(fragment.go:1068-1227).

TPU-native design: instead of the reference's iterative keep/filter loops we
compute all comparison masks in ONE branchless pass — the classic vectorized
magnitude comparator. For each column (a bit lane across D magnitude planes):

    eq_i  : magnitude so far equals the predicate's high bits
    lt/gt : first differing bit decides

which XLA unrolls over the (static, <=64) bit depth into fused elementwise ops.
This is mathematically equivalent to the reference algorithm but has no
data-dependent control flow — exactly what the MXU/VPU pipeline wants.

Layout convention here: `planes` is a [D, W] uint32 stack, planes[i] =
magnitude bit i (LSB first), `sign` and `exists` are [W] planes. Predicates
arrive as a [D] uint32 0/1 vector of predicate magnitude bits (host-computed),
so kernels never see 64-bit scalars (TPU is 32-bit native).
"""

from functools import partial

import jax
import jax.numpy as jnp
import numpy as np

from .bitplane import popcount_rows

__all__ = [
    "predicate_bits",
    "compare_unsigned",
    "range_eq",
    "range_lt",
    "range_gt",
    "range_between_unsigned",
    "bsi_plane_counts",
    "max_unsigned",
    "min_unsigned",
]

FULL = jnp.uint32(0xFFFFFFFF)


def predicate_bits(upredicate, depth):
    """Host helper: magnitude bits of an unsigned predicate as a [depth]
    uint32 0/1 vector (LSB first).

    Raises ValueError when the predicate doesn't fit in `depth` bits: the
    correct result then depends on the comparison operator (everything is LT
    an over-wide predicate, nothing is EQ/GT it), so the executor must clamp
    BEFORE building bits (see exec layer rangeOp handling)."""
    if int(upredicate) >> depth:
        raise ValueError(
            f"predicate magnitude {upredicate} does not fit in bitDepth {depth}; "
            "caller must clamp")
    return np.array(
        [(int(upredicate) >> i) & 1 for i in range(depth)], dtype=np.uint32
    )


@jax.jit
def compare_unsigned(planes, pbits):
    """One-pass vectorized comparator of per-column magnitudes vs. predicate.

    Returns (lt, eq, gt) masks, each shaped like one plane. Equivalent to
    the reference's rangeLTUnsigned / rangeGTUnsigned / rangeEQ scans
    (fragment.go:1357-1470) but computed simultaneously with no branching.
    Shape-polymorphic: `planes` may be [D, W] (one shard) or [D, S, W]
    (stacked serving path) — the scan is elementwise over plane shape.
    """
    eq = jnp.full(planes.shape[1:], FULL, dtype=jnp.uint32)
    lt = jnp.zeros(planes.shape[1:], dtype=jnp.uint32)
    gt = jnp.zeros(planes.shape[1:], dtype=jnp.uint32)

    def step(carry, xs):
        lt, eq, gt = carry
        plane, bit = xs
        pmask = jnp.where(bit == 1, FULL, jnp.uint32(0))
        # Column bit set, predicate bit clear -> column > predicate (at first
        # difference); column bit clear, predicate bit set -> column < pred.
        gt = gt | (eq & plane & ~pmask)
        lt = lt | (eq & ~plane & pmask)
        eq = eq & ~(plane ^ pmask)
        return (lt, eq, gt), None

    # MSB-first scan: reverse the plane stack and predicate bits.
    (lt, eq, gt), _ = jax.lax.scan(
        step, (lt, eq, gt), (planes[::-1], pbits[::-1].astype(jnp.uint32))
    )
    return lt, eq, gt


@jax.jit
def _range_eq_jnp(planes, sign, exists, pbits, neg_predicate):
    base = jnp.where(neg_predicate, exists & sign, exists & ~sign)
    _, eq, _ = compare_unsigned(planes, pbits)
    return base & eq


@jax.jit
def _range_lt_jnp(planes, sign, exists, pbits, neg_predicate, allow_eq):
    pos = exists & ~sign
    neg = exists & sign
    lt, eq, gt = compare_unsigned(planes, pbits)
    eq_mask = jnp.where(allow_eq, FULL, jnp.uint32(0))

    pos_result = neg | (pos & (lt | (eq & eq_mask)))
    neg_result = neg & (gt | (eq & eq_mask))
    return jnp.where(neg_predicate, neg_result, pos_result)


@jax.jit
def _range_gt_jnp(planes, sign, exists, pbits, neg_predicate, allow_eq):
    pos = exists & ~sign
    neg = exists & sign
    lt, eq, gt = compare_unsigned(planes, pbits)
    eq_mask = jnp.where(allow_eq, FULL, jnp.uint32(0))

    pos_result = pos & (gt | (eq & eq_mask))
    neg_result = pos | (neg & (lt | (eq & eq_mask)))
    return jnp.where(neg_predicate, neg_result, pos_result)


def _use_pallas(planes):
    """Fused single-pass pallas kernel, under the same opt-in gate as the
    count kernels; requires full-width planes (the kernel grids over
    WORDS_PER_ROW blocks)."""
    from . import pallas_kernels
    from ..shardwidth import WORDS_PER_ROW

    return (pallas_kernels.enabled()
            and planes.ndim == 2 and planes.shape[-1] == WORDS_PER_ROW
            # the kernel grids over fixed word blocks; narrow shard widths
            # (PILOSA_TPU_SHARD_EXP<=17) would yield an empty grid that
            # never writes the output — use the jnp path there
            and WORDS_PER_ROW % pallas_kernels._BSI_BLOCK_WORDS == 0)


def range_eq(planes, sign, exists, pbits, neg_predicate):
    """Columns whose signed value == predicate (reference: rangeEQ
    fragment.go:1292). Dispatches to the fused pallas kernel when opted in
    (one HBM pass, no intermediate comparator masks)."""
    if _use_pallas(planes):
        from .pallas_kernels import bsi_range_mask

        return bsi_range_mask("eq", planes, sign, exists, pbits,
                              neg_predicate, False)
    return _range_eq_jnp(planes, sign, exists, pbits, neg_predicate)


def range_lt(planes, sign, exists, pbits, neg_predicate, allow_eq):
    """Columns whose signed value < predicate (<= when allow_eq).

    Sign-magnitude semantics (reference: rangeLT fragment.go:1335):
      pred >= 0: all negatives qualify; positives compare magnitudes.
      pred <  0: only negatives, with magnitude > |pred| (reversed order).
    """
    if _use_pallas(planes):
        from .pallas_kernels import bsi_range_mask

        return bsi_range_mask("lt", planes, sign, exists, pbits,
                              neg_predicate, allow_eq)
    return _range_lt_jnp(planes, sign, exists, pbits, neg_predicate,
                         allow_eq)


def range_gt(planes, sign, exists, pbits, neg_predicate, allow_eq):
    """Columns whose signed value > predicate (>= when allow_eq).
    Mirror of range_lt (reference: rangeGT fragment.go:1403)."""
    if _use_pallas(planes):
        from .pallas_kernels import bsi_range_mask

        return bsi_range_mask("gt", planes, sign, exists, pbits,
                              neg_predicate, allow_eq)
    return _range_gt_jnp(planes, sign, exists, pbits, neg_predicate,
                         allow_eq)


@jax.jit
def range_between_unsigned(planes, filter_plane, lo_bits, hi_bits):
    """filter ∩ {lo <= value <= hi} on magnitudes only (reference:
    rangeBetweenUnsigned fragment.go:1489; the executor handles sign split)."""
    lt_lo, _, _ = compare_unsigned(planes, lo_bits)
    lt_hi, eq_hi, _ = compare_unsigned(planes, hi_bits)
    le_hi = lt_hi | eq_hi
    return filter_plane & ~lt_lo & le_hi


@jax.jit
def bsi_plane_counts(planes, sign, exists, filter_plane):
    """Per-plane popcounts for Sum (reference: fragment.sum fragment.go:1068).

    Returns (pos_counts [D], neg_counts [D], count): the host computes
    sum = Σ 2^i·pos[i] − Σ 2^i·neg[i] in arbitrary-precision Python ints,
    avoiding on-device 64-bit overflow.
    """
    consider = exists & filter_plane
    pos = consider & ~sign
    neg = consider & sign
    pos_counts = popcount_rows(planes & pos[None, :])
    neg_counts = popcount_rows(planes & neg[None, :])
    count = jnp.sum(jax.lax.population_count(consider).astype(jnp.int32))
    return pos_counts, neg_counts, count


@jax.jit
def max_unsigned(planes, filter_plane):
    """(max magnitude, columns achieving it) under filter — MSB-down narrowing
    walk (reference: maxUnsigned fragment.go:1139), branchless via where().

    Returns (bits [D] int32 of the max value MSB-first-reversed back to LSB,
    final filter plane). Host reassembles the integer and popcounts the plane.
    """

    def step(filt, plane):
        cand = filt & plane
        nonzero = jnp.any(cand != 0)
        new_filt = jnp.where(nonzero, cand, filt)
        return new_filt, nonzero.astype(jnp.int32)

    final, bits_msb_first = jax.lax.scan(step, filter_plane, planes[::-1])
    return bits_msb_first[::-1], final


@jax.jit
def min_unsigned(planes, filter_plane):
    """(min magnitude, columns achieving it) under filter (reference:
    minUnsigned fragment.go:1110)."""

    def step(filt, plane):
        cand = filt & ~plane
        nonzero = jnp.any(cand != 0)
        new_filt = jnp.where(nonzero, cand, filt)
        # Bit participates in the min when no column can keep it clear.
        return new_filt, (~nonzero).astype(jnp.int32)

    final, bits_msb_first = jax.lax.scan(step, filter_plane, planes[::-1])
    return bits_msb_first[::-1], final
