"""Pallas TPU kernels for the hot query loops.

The jnp kernels in `bitplane.py` already let XLA fuse AND+popcount+reduce;
and the north-star scan — Count(Intersect(a, b)) over every shard of a
1B-column index (reference: intersectionCount* kernels
roaring/roaring.go:3121-3480 driven by executor.mapReduce
executor.go:2455) — is pure AND+popcount+reduce, so that fused XLA path is
already bandwidth-optimal. Measured on a TPU v5 lite chip (fresh inputs,
960 shards x 128 KiB planes): jnp 3.57 ms vs pallas 3.39 ms — parity
within noise. These kernels therefore exist as an *alternative backend* —
explicit HBM->VMEM streaming with a lane-resident accumulator — selectable
with `PILOSA_TPU_PALLAS=1`, not the default ("don't hand-schedule what the
compiler already fuses"). They also serve as the template for future fused
ops XLA can't express in one pass (e.g. BSI multi-plane compare+count).

Dispatch contract: `available()` says whether pallas can run here; callers
(`QueryKernels`) consult `enabled()`. On non-TPU backends the kernels run
via the Pallas interpreter (used by the differential tests).
"""

import functools
import os

import jax
import jax.numpy as jnp
import numpy as np

from ..shardwidth import WORDS_PER_ROW

__all__ = [
    "available",
    "enabled",
    "count_intersect_stack",
    "count_expr_stack",
    "count_blocks_stack",
    "count_and_blocks_stack",
    "topn_counts_stack",
    "pairwise_counts_stack",
    "bsi_range_mask",
]

# Rows of the [S, W] stack processed per grid step. 16 sublanes x 32768
# words = 2 MiB/input block in VMEM — two inputs + scratch + double
# buffering fit in ~16 MiB VMEM. (32 rows fails to compile on v5 lite.)
_BLOCK_ROWS = 16


def _interpret():
    return jax.default_backend() != "tpu"


@functools.lru_cache(maxsize=1)
def available():
    """True when pallas is importable and a trivial kernel runs."""
    try:
        out = count_intersect_stack(
            np.full((1, WORDS_PER_ROW), 0xFFFFFFFF, dtype=np.uint32),
            np.full((1, WORDS_PER_ROW), 0xFFFFFFFF, dtype=np.uint32),
        )
        return int(out) == WORDS_PER_ROW * 32
    except Exception:
        return False


def enabled():
    """Use pallas for the serving hot path? Opt-in AND real TPU only: XLA's
    fused jnp path is at parity on TPU (see module docstring) and on other
    backends the kernels would run through the (very slow) interpreter."""
    return (os.environ.get("PILOSA_TPU_PALLAS", "0") == "1"
            and jax.default_backend() == "tpu" and available())


def _pad_rows(x, block):
    s = x.shape[0]
    pad = (-s) % block
    if pad:
        x = jnp.pad(x, ((0, pad),) + ((0, 0),) * (x.ndim - 1))
    return x


# ---------------------------------------------------------------------------
# Count(expr) over a shard stack
# ---------------------------------------------------------------------------

def _count_expr_kernel(ops, n_blocks):
    """Kernel: fold `ops` over the operand blocks, popcount, and accumulate
    into a lane-resident [8, 128] int32 scratch across grid steps (vector
    adds only — no scalar reduce until the final host-side sum)."""
    from jax.experimental import pallas as pl

    def kernel(*refs):
        from ..parallel.sharded import apply_op_chain

        out_ref, acc_ref = refs[-2], refs[-1]
        acc = apply_op_chain(
            refs[0][:], [r[:] for r in refs[1:-2]], ops)
        pc = jax.lax.population_count(acc).astype(jnp.int32)
        part = jnp.sum(
            pc.reshape(_BLOCK_ROWS, WORDS_PER_ROW // 128, 128), axis=1)
        part = jnp.sum(part.reshape(_BLOCK_ROWS // 8, 8, 128), axis=0)

        @pl.when(pl.program_id(0) == 0)
        def _init():
            acc_ref[:] = jnp.zeros((8, 128), jnp.int32)

        acc_ref[:] += part

        @pl.when(pl.program_id(0) == n_blocks - 1)
        def _flush():
            out_ref[:] = acc_ref[:]

    return kernel


@functools.lru_cache(maxsize=64)
def _count_expr_call(ops, n_rows, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    arity = len(ops) + 1
    n_blocks = n_rows // _BLOCK_ROWS
    spec = pl.BlockSpec((_BLOCK_ROWS, WORDS_PER_ROW), lambda i: (i, 0))

    call = pl.pallas_call(
        _count_expr_kernel(ops, n_blocks),
        grid=(n_blocks,),
        in_specs=[spec] * arity,
        out_specs=pl.BlockSpec((8, 128), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((8, 128), jnp.int32),
        scratch_shapes=[pltpu.VMEM((8, 128), jnp.int32)],
        interpret=interpret,
    )

    @jax.jit
    def run(*planes):
        return jnp.sum(call(*planes))

    return run


def count_expr_stack(first, rest, ops):
    """sum(popcount(fold(ops, first, rest))) over a [S, W] uint32 stack.

    `ops` is a chain like ("&", "-") applied left-to-right (the kernel folds
    it with parallel.sharded.apply_op_chain — ONE definition of expression
    semantics, validated there). Zero-padding rows is safe: padding
    contributes popcount(0 op 0) = 0 for every op chain whose first operand
    is 0 — true for &, |, ^, and &~.
    """
    ops = tuple(ops)
    if len(ops) != len(rest):
        raise ValueError(
            f"op chain length {len(ops)} != operand count {len(rest)}")
    if first.shape[0] == 0:
        return jnp.int32(0)  # empty grid would never write the output
    planes = [_pad_rows(jnp.asarray(p), _BLOCK_ROWS)
              for p in (first, *rest)]
    run = _count_expr_call(ops, planes[0].shape[0], _interpret())
    return run(*planes)


def count_intersect_stack(a, b):
    """Fused Count(Intersect(a, b)) over shard stacks — the north star."""
    return count_expr_stack(a, [b], ("&",))


# ---------------------------------------------------------------------------
# Compressed-container block popcounts (ops/containers.py block-sparse repr)
# ---------------------------------------------------------------------------
#
# A block-sparse container stores only the non-empty BLOCK_WORDS=128-word
# blocks of a plane stack as [NB, 128] uint32 — already the native TPU
# tile shape, so each grid step streams 8 blocks from HBM and accumulates
# their popcounts into the same lane-resident [8, 128] int32 tile the
# count kernels use. The fused AND variant counts a two-operand sparse
# intersect chain in one compressed pass (the caller aligns operand B
# onto A's block index first; unmatched blocks arrive zeroed).
#
# PERF STATUS: correctness is covered by the containers differential
# suite (interpreter mode on CPU); device time on a real chip is
# UNMEASURED — like every kernel here these stay opt-in
# (PILOSA_TPU_PALLAS=1) and the jnp popcount path is the default.
# Int32 accumulation is safe under the chooser's gate (a container is
# only built compressed when its stack holds < 2^31 bits).

# Blocks per grid step: 8 sublanes x 128 lanes = one int32 tile.
_CB_BLOCK_ROWS = 8


def _count_blocks_kernel(n_steps, fuse_and):
    from jax.experimental import pallas as pl

    def kernel(*refs):
        out_ref, acc_ref = refs[-2], refs[-1]
        x = refs[0][:] & refs[1][:] if fuse_and else refs[0][:]
        pc = jax.lax.population_count(x).astype(jnp.int32)

        @pl.when(pl.program_id(0) == 0)
        def _init():
            acc_ref[:] = jnp.zeros((_CB_BLOCK_ROWS, 128), jnp.int32)

        acc_ref[:] += pc

        @pl.when(pl.program_id(0) == n_steps - 1)
        def _flush():
            out_ref[:] = acc_ref[:]

    return kernel


@functools.lru_cache(maxsize=32)
def _count_blocks_call(n_rows, fuse_and, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_steps = n_rows // _CB_BLOCK_ROWS
    spec = pl.BlockSpec((_CB_BLOCK_ROWS, 128), lambda i: (i, 0))
    call = pl.pallas_call(
        _count_blocks_kernel(n_steps, fuse_and),
        grid=(n_steps,),
        in_specs=[spec] * (2 if fuse_and else 1),
        out_specs=pl.BlockSpec((_CB_BLOCK_ROWS, 128), lambda i: (0, 0)),
        out_shape=jax.ShapeDtypeStruct((_CB_BLOCK_ROWS, 128), jnp.int32),
        scratch_shapes=[pltpu.VMEM((_CB_BLOCK_ROWS, 128), jnp.int32)],
        interpret=interpret,
    )

    @jax.jit
    def run(*blocks):
        return jnp.sum(call(*blocks))

    return run


def count_blocks_stack(blocks):
    """Σ popcount over a [NB, 128] uint32 block stack (zero-padding rows
    count zero). Traced inside the compressed serving programs."""
    if blocks.shape[0] == 0:
        return jnp.int32(0)
    blocks = _pad_rows(jnp.asarray(blocks), _CB_BLOCK_ROWS)
    run = _count_blocks_call(blocks.shape[0], False, _interpret())
    return run(blocks)


def count_and_blocks_stack(a, b):
    """Σ popcount(a & b) over block-aligned [NB, 128] stacks — the fused
    compressed intersect-count (operands pre-aligned by the caller)."""
    if a.shape[0] == 0:
        return jnp.int32(0)
    a = _pad_rows(jnp.asarray(a), _CB_BLOCK_ROWS)
    b = _pad_rows(jnp.asarray(b), _CB_BLOCK_ROWS)
    run = _count_blocks_call(a.shape[0], True, _interpret())
    return run(a, b)


# ---------------------------------------------------------------------------
# TopN: per-row filtered popcounts
# ---------------------------------------------------------------------------

def _topn_kernel(r_blk):
    from jax.experimental import pallas as pl  # noqa: F401

    def kernel(rows_ref, filt_ref, out_ref):
        # rows_ref: [r_blk, W]; filt_ref: [1, W]; out_ref: [r_blk, 128].
        # Counts broadcast across a 128-lane minor dim to satisfy TPU tiling;
        # the caller reads lane 0.
        masked = rows_ref[:] & filt_ref[:]
        sums = jnp.sum(
            jax.lax.population_count(masked).astype(jnp.int32), axis=-1)
        out_ref[:] = jnp.broadcast_to(sums[:, None], (r_blk, 128))

    return kernel


@functools.lru_cache(maxsize=16)
def _topn_call(n_rows, interpret):
    from jax.experimental import pallas as pl

    grid = (n_rows // _BLOCK_ROWS,)
    call = pl.pallas_call(
        _topn_kernel(_BLOCK_ROWS),
        grid=grid,
        in_specs=[
            pl.BlockSpec((_BLOCK_ROWS, WORDS_PER_ROW), lambda i: (i, 0)),
            pl.BlockSpec((1, WORDS_PER_ROW), lambda i: (0, 0)),
        ],
        out_specs=pl.BlockSpec((_BLOCK_ROWS, 128), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n_rows, 128), jnp.int32),
        interpret=interpret,
    )

    @jax.jit
    def run(rows, filt):
        return call(rows, filt)[:, 0]

    return run


# ---------------------------------------------------------------------------
# Pairwise intersect-count matrix (GroupBy cross product)
# ---------------------------------------------------------------------------
#
# counts[i, j] = Σ_w popcount(A[i] & B[j] & filt) — matmul loop structure
# with popcount+add in place of multiply+add: the grid walks (A block,
# B block, word block) with the word axis innermost, the [8, 128] count
# tile accumulates in place across word blocks, and each step streams one
# B row block against the A block while the output tile stays resident.

# A rows per block (sublanes of the output tile).
_PW_A_BLOCK = 8
# B rows per block (lanes of the output tile).
_PW_B_BLOCK = 128
# Words per grid step: B block 128 x 4096 x 4 B = 2 MiB in VMEM; the
# flattened [R, S*W] word axis is always a multiple (W = 32768).
_PW_BLOCK_WORDS = 4096


def _pairwise_kernel(has_filt):
    from jax.experimental import pallas as pl

    def kernel(*refs):
        if has_filt:
            a_ref, b_ref, filt_ref, out_ref = refs
            a = a_ref[:] & filt_ref[:]
        else:
            a_ref, b_ref, out_ref = refs
            a = a_ref[:]
        b = b_ref[:]
        # Unrolled over the (static, small) A block: each step is a
        # [B_BLOCK, W_BLOCK] AND+popcount reduced to one output row.
        rows = []
        for i in range(_PW_A_BLOCK):
            pc = jax.lax.population_count(a[i][None, :] & b)
            rows.append(jnp.sum(pc.astype(jnp.int32), axis=-1))
        part = jnp.stack(rows)                   # [A_BLOCK, B_BLOCK]

        @pl.when(pl.program_id(2) == 0)
        def _init():
            out_ref[:] = jnp.zeros((_PW_A_BLOCK, _PW_B_BLOCK), jnp.int32)

        out_ref[:] += part

    return kernel


@functools.lru_cache(maxsize=32)
def _pairwise_call(n_r1, n_r2, n_words, has_filt, interpret):
    from jax.experimental import pallas as pl

    grid = (n_r1 // _PW_A_BLOCK, n_r2 // _PW_B_BLOCK,
            n_words // _PW_BLOCK_WORDS)
    in_specs = [
        pl.BlockSpec((_PW_A_BLOCK, _PW_BLOCK_WORDS),
                     lambda i, j, w: (i, w)),
        pl.BlockSpec((_PW_B_BLOCK, _PW_BLOCK_WORDS),
                     lambda i, j, w: (j, w)),
    ]
    if has_filt:
        in_specs.append(
            pl.BlockSpec((1, _PW_BLOCK_WORDS), lambda i, j, w: (0, w)))
    call = pl.pallas_call(
        _pairwise_kernel(has_filt),
        grid=grid,
        in_specs=in_specs,
        out_specs=pl.BlockSpec((_PW_A_BLOCK, _PW_B_BLOCK),
                               lambda i, j, w: (i, j)),
        out_shape=jax.ShapeDtypeStruct((n_r1, n_r2), jnp.int32),
        interpret=interpret,
    )
    return jax.jit(call)


def pairwise_counts_stack(a, b, filt=None):
    """[R1, R2] int32 pairwise intersect-count matrix over row stacks
    a [R1, S, W] and b [R2, S, W] (filt [S, W] optional). Plain int32
    accumulation — callers gate on S*SHARD_WIDTH < 2^31 set bits, exactly
    as QueryKernels.count_expr gates the count kernels. Zero padding rows
    contributes zero counts and is sliced off before returning."""
    r1, r2 = a.shape[0], b.shape[0]
    if r1 == 0 or r2 == 0:
        return jnp.zeros((r1, r2), jnp.int32)
    t = a.shape[1] * a.shape[2]
    a2 = _pad_rows(jnp.asarray(a).reshape(r1, t), _PW_A_BLOCK)
    b2 = _pad_rows(jnp.asarray(b).reshape(r2, t), _PW_B_BLOCK)
    run = _pairwise_call(a2.shape[0], b2.shape[0], t, filt is not None,
                         _interpret())
    if filt is not None:
        out = run(a2, b2, jnp.asarray(filt).reshape(1, t))
    else:
        out = run(a2, b2)
    return out[:r1, :r2]


# ---------------------------------------------------------------------------
# Fused BSI range compare (reference: rangeLTUnsigned fragment.go:1357-1400)
# ---------------------------------------------------------------------------
#
# The jnp path (ops/bsi.py) computes the (lt, eq, gt) comparator masks with
# a lax.scan, then combines with sign/exists in a second jitted call — XLA
# materializes the intermediate masks between the two programs. This kernel
# fuses the whole range op into ONE pass: each grid step streams a word
# block of all D magnitude planes + sign + exists from HBM once, unrolls
# the MSB-first comparator over the (static) depth with the predicate bits
# read from SMEM, applies the sign-magnitude combine for the (static)
# operator, and writes only the final row mask.
#
# PERF STATUS (honest, unlike a claimed win): correctness is verified
# against the jnp path by the differential suite (test_pallas.py,
# interpreter mode), but the fusion's device-time advantage is UNMEASURED —
# the count kernels above measured at parity with XLA's own fusion, and the
# same may hold here. Like them, this kernel stays opt-in
# (PILOSA_TPU_PALLAS=1), never the default. Measurement recipe (real chip):
#   time bsi_range_mask("lt", planes[D=16], sign, exists, pbits, False,
#   True) vs ops.bsi._range_lt_jnp on the same [16, WORDS_PER_ROW] inputs,
#   n>=30 dispatches, block_until_ready on the batch; record both ms here.

# Words per grid step of the BSI kernel. D+2 blocks of W_BLK words must fit
# VMEM with double buffering: 64 planes x 4 KiB x 4 B = 1 MiB per step.
_BSI_BLOCK_WORDS = 4096


def _bsi_range_kernel(op, allow_eq, neg_pred, depth):
    from jax.experimental import pallas as pl  # noqa: F401

    def kernel(pbits_ref, planes_ref, sign_ref, exists_ref, out_ref):
        _FULL = jnp.uint32(0xFFFFFFFF)  # built in-kernel: no captured consts
        w = planes_ref.shape[-1]
        eq = jnp.full((1, w), _FULL, dtype=jnp.uint32)
        lt = jnp.zeros((1, w), dtype=jnp.uint32)
        gt = jnp.zeros((1, w), dtype=jnp.uint32)
        # MSB-first unrolled comparator (zero-padded planes above the real
        # MSB carry pbit 0 and plane 0: an exact no-op on (lt, eq, gt)).
        for d in range(depth - 1, -1, -1):
            plane = planes_ref[d][None, :]
            pmask = jnp.where(pbits_ref[d] == 1, _FULL, jnp.uint32(0))
            gt = gt | (eq & plane & ~pmask)
            lt = lt | (eq & ~plane & pmask)
            eq = eq & ~(plane ^ pmask)
        sign = sign_ref[:]
        exists = exists_ref[:]
        pos = exists & ~sign
        neg = exists & sign
        eq_mask = _FULL if allow_eq else jnp.uint32(0)
        if op == "eq":
            base = neg if neg_pred else pos
            out = base & eq
        elif op == "lt":
            # (reference: rangeLT fragment.go:1335; ops/bsi.range_lt)
            if neg_pred:
                out = neg & (gt | (eq & eq_mask))
            else:
                out = neg | (pos & (lt | (eq & eq_mask)))
        else:  # gt (reference: rangeGT fragment.go:1403)
            if neg_pred:
                out = pos | (neg & (lt | (eq & eq_mask)))
            else:
                out = pos & (gt | (eq & eq_mask))
        out_ref[:] = out

    return kernel


@functools.lru_cache(maxsize=64)
def _bsi_range_call(op, allow_eq, neg_pred, depth, interpret):
    from jax.experimental import pallas as pl
    from jax.experimental.pallas import tpu as pltpu

    n_blocks = WORDS_PER_ROW // _BSI_BLOCK_WORDS
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=1,  # pbits [depth] int32 in SMEM
        grid=(n_blocks,),
        in_specs=[
            pl.BlockSpec((depth, _BSI_BLOCK_WORDS), lambda i, _: (0, i)),
            pl.BlockSpec((1, _BSI_BLOCK_WORDS), lambda i, _: (0, i)),
            pl.BlockSpec((1, _BSI_BLOCK_WORDS), lambda i, _: (0, i)),
        ],
        out_specs=pl.BlockSpec((1, _BSI_BLOCK_WORDS), lambda i, _: (0, i)),
    )
    call = pl.pallas_call(
        _bsi_range_kernel(op, allow_eq, neg_pred, depth),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((1, WORDS_PER_ROW), jnp.uint32),
        interpret=interpret,
    )

    @jax.jit
    def run(pbits, planes, sign, exists):
        return call(pbits, planes, sign[None, :], exists[None, :])[0]

    return run


def bsi_range_mask(op, planes, sign, exists, pbits, neg_pred, allow_eq):
    """Fused signed BSI range compare: one HBM pass over all planes.

    op: "eq" | "lt" | "gt" (NEQ composes as exists − eq at the caller,
    matching ops/bsi.py). planes: [D, W] magnitude bit planes (LSB first);
    sign/exists: [W]; pbits: [D] 0/1 predicate magnitude bits; neg_pred /
    allow_eq: static Python bools. Semantics are identical to
    ops.bsi.range_eq/range_lt/range_gt (differential-tested)."""
    planes = jnp.asarray(planes)
    depth = planes.shape[0]
    pbits = jnp.asarray(pbits, dtype=jnp.int32)
    # pad depth to a sublane multiple; zero planes with zero pbits are
    # comparator no-ops (see kernel comment)
    pad = (-depth) % 8
    if pad:
        planes = jnp.pad(planes, ((0, pad), (0, 0)))
        pbits = jnp.pad(pbits, (0, pad))
    run = _bsi_range_call(op, bool(allow_eq), bool(neg_pred),
                          int(planes.shape[0]), _interpret())
    return run(pbits, planes, jnp.asarray(sign), jnp.asarray(exists))


def topn_counts_stack(rows, filter_plane, k):
    """Per-row popcount(row & filter) then top_k — reference: fragment.top
    fragment.go:1570. rows: [R, W]; filter_plane: [W]. Returns (vals, idx),
    both [k]; callers drop zero-count entries (as bitplane.topn_counts)."""
    n = rows.shape[0]
    if n == 0:
        return jnp.zeros(k, jnp.int32), jnp.zeros(k, jnp.int32)
    rows = _pad_rows(jnp.asarray(rows), _BLOCK_ROWS)
    run = _topn_call(rows.shape[0], _interpret())
    counts = run(rows, jnp.asarray(filter_plane)[None, :])[:n]
    return jax.lax.top_k(counts, k)
