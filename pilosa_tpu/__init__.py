"""pilosa_tpu — a TPU-native distributed bitmap index.

A from-scratch framework with the capabilities of the reference (Pilosa v1.4:
distributed roaring-bitmap index answering PQL), re-designed for TPU:

- set algebra runs as dense bit-plane kernels in HBM (`pilosa_tpu.ops`),
- shards map onto a `jax.sharding.Mesh`; cross-shard reduces ride ICI
  collectives (`pilosa_tpu.parallel`),
- roaring remains the host-side interchange/at-rest format
  (`pilosa_tpu.roaring`),
- the metadata tree (holder/index/field/view/fragment), PQL, executor, HTTP
  API, and cluster control plane mirror the reference's public capabilities
  (`pilosa_tpu.core`, `.pql`, `.exec`, `.server`).

Heavy imports (jax) are deferred: importing `pilosa_tpu` alone loads no
device code.
"""

__version__ = "0.1.0"

from .shardwidth import SHARD_WIDTH
