"""Host-side durable stores (reference: boltdb/ — BoltDB-backed attribute
and key-translation stores, attr.go, translate.go).

The reference keeps these on the CPU/disk side of the system and so do we
(SURVEY.md §2 #18/#19: "stays on CPU per north star"). SQLite replaces
BoltDB as the embedded KV engine; the interfaces mirror the reference's
`AttrStore` (attr.go:34) and `TranslateStore` (translate.go:35).
"""

from .attrs import AttrStore, SqliteAttrStore, MemAttrStore
from .oplog import OpLog, OpLogError, fsync_policy, set_fsync_policy
from .translate import (
    TranslateStore,
    SqliteTranslateStore,
    MemTranslateStore,
    TranslateEntry,
    TranslateReadOnlyError,
)

__all__ = [
    "OpLog",
    "OpLogError",
    "fsync_policy",
    "set_fsync_policy",
    "AttrStore",
    "SqliteAttrStore",
    "MemAttrStore",
    "TranslateStore",
    "SqliteTranslateStore",
    "MemTranslateStore",
    "TranslateEntry",
    "TranslateReadOnlyError",
]
