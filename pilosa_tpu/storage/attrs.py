"""Attribute stores: arbitrary K/V attributes on rows and columns.

Reference: attr.go:34 (AttrStore interface), boltdb/attrstore.go:67-398
(BoltDB impl with LRU cache and per-block checksums used by anti-entropy
attr diffing, api.go:817-891).

Semantics mirrored from the reference:
- set_attrs MERGES into existing attrs; a None value deletes that key
  (attr.go SetAttrs / cloneAttrs).
- Values are str | int | float | bool | list[str].
- blocks() returns (block_id, checksum) per 100-id block
  (attrBlockSize attr.go:30); block_data(block) returns {id: attrs} for
  cross-node diffing.
"""

import hashlib
import json
import sqlite3
import threading

ATTR_BLOCK_SIZE = 100  # reference: attrBlockSize attr.go:30
_CACHE_SIZE = 8192     # reference: attrCacheSize boltdb/attrstore.go


def _validate_attrs(attrs):
    for k, v in attrs.items():
        if not isinstance(k, str):
            raise TypeError(f"attr key must be str: {k!r}")
        if v is None:
            continue
        if isinstance(v, (str, bool, int, float)):
            continue
        if isinstance(v, list) and all(isinstance(x, str) for x in v):
            continue
        raise TypeError(f"unsupported attr value for {k!r}: {v!r}")


def _merge(existing, updates):
    out = dict(existing)
    for k, v in updates.items():
        if v is None:
            out.pop(k, None)
        else:
            out[k] = v
    return out


def _checksum(items):
    """Checksum over sorted (id, canonical-json attrs) pairs."""
    h = hashlib.blake2b(digest_size=8)
    for id, attrs in sorted(items):
        h.update(str(id).encode())
        h.update(json.dumps(attrs, sort_keys=True).encode())
    return h.hexdigest()


class AttrStore:
    """Abstract store (reference: AttrStore attr.go:34)."""

    def attrs(self, id):
        raise NotImplementedError

    def set_attrs(self, id, attrs):
        raise NotImplementedError

    def set_bulk_attrs(self, attr_map):
        for id, attrs in attr_map.items():
            self.set_attrs(id, attrs)

    def all_items(self):
        raise NotImplementedError

    def _grouped(self):
        """{block_id: [(id, attrs)]} in one store scan."""
        by_block = {}
        for id, attrs in self.all_items():
            by_block.setdefault(id // ATTR_BLOCK_SIZE, []).append((id, attrs))
        return by_block

    def blocks(self):
        """[(block_id, checksum)] for every non-empty 100-id block."""
        return sorted((b, _checksum(items))
                      for b, items in self._grouped().items())

    def diff(self, remote_blocks):
        """{id: attrs} from every local block whose checksum differs from
        (or is absent in) the caller's [(id, checksum)] dict list — one
        round of attr anti-entropy, in a single store scan (reference:
        attrBlocks.Diff attr.go:90 + api.IndexAttrDiff api.go:817)."""
        remote = {int(b["id"]): b.get("checksum")
                  for b in (remote_blocks or [])}
        out = {}
        for bid, items in self._grouped().items():
            if remote.get(bid) != _checksum(items):
                out.update(items)
        return out

    def block_data(self, block_id):
        lo = block_id * ATTR_BLOCK_SIZE
        hi = lo + ATTR_BLOCK_SIZE
        return {id: attrs for id, attrs in self.all_items() if lo <= id < hi}

    def close(self):
        pass


class SqliteAttrStore(AttrStore):
    """SQLite-backed store with a small read cache."""

    def __init__(self, path):
        self.path = path
        self._lock = threading.RLock()
        self._cache = {}
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS attrs ("
            " id INTEGER PRIMARY KEY, data TEXT NOT NULL)")
        self._db.commit()

    def attrs(self, id):
        id = int(id)
        with self._lock:
            hit = self._cache.get(id)
            if hit is not None:
                return dict(hit)
            row = self._db.execute(
                "SELECT data FROM attrs WHERE id=?", (id,)).fetchone()
            attrs = json.loads(row[0]) if row is not None else {}
            if len(self._cache) >= _CACHE_SIZE:
                self._cache.clear()
            self._cache[id] = attrs
        return dict(attrs)

    def attrs_many(self, ids):
        """{id: attrs} for ids that HAVE attrs — one batched SELECT per 500
        ids instead of a query per column (columnAttrs response path)."""
        out = {}
        ids = [int(i) for i in ids]
        with self._lock:
            for i in range(0, len(ids), 500):
                chunk = ids[i:i + 500]
                marks = ",".join("?" * len(chunk))
                for id_, data in self._db.execute(
                        f"SELECT id, data FROM attrs WHERE id IN ({marks})",
                        chunk):
                    attrs = json.loads(data)
                    if attrs:
                        out[int(id_)] = attrs
        return out

    def set_attrs(self, id, attrs):
        _validate_attrs(attrs)
        id = int(id)
        with self._lock:
            merged = _merge(self.attrs(id), attrs)
            self._db.execute(
                "INSERT OR REPLACE INTO attrs(id, data) VALUES (?, ?)",
                (id, json.dumps(merged, sort_keys=True)))
            self._db.commit()
            self._cache[id] = merged
        return merged

    def set_bulk_attrs(self, attr_map):
        with self._lock:
            for id, attrs in attr_map.items():
                _validate_attrs(attrs)
                merged = _merge(self.attrs(int(id)), attrs)
                self._db.execute(
                    "INSERT OR REPLACE INTO attrs(id, data) VALUES (?, ?)",
                    (int(id), json.dumps(merged, sort_keys=True)))
                self._cache[int(id)] = merged
            self._db.commit()

    def all_items(self):
        with self._lock:
            rows = self._db.execute(
                "SELECT id, data FROM attrs ORDER BY id").fetchall()
        return [(int(id), json.loads(data)) for id, data in rows]

    def close(self):
        with self._lock:
            self._db.close()


class MemAttrStore(AttrStore):
    """In-memory store (tests / cache-less mode)."""

    def __init__(self):
        self._data = {}
        self._lock = threading.RLock()

    def attrs(self, id):
        with self._lock:
            return dict(self._data.get(int(id), {}))

    def attrs_many(self, ids):
        with self._lock:
            return {int(i): dict(self._data[int(i)]) for i in ids
                    if self._data.get(int(i))}

    def set_attrs(self, id, attrs):
        _validate_attrs(attrs)
        with self._lock:
            merged = _merge(self._data.get(int(id), {}), attrs)
            self._data[int(id)] = merged
        return merged

    def all_items(self):
        with self._lock:
            return sorted((i, dict(a)) for i, a in self._data.items())
