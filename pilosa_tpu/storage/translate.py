"""Key translation: string key <-> uint64 ID, per-index (columns) and
per-field (rows).

Reference: translate.go:35-70 (TranslateStore interface), boltdb/translate.go
(BoltDB impl, monotonic IDs from a bucket sequence), holder.go:702-880
(primary -> replica streaming replication via TranslateEntryReader).

TPU-native design: translation is pure host-side metadata (IDs are what land
on device planes), so the store is an embedded SQLite table with an
autoincrementing rowid — the same monotonic-allocation semantics as the
reference's bucket sequence. Replication uses `entries(offset)` which
yields (id, key) pairs in ID order, the same contract as the reference's
EntryReader (boltdb/translate.go:290).

Writes are only legal on the primary; replicas mark the store read-only and
raise TranslateReadOnlyError so callers redirect to the primary (reference:
ErrTranslateStoreReadOnly, http/handler.go:518-522).
"""

import sqlite3
import threading


class TranslateReadOnlyError(Exception):
    """Raised when a key would be created on a read-only (replica) store."""


class TranslateEntry:
    """One key/ID pair in the replication stream (reference:
    TranslateEntry translate.go:73)."""

    __slots__ = ("index", "field", "id", "key")

    def __init__(self, index="", field="", id=0, key=""):
        self.index = index
        self.field = field
        self.id = id
        self.key = key

    def to_json(self):
        out = {"id": self.id, "key": self.key}
        if self.index:
            out["index"] = self.index
        if self.field:
            out["field"] = self.field
        return out

    @classmethod
    def from_json(cls, d):
        return cls(index=d.get("index", ""), field=d.get("field", ""),
                   id=d["id"], key=d["key"])

    def __repr__(self):
        return f"TranslateEntry({self.index}/{self.field}: {self.id}={self.key!r})"


class TranslateStore:
    """Abstract store (reference: TranslateStore translate.go:35)."""

    def __init__(self, index="", field=""):
        self.index = index
        self.field = field
        self._read_only = False
        # Replica-side hook: called with the missing keys when a create
        # hits a read-only store; must return their ids (allocated on the
        # primary). Installed by the TranslateReplicator (reference:
        # ErrTranslateStoreReadOnly redirect http/handler.go:518-522).
        self.remote_create = None

    # -- read-only flag ------------------------------------------------------

    @property
    def read_only(self):
        return self._read_only

    def set_read_only(self, v):
        self._read_only = bool(v)

    # -- interface -----------------------------------------------------------

    def max_id(self):
        raise NotImplementedError

    def translate_key(self, key, create=True):
        """key -> id, allocating a new monotonic id when absent (unless the
        store is read-only or create=False). Returns None when absent and
        not created."""
        return self.translate_keys([key], create=create)[0]

    def translate_keys(self, keys, create=True):
        try:
            return self._translate_keys(keys, create=create)
        except TranslateReadOnlyError:
            if self.remote_create is None:
                raise
            # allocate on the primary, then mirror locally so subsequent
            # lookups resolve before the replication poll catches up
            ids = self.remote_create(list(keys))
            for key, id in zip(keys, ids):
                self.force_set(id, key)
            return ids

    def _translate_keys(self, keys, create=True):
        raise NotImplementedError

    def translate_id(self, id):
        return self.translate_ids([id])[0]

    def translate_ids(self, ids):
        raise NotImplementedError

    def force_set(self, id, key):
        """Write a key/id pair even when read-only (replication apply)."""
        raise NotImplementedError

    def entries(self, offset=0):
        """Yield TranslateEntry for every pair with id > offset, in id
        order (replication read side)."""
        raise NotImplementedError

    def close(self):
        pass


class SqliteTranslateStore(TranslateStore):
    """SQLite-backed store; one file per (index[, field]).

    IDs allocate from 1 monotonically (INTEGER PRIMARY KEY AUTOINCREMENT
    never reuses rowids, matching the reference's bucket sequence)."""

    def __init__(self, path, index="", field=""):
        super().__init__(index, field)
        self.path = path
        self._lock = threading.RLock()
        self._db = sqlite3.connect(path, check_same_thread=False)
        self._db.execute("PRAGMA journal_mode=WAL")
        self._db.execute(
            "CREATE TABLE IF NOT EXISTS keys ("
            " id INTEGER PRIMARY KEY AUTOINCREMENT,"
            " key TEXT NOT NULL UNIQUE)")
        self._db.commit()

    def max_id(self):
        with self._lock:
            row = self._db.execute("SELECT MAX(id) FROM keys").fetchone()
        return int(row[0] or 0)

    def _translate_keys(self, keys, create=True):
        for key in keys:
            if not isinstance(key, str):
                raise TypeError(f"translate key must be str: {key!r}")
        out = []
        with self._lock:
            created = False
            try:
                for key in keys:
                    row = self._db.execute(
                        "SELECT id FROM keys WHERE key=?", (key,)).fetchone()
                    if row is not None:
                        out.append(int(row[0]))
                        continue
                    if not create:
                        out.append(None)
                        continue
                    if self._read_only:
                        raise TranslateReadOnlyError(
                            f"translate store read only:"
                            f" {self.index}/{self.field}")
                    cur = self._db.execute(
                        "INSERT INTO keys(key) VALUES (?)", (key,))
                    out.append(int(cur.lastrowid))
                    created = True
            except BaseException:
                if created:
                    self._db.rollback()
                raise
            if created:
                self._db.commit()
        return out

    def translate_ids(self, ids):
        out = []
        with self._lock:
            for id in ids:
                row = self._db.execute(
                    "SELECT key FROM keys WHERE id=?", (int(id),)).fetchone()
                out.append(row[0] if row is not None else None)
        return out

    def force_set(self, id, key):
        with self._lock:
            self._db.execute(
                "INSERT OR REPLACE INTO keys(id, key) VALUES (?, ?)",
                (int(id), key))
            # keep AUTOINCREMENT's high-water mark >= id so future local
            # allocations (if ever promoted to primary) don't collide
            self._db.execute(
                "UPDATE sqlite_sequence SET seq=MAX(seq, ?) WHERE name='keys'",
                (int(id),))
            self._db.commit()

    def entries(self, offset=0):
        with self._lock:
            rows = self._db.execute(
                "SELECT id, key FROM keys WHERE id > ? ORDER BY id",
                (int(offset),)).fetchall()
        for id, key in rows:
            yield TranslateEntry(self.index, self.field, int(id), key)

    def close(self):
        with self._lock:
            self._db.close()


class MemTranslateStore(TranslateStore):
    """In-memory store (reference: translate.go:195-330 in-mem impl)."""

    def __init__(self, index="", field=""):
        super().__init__(index, field)
        self._by_key = {}
        self._by_id = {}
        self._max = 0
        self._lock = threading.RLock()

    def max_id(self):
        return self._max

    def _translate_keys(self, keys, create=True):
        out = []
        with self._lock:
            for key in keys:
                if not isinstance(key, str):
                    raise TypeError(f"translate key must be str: {key!r}")
                id = self._by_key.get(key)
                if id is None:
                    if not create:
                        out.append(None)
                        continue
                    if self._read_only:
                        raise TranslateReadOnlyError(
                            f"translate store read only: "
                            f"{self.index}/{self.field}")
                    self._max += 1
                    id = self._max
                    self._by_key[key] = id
                    self._by_id[id] = key
                out.append(id)
        return out

    def translate_ids(self, ids):
        with self._lock:
            return [self._by_id.get(int(i)) for i in ids]

    def force_set(self, id, key):
        with self._lock:
            self._by_key[key] = int(id)
            self._by_id[int(id)] = key
            self._max = max(self._max, int(id))

    def entries(self, offset=0):
        with self._lock:
            items = sorted(
                (i, k) for i, k in self._by_id.items() if i > offset)
        for id, key in items:
            yield TranslateEntry(self.index, self.field, id, key)
