"""Node-local durable write-ahead oplog (storage/oplog.py).

The fragment layer's file is already ``snapshot ++ op log`` (reference:
fragment.go), but nothing above it is durable: the API acks an import
after an in-memory apply, and a queued resize write dies with the
process. This module closes that gap with a node-level WAL the API
appends to BEFORE any ack can return:

  - segmented append-only log: ``oplog/seg-<first_lsn>.wal`` files of
    length-prefixed, CRC32-checksummed JSON records, rotated past
    ``segment_max_bytes``;
  - fsync policy ``always | interval | never``: per-append fsync,
    background fsync every ``fsync_interval`` seconds, or OS-cache only.
    Every append is ``write()+flush()`` regardless, so a plain process
    crash (kill -9) loses nothing even at ``never`` — the policy only
    decides exposure to power/kernel loss;
  - checkpoint-based truncation: ``CHECKPOINT`` records the last LSN
    whose effects are known durable below the log (fragments fsynced);
    whole segments at or below it are deleted;
  - torn-tail recovery: a short/corrupt record at open TRUNCATES the log
    there (flightrec ``oplog.truncated_tail``) instead of failing boot —
    a torn record was never acked, because the append path returns only
    after the full record hit the OS;
  - applied watermark: appends are acked after a synchronous apply, and
    ``mark_applied(lsn)`` advances a contiguous watermark the checkpoint
    never passes, so a checkpoint can't bless a record whose apply raced
    a fragment fsync.

Replay order is LSN order == arrival order: set-bit records are
idempotent and BSI value records are last-write-wins, so re-applying an
already-applied suffix converges to the pre-crash state.

The module also owns the PROCESS-WIDE fsync policy shared with
``core/fragment.py`` (one ``--fsync`` flag / ``[storage]`` config key
covers both layers): ``set_fsync_policy()`` + ``after_append()`` give
fragments the same always/interval/never semantics on their own op
appends, and the interval syncer thread services both.
"""

import json
import os
import struct
import threading
import time
import zlib

from ..utils import faultpoints, flightrec
from ..utils.stats import global_stats

#: record header: payload length, crc32(payload), lsn
_HEADER = struct.Struct("<IIQ")
#: upper bound on a sane record; a longer length prefix is torn garbage
MAX_RECORD_BYTES = 256 << 20

DEFAULT_SEGMENT_BYTES = 64 << 20
DEFAULT_FSYNC_INTERVAL = 0.05

FSYNC_MODES = ("always", "interval", "never")

_CHECKPOINT = "CHECKPOINT"


class OpLogError(Exception):
    pass


# -- process-wide fsync policy (shared with core/fragment.py) ---------------

_policy = "never"
_policy_interval = DEFAULT_FSYNC_INTERVAL
_dirty_lock = threading.Lock()
_dirty = set()  # file objects awaiting an interval fsync
_syncer = None


def set_fsync_policy(mode, interval=None):
    """Install the process-wide fsync policy (``--fsync`` / ``[storage]
    fsync``). Fragments and any OpLog built without an explicit mode
    follow it."""
    global _policy, _policy_interval
    if mode not in FSYNC_MODES:
        raise ValueError(
            f"invalid fsync mode {mode!r} (want one of {FSYNC_MODES})")
    _policy = mode
    if interval is not None:
        _policy_interval = float(interval)
    if mode == "interval":
        _ensure_syncer()


def fsync_policy():
    return _policy


def fsync_file(f, stat_name=None):
    """flush+fsync one file object, timing into ``stat_name``. Tolerates
    a concurrently-closed file (snapshot rename, shutdown): durability
    of a closed-and-replaced file is the replacer's problem."""
    faultpoints.reached("oplog.fsync")
    t0 = time.monotonic()
    try:
        f.flush()
        os.fsync(f.fileno())
    except (ValueError, OSError):
        return
    if stat_name is not None:
        global_stats.timing(stat_name, time.monotonic() - t0)


def after_append(f, stat_name="fragment_fsync_seconds"):
    """Durability hook for a just-flushed append (fragment op appends
    call this): fsync now (``always``), mark dirty for the background
    syncer (``interval``), or nothing (``never`` — the default, which
    keeps this a single global read on the hot path)."""
    if _policy == "never":
        return
    if _policy == "always":
        fsync_file(f, stat_name)
        return
    with _dirty_lock:
        _dirty.add(f)
    _ensure_syncer()


def _ensure_syncer():
    global _syncer
    if _syncer is not None and _syncer.is_alive():
        return
    _syncer = threading.Thread(
        target=_syncer_loop, name="fsync-interval", daemon=True)
    _syncer.start()


def _syncer_loop():
    while True:
        time.sleep(_policy_interval)
        with _dirty_lock:
            batch = list(_dirty)
            _dirty.clear()
        for f in batch:
            fsync_file(f)


# -- the oplog ---------------------------------------------------------------


class OpLog:
    """Segmented durable write-ahead log of import records.

    Thread-safe; one instance per node, living at ``<data-dir>/oplog``.
    ``append()`` returns only after the record is durable to the
    configured policy; ``mark_applied()`` is called after the write's
    synchronous apply; ``checkpoint()`` persists the applied watermark
    and drops fully-applied segments.
    """

    def __init__(self, path, fsync=None, fsync_interval=None,
                 segment_max_bytes=DEFAULT_SEGMENT_BYTES, logger=None,
                 on_rotate=None):
        self.path = path
        self.fsync = fsync if fsync is not None else _policy
        if self.fsync not in FSYNC_MODES:
            raise ValueError(f"invalid fsync mode {self.fsync!r}")
        self._fsync_interval = (fsync_interval if fsync_interval is not None
                                else _policy_interval)
        self.segment_max_bytes = int(segment_max_bytes)
        self.logger = logger
        #: called with the just-sealed segment's last LSN after a
        #: rotation — the API hooks a fragment-fsync + checkpoint here
        #: so the log stays bounded without a periodic ticker
        self.on_rotate = on_rotate

        self._lock = threading.RLock()
        self._file = None
        # [{name, first_lsn, last_lsn, bytes}] in LSN order; the last
        # entry is the active segment
        self._segments = []
        self._next_lsn = 1
        self._checkpoint_lsn = 0
        self._applied_lsn = 0
        self._applied_gap = set()  # lsns applied out of order
        self._appends = 0
        self._total_bytes = 0
        self._truncated_tail = 0
        self._replayed = 0
        self._opened = False

    # -- lifecycle -----------------------------------------------------------

    def open(self):
        """Scan segments, recover the torn tail, open for append."""
        os.makedirs(self.path, exist_ok=True)
        self._checkpoint_lsn = self._load_checkpoint()
        self._applied_lsn = self._checkpoint_lsn
        names = sorted(n for n in os.listdir(self.path)
                       if n.startswith("seg-") and n.endswith(".wal"))
        last_lsn = self._checkpoint_lsn
        for i, name in enumerate(names):
            seg_path = os.path.join(self.path, name)
            first, last, good_bytes, torn = self._scan_segment(seg_path)
            if torn:
                # torn tail: truncate at the first bad record. Anything
                # past it (including later segments) was never acked —
                # the appender returns only after write+flush succeeds
                # in LSN order — so dropping it loses no acked write.
                with open(seg_path, "r+b") as f:
                    f.truncate(good_bytes)
                self._truncated_tail += 1
                flightrec.record("oplog.truncated_tail", segment=name,
                                 kept_bytes=good_bytes)
                self._log("oplog: torn tail in %s — truncated to %d "
                          "bytes", name, good_bytes)
                for later in names[i + 1:]:
                    os.unlink(os.path.join(self.path, later))
                    flightrec.record("oplog.truncated_tail",
                                     segment=later, kept_bytes=0)
                    self._log("oplog: dropped segment %s after torn "
                              "tail", later)
            if good_bytes == 0 and first is None:
                os.unlink(seg_path)
                if torn:
                    break
                continue
            self._segments.append({
                "name": name, "first_lsn": first, "last_lsn": last,
                "bytes": good_bytes})
            if last is not None:
                last_lsn = max(last_lsn, last)
            if torn:
                break
        self._next_lsn = last_lsn + 1
        if not self._segments:
            self._new_segment()
        else:
            active = os.path.join(self.path, self._segments[-1]["name"])
            self._file = open(active, "ab")
        if self.fsync == "interval":
            _ensure_syncer()
        self._opened = True
        self._update_gauges()
        return self

    def close(self):
        """Clean shutdown: checkpoint at the applied watermark (an
        orderly restart replays nothing) and close the active file."""
        with self._lock:
            if not self._opened:
                return
            try:
                self.checkpoint()
            except Exception:
                pass  # a failed final checkpoint only costs replay time
            if self._file is not None:
                try:
                    if self.fsync != "never":
                        fsync_file(self._file, "oplog_fsync_seconds")
                    self._file.close()
                except (ValueError, OSError):
                    pass
                self._file = None
            self._opened = False

    def _log(self, fmt, *args):
        if self.logger is not None:
            self.logger.printf(fmt, *args)

    # -- append path ---------------------------------------------------------

    def append(self, record):
        """Append one import record (a JSON-safe dict). Returns its LSN
        only after the record is durable per the fsync policy — callers
        ack AFTER this returns, which is the whole durability contract."""
        payload = json.dumps(record, separators=(",", ":")).encode()
        crc = zlib.crc32(payload)
        size = _HEADER.size + len(payload)
        rotated_last = None
        with self._lock:
            if self._file is None:
                raise OpLogError("oplog is closed")
            lsn = self._next_lsn
            self._next_lsn += 1
            self._file.write(_HEADER.pack(len(payload), crc, lsn))
            self._file.write(payload)
            # flush to the OS unconditionally: records survive a process
            # kill even at fsync=never; the policy below only adds
            # power-loss durability
            self._file.flush()
            if self.fsync == "always":
                fsync_file(self._file, "oplog_fsync_seconds")
            elif self.fsync == "interval":
                with _dirty_lock:
                    _dirty.add(self._file)
            seg = self._segments[-1]
            if seg["first_lsn"] is None:
                seg["first_lsn"] = lsn
            seg["last_lsn"] = lsn
            seg["bytes"] += size
            self._total_bytes += size
            self._appends += 1
            if seg["bytes"] >= self.segment_max_bytes:
                rotated_last = self._rotate()
        global_stats.count("oplog_appends_total")
        global_stats.gauge("oplog_bytes", self._total_bytes)
        if rotated_last is not None and self.on_rotate is not None:
            # outside the lock: the hook fsyncs fragments + checkpoints,
            # neither of which should serialize concurrent appends
            self.on_rotate(rotated_last)
        return lsn

    def _rotate(self):
        """Seal the active segment, open the next (lock held)."""
        seg = self._segments[-1]
        if self.fsync != "never":
            fsync_file(self._file, "oplog_fsync_seconds")
        self._file.close()
        last = seg["last_lsn"]
        self._new_segment()
        flightrec.record("oplog.rotate", sealed=seg["name"],
                         last_lsn=last, bytes=seg["bytes"])
        return last

    def _new_segment(self):
        name = f"seg-{self._next_lsn:016d}.wal"
        self._segments.append({
            "name": name, "first_lsn": None, "last_lsn": None, "bytes": 0})
        self._file = open(os.path.join(self.path, name), "ab")

    def sync(self):
        """Force an fsync of the active segment now."""
        with self._lock:
            if self._file is not None:
                fsync_file(self._file, "oplog_fsync_seconds")

    # -- applied watermark + checkpoint --------------------------------------

    def mark_applied(self, lsn):
        """Record that the write at ``lsn`` finished its synchronous
        apply. The watermark advances only over CONTIGUOUS applied LSNs:
        an append whose apply is still in flight pins the checkpoint
        below it, so a crash between fragment fsync and apply can never
        lose it."""
        with self._lock:
            if lsn <= self._applied_lsn:
                return
            self._applied_gap.add(lsn)
            while self._applied_lsn + 1 in self._applied_gap:
                self._applied_lsn += 1
                self._applied_gap.discard(self._applied_lsn)

    def checkpoint(self, lsn=None):
        """Persist the applied-through marker and delete whole segments
        at or below it. ``lsn`` defaults to (and is clamped by) the
        applied watermark — a checkpoint must never claim a record whose
        apply hasn't finished."""
        with self._lock:
            target = self._applied_lsn if lsn is None \
                else min(int(lsn), self._applied_lsn)
            if target < self._checkpoint_lsn:
                return self._checkpoint_lsn
            tmp = os.path.join(self.path, _CHECKPOINT + ".tmp")
            with open(tmp, "w") as f:
                json.dump({"lsn": target}, f)
                if self.fsync != "never":
                    f.flush()
                    os.fsync(f.fileno())
            os.replace(tmp, os.path.join(self.path, _CHECKPOINT))
            self._checkpoint_lsn = target
            # drop sealed segments that are entirely applied
            keep = []
            for seg in self._segments:
                sealed = seg is not self._segments[-1]
                if sealed and seg["last_lsn"] is not None \
                        and seg["last_lsn"] <= target:
                    os.unlink(os.path.join(self.path, seg["name"]))
                else:
                    keep.append(seg)
            self._segments = keep
        self._update_gauges()
        return target

    def _load_checkpoint(self):
        try:
            with open(os.path.join(self.path, _CHECKPOINT)) as f:
                return int(json.load(f)["lsn"])
        except (OSError, ValueError, KeyError):
            return 0

    # -- replay --------------------------------------------------------------

    def replay(self):
        """Yield ``(lsn, record)`` for every record past the checkpoint,
        in LSN (== arrival) order. Defensive against a record corrupted
        after open: stops there like the open-time torn-tail rule."""
        with self._lock:
            segments = [dict(s) for s in self._segments]
            ckpt = self._checkpoint_lsn
        for seg in segments:
            if seg["last_lsn"] is not None and seg["last_lsn"] <= ckpt:
                continue
            for lsn, record, _off in self._read_segment(
                    os.path.join(self.path, seg["name"])):
                if lsn <= ckpt:
                    continue
                self._replayed += 1
                yield lsn, record

    def _scan_segment(self, path):
        """(first_lsn, last_lsn, good_bytes, torn) for one segment."""
        first = last = None
        good = 0
        torn = False
        try:
            for lsn, _record, end in self._read_segment(path):
                if first is None:
                    first = lsn
                last = lsn
                good = end
            if good < os.path.getsize(path):
                torn = True
        except _TornRecord:
            torn = True
        return first, last, good, torn

    def _read_segment(self, path):
        """Yield ``(lsn, record, end_offset)`` until EOF or the first bad
        record (short header, short payload, insane length, CRC
        mismatch, undecodable JSON) — the torn-tail boundary."""
        with open(path, "rb") as f:
            off = 0
            while True:
                header = f.read(_HEADER.size)
                if not header:
                    return
                if len(header) < _HEADER.size:
                    raise _TornRecord(off)
                length, crc, lsn = _HEADER.unpack(header)
                if length > MAX_RECORD_BYTES:
                    raise _TornRecord(off)
                payload = f.read(length)
                if len(payload) < length or zlib.crc32(payload) != crc:
                    raise _TornRecord(off)
                try:
                    record = json.loads(payload.decode())
                except (UnicodeDecodeError, ValueError) as e:
                    raise _TornRecord(off) from e
                off += _HEADER.size + length
                yield lsn, record, off

    # -- observability -------------------------------------------------------

    def _update_gauges(self):
        with self._lock:
            self._total_bytes = sum(s["bytes"] for s in self._segments)
            total = self._total_bytes
        global_stats.gauge("oplog_bytes", total)

    @property
    def last_lsn(self):
        with self._lock:
            return self._next_lsn - 1

    @property
    def applied_lsn(self):
        with self._lock:
            return self._applied_lsn

    @property
    def checkpoint_lsn(self):
        with self._lock:
            return self._checkpoint_lsn

    def summary(self, compact=False):
        """State for GET /debug/oplog and the /status observability
        roll-up. ``replay_lag`` = appended-but-not-yet-applied records
        (nonzero under load or with a wedged apply); ``unapplied`` =
        records a crash right now would replay at next boot."""
        with self._lock:
            out = {
                "path": self.path,
                "fsync": self.fsync,
                "last_lsn": self._next_lsn - 1,
                "applied_lsn": self._applied_lsn,
                "checkpoint_lsn": self._checkpoint_lsn,
                "replay_lag": (self._next_lsn - 1) - self._applied_lsn,
                "unapplied": (self._next_lsn - 1) - self._checkpoint_lsn,
                "appends": self._appends,
                "bytes": sum(s["bytes"] for s in self._segments),
                "segments": len(self._segments),
                "truncated_tails": self._truncated_tail,
            }
            if not compact:
                out["segment_files"] = [dict(s) for s in self._segments]
                out["segment_max_bytes"] = self.segment_max_bytes
        return out


class _TornRecord(Exception):
    """Internal: segment read hit a torn/corrupt record at offset."""
