"""ctypes bindings for the native host-side kernels (native/pilosa_native.cpp).

Loads `native/libpilosa_native.so`, building it once with `make` if absent
(and a compiler is available). Every entry point has a pure-Python/numpy
fallback so the package works without a toolchain; `PILOSA_TPU_NATIVE=0`
forces the fallbacks.

The split mirrors the reference: query algebra is device-side
(ops/bitplane.py); this module covers the host storage loops — WAL op
checksums (reference: roaring.go:4694), position<->plane conversion on
import/export (fragment.go:1997, roaring.go:1511), and run detection for
container optimization (roaring.go:2334).
"""

import ctypes
import os
import subprocess
import threading

import numpy as np

_lock = threading.Lock()
_lib = None
_tried = False

_NATIVE_DIR = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libpilosa_native.so")


def _load():
    """Load (building if needed) the shared library; None on any failure."""
    global _lib, _tried
    if _tried:
        return _lib
    with _lock:
        if _tried:
            return _lib
        lib = None
        if os.environ.get("PILOSA_TPU_NATIVE", "1") != "0":
            try:
                # Build to a process-private name then atomically publish:
                # concurrent processes (multi-node-on-one-host, xdist) must
                # never CDLL a half-written .so. make no-ops when current.
                tmp = f"{_SO_PATH}.{os.getpid()}"
                subprocess.run(
                    ["make", "-C", _NATIVE_DIR, f"SO_OUT={tmp}"],
                    check=True, capture_output=True, timeout=120)
                if os.path.exists(tmp):
                    os.replace(tmp, _SO_PATH)
                lib = ctypes.CDLL(_SO_PATH)
                _declare(lib)
            except Exception as e:
                import warnings

                warnings.warn(
                    f"pilosa_tpu native library unavailable, using Python "
                    f"fallbacks ({type(e).__name__}: {e})", RuntimeWarning)
                lib = None
        _lib = lib
        _tried = True
        return _lib


def _declare(lib):
    u8p = ctypes.POINTER(ctypes.c_uint8)
    u16p = ctypes.POINTER(ctypes.c_uint16)
    u32p = ctypes.POINTER(ctypes.c_uint32)
    u64p = ctypes.POINTER(ctypes.c_uint64)
    i64p = ctypes.POINTER(ctypes.c_int64)
    size_t = ctypes.c_size_t

    lib.pilosa_fnv1a32.restype = ctypes.c_uint32
    lib.pilosa_fnv1a32.argtypes = [u8p, size_t, ctypes.c_uint32]
    lib.pilosa_popcount.restype = ctypes.c_int64
    lib.pilosa_popcount.argtypes = [u32p, size_t]
    lib.pilosa_popcount_per_word.restype = None
    lib.pilosa_popcount_per_word.argtypes = [u32p, size_t, i64p]
    lib.pilosa_scatter_u64.restype = size_t
    lib.pilosa_scatter_u64.argtypes = [u64p, size_t, u32p, size_t]
    lib.pilosa_scatter_u16.restype = size_t
    lib.pilosa_scatter_u16.argtypes = [u16p, size_t, u32p, size_t]
    lib.pilosa_extract_u64.restype = size_t
    lib.pilosa_extract_u64.argtypes = [u32p, size_t, u64p]
    lib.pilosa_extract_u16.restype = size_t
    lib.pilosa_extract_u16.argtypes = [u32p, size_t, u16p]
    lib.pilosa_extract_runs_u16.restype = size_t
    lib.pilosa_extract_runs_u16.argtypes = [u32p, size_t, u16p]
    lib.pilosa_fill_range.restype = None
    lib.pilosa_fill_range.argtypes = [
        u32p, size_t, ctypes.c_uint32, ctypes.c_uint32]


def enabled():
    return _load() is not None


def _ptr(arr, ctype):
    return arr.ctypes.data_as(ctypes.POINTER(ctype))


def _check_inplace(plane):
    """Functions mutating a plane require a C-contiguous uint32 buffer —
    a silent dtype/layout copy would discard the caller's writes."""
    if not (isinstance(plane, np.ndarray) and plane.dtype == np.uint32
            and plane.flags.c_contiguous and plane.flags.writeable):
        raise ValueError(
            "in-place plane op requires a writeable C-contiguous uint32 "
            f"ndarray, got {type(plane).__name__}"
            + (f" dtype={plane.dtype}" if isinstance(plane, np.ndarray)
               else ""))
    return plane


# ---------------------------------------------------------------------------
# Entry points (native with Python fallback)
# ---------------------------------------------------------------------------

def fnv1a32(data, h0=2166136261):
    """FNV-1a 32 over bytes/ndarray, chainable via h0."""
    lib = _load()
    buf = np.frombuffer(data, dtype=np.uint8) if not isinstance(
        data, np.ndarray) else np.ascontiguousarray(data).view(np.uint8)
    if lib is not None:
        return int(lib.pilosa_fnv1a32(
            _ptr(buf, ctypes.c_uint8), buf.size, h0))
    h = h0
    for b in buf.tobytes():
        h ^= b
        h = (h * 16777619) & 0xFFFFFFFF
    return h


def popcount(words):
    """Total set bits of a uint32 ndarray."""
    lib = _load()
    words = np.ascontiguousarray(words, dtype=np.uint32)
    if lib is not None:
        return int(lib.pilosa_popcount(_ptr(words, ctypes.c_uint32),
                                       words.size))
    return int(np.sum(_popcount_per_word_py(words)))


_POP8 = np.array([bin(i).count("1") for i in range(256)], dtype=np.int64)


def _popcount_per_word_py(words):
    return _POP8[words.view(np.uint8)].reshape(-1, 4).sum(
        axis=1, dtype=np.int64)


def popcount_per_word(words):
    """Per-uint32-word popcount -> int64 ndarray."""
    lib = _load()
    words = np.ascontiguousarray(words, dtype=np.uint32)
    if lib is not None:
        out = np.empty(words.size, dtype=np.int64)
        lib.pilosa_popcount_per_word(
            _ptr(words, ctypes.c_uint32), words.size,
            _ptr(out, ctypes.c_int64))
        return out
    return _popcount_per_word_py(words)


def scatter(positions, plane):
    """OR bit positions into a uint32 plane in place; ignores out-of-range."""
    lib = _load()
    plane = _check_inplace(plane)
    if lib is not None:
        pos = np.ascontiguousarray(positions, dtype=np.uint64)
        lib.pilosa_scatter_u64(
            _ptr(pos, ctypes.c_uint64), pos.size,
            _ptr(plane, ctypes.c_uint32), plane.size)
        return plane
    pos = np.asarray(positions, dtype=np.uint64)
    pos = pos[pos < np.uint64(plane.size * 32)]
    np.bitwise_or.at(plane, (pos // 32).astype(np.int64),
                     np.uint32(1) << (pos % np.uint64(32)).astype(np.uint32))
    return plane


def extract(plane):
    """Sorted uint64 set-bit positions of a uint32 plane."""
    lib = _load()
    plane = np.ascontiguousarray(plane, dtype=np.uint32)
    if lib is not None:
        out = np.empty(popcount(plane), dtype=np.uint64)
        n = lib.pilosa_extract_u64(
            _ptr(plane, ctypes.c_uint32), plane.size,
            _ptr(out, ctypes.c_uint64))
        return out[:n]
    nz = np.nonzero(plane)[0]
    if len(nz) == 0:
        return np.empty(0, dtype=np.uint64)
    bits = np.unpackbits(plane[nz].view(np.uint8).reshape(-1, 4), axis=1,
                         bitorder="little")
    w, b = np.nonzero(bits)
    return nz[w].astype(np.uint64) * 32 + b.astype(np.uint64)


def extract_u16(plane):
    """Sorted uint16 set-bit positions of a container plane (<=2^16 bits)."""
    lib = _load()
    plane = np.ascontiguousarray(plane, dtype=np.uint32)
    if lib is not None:
        out = np.empty(popcount(plane), dtype=np.uint16)
        n = lib.pilosa_extract_u16(
            _ptr(plane, ctypes.c_uint32), plane.size,
            _ptr(out, ctypes.c_uint16))
        return out[:n]
    return extract(plane).astype(np.uint16)


def scatter_u16(values, plane):
    """OR uint16 positions into a container plane in place."""
    lib = _load()
    plane = _check_inplace(plane)
    if lib is not None:
        pos = np.ascontiguousarray(values, dtype=np.uint16)
        lib.pilosa_scatter_u16(
            _ptr(pos, ctypes.c_uint16), pos.size,
            _ptr(plane, ctypes.c_uint32), plane.size)
        return plane
    return scatter(np.asarray(values, dtype=np.uint64), plane)


def extract_runs(plane):
    """[R, 2] uint16 [start, last] inclusive runs of a container plane."""
    lib = _load()
    plane = np.ascontiguousarray(plane, dtype=np.uint32)
    if lib is not None:
        out = np.empty((plane.size * 16 + 1, 2), dtype=np.uint16)
        n = lib.pilosa_extract_runs_u16(
            _ptr(plane, ctypes.c_uint32), plane.size,
            _ptr(out, ctypes.c_uint16))
        return out[:n].copy()
    values = extract(plane).astype(np.int64)
    if len(values) == 0:
        return np.empty((0, 2), dtype=np.uint16)
    breaks = np.nonzero(np.diff(values) != 1)[0]
    starts = np.concatenate([[0], breaks + 1])
    ends = np.concatenate([breaks, [len(values) - 1]])
    return np.stack([values[starts], values[ends]], axis=1).astype(np.uint16)


def fill_range(plane, start, last):
    """Set bits [start, last] inclusive in a uint32 plane, in place."""
    lib = _load()
    plane = _check_inplace(plane)
    if lib is not None:
        lib.pilosa_fill_range(_ptr(plane, ctypes.c_uint32), plane.size,
                              int(start), int(last))
        return plane
    nbits = plane.size * 32
    start = int(start)  # numpy scalars overflow under NEP-50 shifts below
    if start >= nbits:
        return plane
    last = min(int(last), nbits - 1)
    sw, lw = start >> 5, last >> 5
    smask = np.uint32((0xFFFFFFFF << (start & 31)) & 0xFFFFFFFF)
    lmask = np.uint32(0xFFFFFFFF >> (31 - (last & 31)))
    if sw == lw:
        plane[sw] |= smask & lmask
    else:
        plane[sw] |= smask
        plane[sw + 1:lw] = np.uint32(0xFFFFFFFF)
        plane[lw] |= lmask
    return plane
