"""PQL AST (reference: pql/ast.go).

A Query is a list of Calls; a Call has a name, an args dict, and child
calls. Comparison args hold Condition values; the between conditional
(`4 < field <= 9`) folds into a BETWEEN condition with adjusted bounds.
"""

# Condition operators (reference: pql/token.go:25-31).
EQ = "=="
NEQ = "!="
LT = "<"
LTE = "<="
GT = ">"
GTE = ">="
BETWEEN = "><"

RESERVED_ARGS = {"from", "to"}  # plus any _-prefixed (reference: ast.go:281)


def is_reserved_arg(name):
    return name.startswith("_") or name in RESERVED_ARGS


class Condition:
    __slots__ = ("op", "value")

    def __init__(self, op, value):
        self.op = op
        self.value = value

    def int_values(self):
        """Bounds for BETWEEN (list) or single predicate."""
        if isinstance(self.value, list):
            return [int(v) for v in self.value]
        return [int(self.value)]

    def __eq__(self, other):
        return (isinstance(other, Condition)
                and self.op == other.op and self.value == other.value)

    def __repr__(self):
        return f"Condition({self.op!r}, {self.value!r})"


class Call:
    __slots__ = ("name", "args", "children")

    def __init__(self, name, args=None, children=None):
        self.name = name
        self.args = args or {}
        self.children = children or []

    def field_arg(self):
        """The single non-reserved arg key (reference: Call.FieldArg)."""
        for key in self.args:
            if not is_reserved_arg(key):
                return key
        raise ValueError("no field argument specified")

    def has_conditions(self):
        return any(isinstance(v, Condition) for v in self.args.values())

    def shape(self):
        """Literal-free normal form for workload fingerprinting
        (utils/workload.py): call name, arg KEYS (field names), condition
        operators, and child nesting survive; row ids, values, and time
        bounds collapse to `_`. `field=`/`_field=` values ARE field names,
        so they survive too — Rows(f) and Rows(g) are different shapes,
        Row(f=3) and Row(f=9) are the same shape."""
        out = []
        self._shape_into(out)
        return "".join(out)

    def _shape_into(self, out):
        # append-based builder: shape() runs once per served query, and
        # nested f-string joins were the single largest per-query cost
        # in the workload_overhead bench
        out.append(self.name)
        out.append("(")
        sep = ""
        for c in self.children:
            out.append(sep)
            c._shape_into(out)
            sep = ","
        for key in sorted(self.args):
            out.append(sep)
            sep = ","
            value = self.args[key]
            if key in ("field", "_field"):
                out.append(f"{key}={value}")
            elif isinstance(value, Condition):
                out.append(f"{key}{value.op}_")
            else:
                out.append(key)
                out.append("=_")
        out.append(")")

    def __eq__(self, other):
        return (isinstance(other, Call) and self.name == other.name
                and self.args == other.args and self.children == other.children)

    def __repr__(self):
        parts = [repr(c) for c in self.children]
        parts += [f"{k}={v!r}" for k, v in self.args.items()]
        return f"{self.name}({', '.join(parts)})"

    def writes(self):
        """True when the call mutates data (reference: executor write set)."""
        return self.name in {
            "Set", "Clear", "ClearRow", "Store", "SetRowAttrs",
            "SetColumnAttrs"}


class Query:
    __slots__ = ("calls",)

    def __init__(self, calls=None):
        self.calls = calls or []

    def write_calls(self):
        return [c for c in self.calls if c.writes()]

    def shape(self):
        """Normalized shape of the whole query (see Call.shape)."""
        out = []
        sep = ""
        for c in self.calls:
            out.append(sep)
            c._shape_into(out)
            sep = ";"
        return "".join(out)

    def __eq__(self, other):
        return isinstance(other, Query) and self.calls == other.calls

    def __repr__(self):
        return f"Query({self.calls!r})"
