"""PQL serialization: Call/Query AST -> parseable PQL text.

Reference: pql.Call.String() (pql/ast.go:482). Used by the cluster layer to
forward (already key-translated) calls to remote nodes; round-trips through
pilosa_tpu.pql.parse.
"""

import json

from .ast import BETWEEN, Call, Condition, Query


def value_to_pql(v):
    if v is None:
        return "null"
    if isinstance(v, bool):
        return "true" if v else "false"
    if isinstance(v, (int, float)):
        return str(v)
    if isinstance(v, str):
        return json.dumps(v)  # double-quoted, escaped
    if isinstance(v, list):
        return "[" + ", ".join(value_to_pql(x) for x in v) + "]"
    if isinstance(v, Call):
        return call_to_pql(v)
    raise TypeError(f"cannot serialize PQL value: {v!r}")


def _arg_to_pql(key, value):
    if isinstance(value, Condition):
        if value.op == BETWEEN:
            lo, hi = value.int_values()
            return f"{key} >< [{lo}, {hi}]"
        return f"{key} {value.op} {value_to_pql(value.value)}"
    return f"{key}={value_to_pql(value)}"


def _args_to_pql(call, skip=()):
    return [_arg_to_pql(k, v) for k, v in call.args.items() if k not in skip]


def call_to_pql(call):
    name = call.name
    if name in ("Set", "Clear"):
        parts = [value_to_pql(call.args["_col"])]
        parts += _args_to_pql(call, skip=("_col", "_timestamp"))
        if "_timestamp" in call.args:
            parts.append(str(call.args["_timestamp"]))  # bare timestamp form
        return f"{name}({', '.join(parts)})"
    if name == "SetRowAttrs":
        parts = [str(call.args["_field"]), value_to_pql(call.args["_row"])]
        parts += _args_to_pql(call, skip=("_field", "_row"))
        return f"{name}({', '.join(parts)})"
    if name == "SetColumnAttrs":
        parts = [value_to_pql(call.args["_col"])]
        parts += _args_to_pql(call, skip=("_col",))
        return f"{name}({', '.join(parts)})"
    if name == "Store":
        parts = [call_to_pql(call.children[0])]
        parts += _args_to_pql(call)
        return f"{name}({', '.join(parts)})"
    if name in ("TopN", "Rows"):
        parts = [str(call.args["_field"])]
        parts += [call_to_pql(c) for c in call.children]
        parts += _args_to_pql(call, skip=("_field",))
        return f"{name}({', '.join(parts)})"
    # generic: children first, then args (Row, Intersect, GroupBy, Options,
    # Count, ClearRow, ...)
    parts = [call_to_pql(c) for c in call.children]
    parts += _args_to_pql(call)
    return f"{name}({', '.join(parts)})"


def query_to_pql(query):
    if isinstance(query, Call):
        return call_to_pql(query)
    if isinstance(query, Query):
        return "".join(call_to_pql(c) for c in query.calls)
    raise TypeError(f"cannot serialize: {query!r}")
