"""PQL: the Pilosa Query Language (reference: pql/)."""

from .ast import (
    BETWEEN,
    Call,
    Condition,
    EQ,
    GT,
    GTE,
    LT,
    LTE,
    NEQ,
    Query,
    is_reserved_arg,
)
from .parser import ParseError, parse
from .writer import call_to_pql, query_to_pql
