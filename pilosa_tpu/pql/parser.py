"""Recursive-descent PQL parser.

A hand-written port of the reference grammar (pql/pql.peg) — the reference
generates a packrat parser with pointlander/peg; the grammar is small enough
that direct recursive descent is clearer and faster in Python.

Grammar summary (reference: pql/pql.peg:8-83):
  Calls  <- sp (Call sp)* !.
  Call   <- special forms (Set/SetRowAttrs/SetColumnAttrs/Clear/ClearRow/
            Store/TopN/Rows/Range) / IDENT '(' allargs ','? ')'
  allargs<- Call (',' Call)* (',' args)? / args / sp
  arg    <- field '=' value / field COND value / conditional
  conditional <- int (<|<=) field (<|<=) int      -> BETWEEN
  value  <- null/true/false/timestamp/number/nested Call/word/quoted
"""

import re

from .ast import BETWEEN, Call, Condition, EQ, GT, GTE, LT, LTE, NEQ, Query


class ParseError(Exception):
    def __init__(self, message, pos, src):
        line = src.count("\n", 0, pos) + 1
        col = pos - (src.rfind("\n", 0, pos) + 1) + 1
        super().__init__(f"parse error at line {line}, col {col}: {message}")
        self.pos = pos


_IDENT_RE = re.compile(r"[A-Za-z][A-Za-z0-9]*")
_FIELD_RE = re.compile(r"[A-Za-z][A-Za-z0-9_-]*")
_RESERVED_FIELD_RE = re.compile(r"_row|_col|_start|_end|_timestamp|_field")
_UINT_RE = re.compile(r"[1-9][0-9]*|0")
_INT_RE = re.compile(r"-?(?:[1-9][0-9]*|0)")
_NUMBER_RE = re.compile(r"-?(?:[0-9]+(?:\.[0-9]*)?|\.[0-9]+)")
_WORD_RE = re.compile(r"[A-Za-z0-9\-_:]+")
_TIMESTAMP_RE = re.compile(r"[0-9]{4}-[01][0-9]-[0-3][0-9]T[0-9]{2}:[0-9]{2}")
_COND_RE = re.compile(r"><|<=|>=|==|!=|<|>")
_COND_TOKEN = {"><": BETWEEN, "<=": LTE, ">=": GTE, "==": EQ,
               "!=": NEQ, "<": LT, ">": GT}


def parse(src):
    """Parse a PQL string into a Query (reference: pql.ParseString)."""
    return _Parser(src).parse_query()


class _Parser:
    def __init__(self, src):
        self.src = src
        self.pos = 0
        self.n = len(src)

    # -- low-level ----------------------------------------------------------

    def error(self, message):
        raise ParseError(message, self.pos, self.src)

    def sp(self):
        while self.pos < self.n and self.src[self.pos] in " \t\n\r":
            self.pos += 1

    def eof(self):
        return self.pos >= self.n

    def peek(self, s):
        return self.src.startswith(s, self.pos)

    def accept(self, s):
        if self.peek(s):
            self.pos += len(s)
            return True
        return False

    def expect(self, s, what=None):
        if not self.accept(s):
            self.error(f"expected {what or s!r}")

    def match(self, regex):
        m = regex.match(self.src, self.pos)
        if m:
            self.pos = m.end()
            return m.group(0)
        return None

    def comma(self):
        self.sp()
        ok = self.accept(",")
        self.sp()
        return ok

    def expect_comma(self):
        if not self.comma():
            self.error("expected ','")

    def open(self):
        self.expect("(")
        self.sp()

    def close(self):
        self.sp()
        self.expect(")")
        self.sp()

    # -- query/call ---------------------------------------------------------

    def parse_query(self):
        calls = []
        self.sp()
        while not self.eof():
            calls.append(self.parse_call())
            self.sp()
        return Query(calls)

    def parse_call(self):
        start = self.pos
        name = self.match(_IDENT_RE)
        if name is None:
            self.error("expected call name")
        special = getattr(self, f"_parse_{name}", None)
        if special is not None:
            return special()
        call = Call(name)
        self.open()
        self._parse_allargs(call)
        self.comma()  # trailing comma allowed
        self.close()
        return call

    # -- special forms ------------------------------------------------------

    def _parse_Set(self):
        call = Call("Set")
        self.open()
        self._parse_col(call)
        self.expect_comma()
        self._parse_args(call)
        save = self.pos
        if self.comma():
            ts = self._parse_timestampfmt()
            if ts is None:
                self.pos = save
            else:
                call.args["_timestamp"] = ts
        self.close()
        return call

    def _parse_SetRowAttrs(self):
        call = Call("SetRowAttrs")
        self.open()
        self._parse_posfield(call)
        self.expect_comma()
        self._parse_row(call)
        self.expect_comma()
        self._parse_args(call)
        self.close()
        return call

    def _parse_SetColumnAttrs(self):
        call = Call("SetColumnAttrs")
        self.open()
        self._parse_col(call)
        self.expect_comma()
        self._parse_args(call)
        self.close()
        return call

    def _parse_Clear(self):
        call = Call("Clear")
        self.open()
        self._parse_col(call)
        self.expect_comma()
        self._parse_args(call)
        self.close()
        return call

    def _parse_ClearRow(self):
        call = Call("ClearRow")
        self.open()
        self._parse_arg(call)
        self.close()
        return call

    def _parse_Store(self):
        call = Call("Store")
        self.open()
        call.children.append(self.parse_call())
        self.expect_comma()
        self._parse_arg(call)
        self.close()
        return call

    def _parse_TopN(self):
        return self._posfield_call("TopN")

    def _parse_Rows(self):
        return self._posfield_call("Rows")

    def _posfield_call(self, name):
        call = Call(name)
        self.open()
        self._parse_posfield(call)
        save = self.pos
        if self.comma():
            if self.peek(")"):
                self.pos = save
            else:
                self._parse_allargs(call)
        self.close()
        return call

    def _parse_Range(self):
        # Deprecated Range(field=value, from=ts, to=ts) form; Range(Row...)
        # and Range(field >< ...) go through the generic path.
        save = self.pos
        call = Call("Range")
        self.open()
        field = self.match(_FIELD_RE)
        self.sp()
        if field is not None and self.accept("="):
            self.sp()
            val = self._parse_value()
            call.args[field] = val
            if self.comma():
                self.accept("from=")
                call.args["from"] = self._require_timestampfmt()
                self.expect_comma()
                self.accept("to=")
                self.sp()
                call.args["to"] = self._require_timestampfmt()
                self.close()
                return call
        # fall back to generic parse
        self.pos = save
        call = Call("Range")
        self.open()
        self._parse_allargs(call)
        self.comma()
        self.close()
        return call

    # -- args ---------------------------------------------------------------

    def _parse_allargs(self, call):
        self.sp()
        if self.peek(")"):
            return
        # Call (comma Call)* (comma args)?
        if self._at_call():
            call.children.append(self.parse_call())
            while True:
                save = self.pos
                if not self.comma():
                    break
                if self._at_call():
                    call.children.append(self.parse_call())
                elif self.peek(")"):
                    self.pos = save
                    break
                else:
                    self._parse_args(call)
                    break
            return
        self._parse_args(call)

    def _at_call(self):
        """Lookahead: IDENT '(' means nested call, not an arg."""
        m = _IDENT_RE.match(self.src, self.pos)
        if not m:
            return False
        rest = self.src[m.end():m.end() + 16]
        return rest.lstrip(" \t\n").startswith("(")

    def _parse_args(self, call):
        self._parse_arg(call)
        while True:
            save = self.pos
            if not self.comma():
                break
            try:
                self._parse_arg(call)
            except ParseError:
                # PEG backtracking: `args <- arg (comma args)?` — a comma
                # followed by a non-arg (Set's trailing timestamp, trailing
                # comma before ')') belongs to the enclosing rule.
                self.pos = save
                break
        self.sp()

    def _parse_arg(self, call):
        # conditional: int condLT field condLT int
        save = self.pos
        low = self.match(_INT_RE)
        if low is not None:
            self.sp()
            op1 = self.accept("<=") and "<=" or (self.accept("<") and "<")
            if op1:
                self.sp()
                field = self.match(_FIELD_RE)
                if field is not None:
                    self.sp()
                    op2 = self.accept("<=") and "<=" or (self.accept("<") and "<")
                    if op2:
                        self.sp()
                        high = self.match(_INT_RE)
                        if high is not None:
                            lo, hi = int(low), int(high)
                            if op1 == "<":
                                lo += 1
                            if op2 == "<":
                                hi -= 1
                            self._set_arg(call, field,
                                          Condition(BETWEEN, [lo, hi]))
                            return
            self.pos = save

        field = self.match(_FIELD_RE) or self.match(_RESERVED_FIELD_RE)
        if field is None:
            self.error("expected argument name")
        self.sp()
        cond = self.match(_COND_RE)  # before '=': '==' must not half-match
        if cond is not None:
            self.sp()
            value = self._parse_value()
            self._set_arg(call, field, Condition(_COND_TOKEN[cond], value))
            return
        if self.accept("="):
            self.sp()
            self._set_arg(call, field, self._parse_value())
            return
        self.error("expected '=' or comparison operator")

    def _set_arg(self, call, field, value):
        if field in call.args:
            self.error(f"duplicate argument provided: {field}")
        call.args[field] = value

    # -- values -------------------------------------------------------------

    def _parse_value(self):
        if self.accept("["):
            self.sp()
            items = []
            if not self.peek("]"):
                while True:
                    items.append(self._parse_item())
                    if not self.comma():
                        break
            self.sp()
            self.expect("]")
            self.sp()
            return items
        return self._parse_item()

    def _boundary_follows(self):
        i = self.pos
        while i < self.n and self.src[i] in " \t\n":
            i += 1
        return i >= self.n or self.src[i] in ",)]"

    def _parse_item(self):
        for lit, value in (("null", None), ("true", True), ("false", False)):
            if self.peek(lit):
                save = self.pos
                self.pos += len(lit)
                if self._boundary_follows():
                    return value
                self.pos = save

        ts = self._parse_timestampfmt()
        if ts is not None:
            return ts

        save = self.pos
        num = self.match(_NUMBER_RE)
        if num is not None:
            # words like 123abc must not half-match as numbers
            if self._boundary_follows() or not _WORD_RE.match(self.src, self.pos):
                if "." in num:
                    return float(num)
                return int(num)
            self.pos = save

        if self._at_call():
            return self.parse_call()

        word = self.match(_WORD_RE)
        if word is not None:
            return word

        if self.accept('"'):
            return self._quoted('"')
        if self.accept("'"):
            return self._quoted("'")
        self.error("expected value")

    def _quoted(self, quote):
        out = []
        while self.pos < self.n:
            ch = self.src[self.pos]
            if ch == "\\" and self.pos + 1 < self.n:
                nxt = self.src[self.pos + 1]
                if nxt in (quote, "\\"):
                    out.append(nxt)
                    self.pos += 2
                    continue
            if ch == quote:
                self.pos += 1
                return "".join(out)
            out.append(ch)
            self.pos += 1
        self.error("unterminated string")

    def _parse_timestampfmt(self):
        for quote in ('"', "'", ""):
            save = self.pos
            if quote and not self.accept(quote):
                continue
            ts = self.match(_TIMESTAMP_RE)
            if ts is not None:
                if quote:
                    if self.accept(quote):
                        return ts
                elif self._boundary_follows():
                    return ts
            self.pos = save
        return None

    def _require_timestampfmt(self):
        ts = self._parse_timestampfmt()
        if ts is None:
            self.error("expected timestamp (YYYY-MM-DDTHH:MM)")
        return ts

    # -- positional fields --------------------------------------------------

    def _parse_posfield(self, call):
        name = self.match(_FIELD_RE)
        if name is None:
            self.error("expected field name")
        call.args["_field"] = name
        self.sp()

    def _parse_col(self, call):
        self._parse_pos(call, "_col")

    def _parse_row(self, call):
        self._parse_pos(call, "_row")

    def _parse_pos(self, call, key):
        num = self.match(_UINT_RE)
        if num is not None:
            call.args[key] = int(num)
            self.sp()
            return
        if self.accept("'"):
            call.args[key] = self._quoted("'")
        elif self.accept('"'):
            call.args[key] = self._quoted('"')
        else:
            self.error(f"expected column/row id or quoted key")
        self.sp()
