"""Pod-scale SPMD data plane: cross-node query merge over collectives.

The reference merges cross-node partial results over HTTP/protobuf
(executor.remoteExec executor.go:2414, http/client.go:268) — the
coordinator POSTs per-node shard lists and sums JSON/proto responses. In
SPMD mode that data plane is replaced by the accelerator fabric: every
server process joins ONE global JAX distributed system
(`jax.distributed.initialize` — gloo across CPU hosts, ICI/DCN collectives
on TPU pods), each query leaf materializes as a single globally-sharded
[shards, words] array whose per-process blocks come from that node's own
fragments, and one jit-compiled count program runs on every process in
lockstep — XLA inserts the cross-process all-reduce, so counts merge as a
psum riding the fabric instead of JSON over REST.

HTTP remains the CONTROL plane (SURVEY §2 "distributed communication
backend": control over DCN, data merge over ICI): the cluster coordinator
announces each step via POST /internal/spmd/step, every process (including
the coordinator) executes the identical program, and the replicated scalar
result is read locally — no result bytes cross HTTP.

Execution model (multi-controller SPMD):
- Only the cluster coordinator node initiates steps, and it serializes
  them under a local lock; peer processes execute steps from their HTTP
  handler thread under the same per-process lock. With a single initiator
  this yields an identical step order on every process — the requirement
  for collectives to rendezvous correctly.
- Queries arriving at non-coordinator nodes (and calls the stacked
  signature can't express) use the HTTP merge path unchanged; SPMD is a
  fast path, never a correctness dependency.
- Steps are gated on every node being READY: a process that never joins a
  collective would hang the others, so degraded clusters fall back to the
  HTTP path (which has per-replica retry).

Count totals use the framework-wide (hi, lo) int32 split reduce
(ops.bitplane.hi_lo) — exact past 2^31 bits without x64.
"""

import threading

import numpy as np

from ..pql import call_to_pql, parse
from ..shardwidth import WORDS_PER_ROW


class SpmdError(Exception):
    pass


class SpmdDataPlane:
    #: process-wide init guard (jax.distributed.initialize is once-only)
    _initialized = False

    @classmethod
    def initialize(cls, coordinator_address, num_processes, process_id):
        """Join the global JAX distributed system. MUST run before any JAX
        backend initializes in this process (same constraint as platform
        selection; see cli._honor_jax_platforms_env)."""
        if cls._initialized:
            return
        import jax

        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id)
        cls._initialized = True

    #: seconds a step announcement may block (first-query jit compile +
    #: collective rendezvous on a cold pod can far exceed the default 30s)
    STEP_TIMEOUT = 300
    #: seconds for the cheap pre-flight validation round
    VALIDATE_TIMEOUT = 5

    def __init__(self, holder, cluster, client_factory):
        self.holder = holder
        self.cluster = cluster
        self.client_factory = client_factory
        self._lock = threading.Lock()  # one step at a time per process
        self._mesh = None
        self._fns = {}
        self._step_id = 0
        self.steps_run = 0  # observability: /internal/spmd/stats
        # The JAX process set is fixed at startup (initialize is
        # once-only); if the cluster later grows or shrinks, SPMD must
        # decline — new nodes are not mesh participants.
        self._boot_node_ids = tuple(sorted(n.id for n in cluster.nodes)) \
            if cluster is not None else ()

    # -- mesh ----------------------------------------------------------------

    def _global_sharding(self, shard_axis=0, ndim=2):
        """NamedSharding over the GLOBAL device list, process-major, so
        each process's addressable block is contiguous along the shard
        axis (what make_array_from_process_local_data fills)."""
        if self._mesh is None:
            import jax

            devices = sorted(jax.devices(),
                             key=lambda d: (d.process_index, d.id))
            self._mesh = jax.sharding.Mesh(np.array(devices), ("shards",))
        import jax

        spec = [None] * ndim
        spec[shard_axis] = "shards"
        return jax.sharding.NamedSharding(
            self._mesh, jax.sharding.PartitionSpec(*spec))

    def _local_device_count(self):
        import jax

        return len(jax.local_devices())

    def _num_processes(self):
        import jax

        return jax.process_count()

    # -- signature helper ----------------------------------------------------

    def _signature(self, idx, call):
        """Tree signature for SPMD coverage. Same shape rules as the
        stacked evaluator (shared walk: exec.stacked.tree_signature) but
        leaf checks consult only REPLICATED state (the schema): every
        process must derive the IDENTICAL signature or the collective
        desyncs, and local view/fragment existence differs per node (a node
        that owns no shards of a field simply contributes zero planes)."""
        from ..exec.stacked import tree_signature

        def leaf(idx, field_name, row_id, leaves):
            if idx.field(field_name) is None:
                return None
            key = (field_name, int(row_id))
            if key not in leaves:
                leaves[key] = len(leaves)
            return ("leaf", leaves[key])

        leaves = {}
        sig = tree_signature(idx, call, leaves, leaf)
        if sig is None or not leaves:
            return None
        ordered = sorted(leaves.items(), key=lambda kv: kv[1])
        return sig, [key for key, _ in ordered]

    # -- coordinator entry ---------------------------------------------------

    def _gate(self, idx, shards):
        """Common SPMD eligibility gates; returns a step skeleton (shard
        segments + padding) or None to fall back to the HTTP merge."""
        cluster = self.cluster
        if cluster is None or len(cluster.nodes) < 2:
            return None
        coord = cluster.coordinator
        if coord is None or coord.id != cluster.local_id:
            return None  # single initiator keeps step order global
        from .node import NODE_STATE_READY

        if any(n.state != NODE_STATE_READY for n in cluster.nodes):
            return None  # a hung participant would stall the collective
        if tuple(sorted(n.id for n in cluster.nodes)) != self._boot_node_ids:
            return None  # membership changed since jax.distributed init

        by_node = cluster.shards_by_node(idx.name, list(shards))
        segments = {node.id: sorted(s) for node, s in by_node.items()}
        # every process contributes an equal-shaped block (zero planes for
        # nodes with fewer/no shards), padded to its device multiple
        dev_pp = self._local_device_count()
        longest = max((len(s) for s in segments.values()), default=0)
        seg_len = max(dev_pp, ((longest + dev_pp - 1) // dev_pp) * dev_pp)
        return {
            "index": idx.name,
            "segments": segments,
            "seg_len": seg_len,
            "dev_pp": dev_pp,
            "nodes": list(self._boot_node_ids),
        }

    def _execute_step(self, step):
        """Announce + run one validated step (coordinator side)."""
        with self._lock:
            self._step_id += 1
            step["step"] = self._step_id
            errors = []

            def post(node):
                try:
                    client = self.client_factory(node.uri)
                    client.timeout = self.STEP_TIMEOUT
                    client.spmd_step(step)
                except Exception as e:  # surfaced after the collective
                    errors.append((node.id, e))

            threads = [threading.Thread(target=post, args=(n,))
                       for n in self.cluster.peers()]
            for t in threads:
                t.start()
            # join the collective ourselves — peers are inside run_step now
            result = self._run_step_locked(step)
            for t in threads:
                t.join()
        if errors:
            # We hold a replicated result, so every process DID join the
            # collective; these are post-collective transport errors (lost
            # responses). Log, don't fail the query.
            import sys

            print(f"spmd: post-collective peer errors (result kept): "
                  f"{errors}", file=sys.stderr)
        return result

    def try_count(self, idx, call, shards):
        """Count(call) merged over the global mesh, or None to fall back
        to the HTTP merge path."""
        if self._signature(idx, call) is None:
            return None
        step = self._gate(idx, shards)
        if step is None:
            return None
        step["kind"] = "count"
        step["pql"] = call_to_pql(call)
        # Pre-flight: every peer must confirm it can execute this step
        # (spmd enabled, schema in sync, matching device count) with a
        # short deadline, BEFORE anyone enters the collective — a peer
        # that never joins would stall the whole mesh with no way out.
        if self._validate_on_peers(step) is None:
            return None
        return self._execute_step(step)

    def try_sum(self, idx, call, shards):
        """Sum(filter?, field=f) merged over the global mesh: the BSI
        bit planes form [depth, shards, words] globally-sharded arrays and
        the per-plane popcounts all-reduce over the fabric. Returns the
        final (value, count) with the field base applied (field.go:1583),
        or None to fall back."""
        field_name = call.args.get("field") or call.args.get("_field")             or call.field_arg()
        field = idx.field(field_name) if field_name else None
        if field is None or field.options.type != "int":
            return None
        filter_call = call.children[0] if call.children else None
        if filter_call is not None                 and self._signature(idx, filter_call) is None:
            return None
        step = self._gate(idx, shards)
        if step is None:
            return None
        step["kind"] = "sum"
        step["field"] = field.name
        step["pql"] = call_to_pql(filter_call) if filter_call else ""
        resps = self._validate_on_peers(step)
        if resps is None:
            return None
        # depth can differ per node (it grows with out-of-range writes);
        # the step uses the cluster-wide max, peers zero-extend
        step["depth"] = max(
            [field.options.bit_depth]
            + [int(r.get("bit_depth", 0)) for r in resps])
        result = self._execute_step(step)
        total, count = result
        return total + field.options.base * count, count

    #: candidate-row cap for SPMD TopN: [rows, shards, words] blocks must
    #: stay bounded per process; larger candidate sets fall back to HTTP
    TOPN_MAX_ROWS = 4096

    def try_topn(self, idx, call, shards):
        """TopN merged over the global mesh: candidate rows are unioned
        across nodes in the validation round, then one [rows, shards,
        words] globally-sharded stack counts every candidate with the
        cross-process all-reduce. Returns the final trimmed pair list
        (reference merge: Pairs.Add cache.go:356 + executor.go:925), or
        None to fall back (attr filters / tanimoto / oversized candidate
        sets use the HTTP path)."""
        field_name = call.args.get("_field") or call.field_arg()
        field = idx.field(field_name) if field_name else None
        if field is None or field.options.type == "int":
            return None
        # tanimoto needs per-row plain counts + src count; attr filters
        # need the attr store — both stay on the HTTP/local path
        if call.args.get("tanimotoThreshold") or                 call.args.get("attrName") is not None:
            return None
        if len(call.children) > 1:
            return None
        filter_call = call.children[0] if call.children else None
        if filter_call is not None                 and self._signature(idx, filter_call) is None:
            return None
        step = self._gate(idx, shards)
        if step is None:
            return None
        step["kind"] = "topn"
        step["field"] = field.name
        step["pql"] = call_to_pql(filter_call) if filter_call else ""
        resps = self._validate_on_peers(step)
        if resps is None:
            return None
        # global candidate set = union of every node's cache/row ids
        rows = set(self._topn_candidates(idx, field.name))
        for r in resps:
            rows.update(int(x) for x in r.get("rows", []))
        rows = sorted(rows)
        if not rows:
            return []
        if len(rows) > self.TOPN_MAX_ROWS:
            return None
        step["rows"] = rows
        counts = self._execute_step(step)

        from ..exec.result import Pair

        threshold = max(int(call.args.get("threshold") or 1), 1)
        pairs = [Pair(r, c) for r, c in zip(rows, counts)
                 if c >= threshold]
        pairs.sort(key=lambda p: (-p.count, p.id))
        n = call.args.get("n")
        if n is not None:
            pairs = pairs[:int(n)]
        return pairs

    def _topn_candidates(self, idx, field_name):
        """This node's TopN candidate rows (shared policy:
        exec.executor.fragment_topn_candidates)."""
        from ..core.view import VIEW_STANDARD
        from ..exec.executor import fragment_topn_candidates

        field = idx.field(field_name)
        view = field.view(VIEW_STANDARD) if field is not None else None
        if view is None:
            return []
        rows = set()
        for frag in list(view.fragments.values()):
            rows.update(fragment_topn_candidates(frag))
        return sorted(rows)

    def _validate_on_peers(self, step):
        """Pre-flight every peer; returns the list of OK responses, or
        None when any peer declined/was unreachable."""
        resps = []

        def probe(node):
            try:
                client = self.client_factory(node.uri)
                client.timeout = self.VALIDATE_TIMEOUT
                resps.append(client.spmd_validate(step))
            except Exception:
                resps.append({"ok": False})

        threads = [threading.Thread(target=probe, args=(n,))
                   for n in self.cluster.peers()]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if len(resps) != len(self.cluster.peers())                 or not all(r.get("ok") for r in resps):
            return None
        return resps

    def validate(self, step):
        """Peer-side pre-flight check (POST /internal/spmd/validate).
        For kind="sum" the response carries this node's bit_depth — depth
        can grow locally past the declared range (field.set_value), so the
        coordinator takes the max over all nodes for the step."""
        idx = self.holder.index(step["index"])
        if idx is None:
            return {"ok": False, "reason": "index not found"}
        if int(step["dev_pp"]) != self._local_device_count():
            return {"ok": False, "reason": "device count mismatch"}
        if tuple(step.get("nodes", ())) != self._boot_node_ids:
            return {"ok": False, "reason": "membership mismatch"}
        out = {"ok": True}
        kind = step.get("kind", "count")
        if kind == "sum":
            field = idx.field(step["field"])
            if field is None or field.options.type != "int":
                return {"ok": False, "reason": "not an int field"}
            out["bit_depth"] = field.options.bit_depth
            if step["pql"] and self._signature(
                    idx, parse(step["pql"]).calls[0]) is None:
                return {"ok": False, "reason": "filter not coverable"}
        elif kind == "topn":
            field = idx.field(step["field"])
            if field is None or field.options.type == "int":
                return {"ok": False, "reason": "not a set field"}
            if step["pql"] and self._signature(
                    idx, parse(step["pql"]).calls[0]) is None:
                return {"ok": False, "reason": "filter not coverable"}
            # contribute this node's candidate rows to the global union
            out["rows"] = self._topn_candidates(idx, step["field"])
        else:
            if self._signature(idx, parse(step["pql"]).calls[0]) is None:
                return {"ok": False, "reason": "tree not coverable"}
        return out

    # -- step execution (every process) --------------------------------------

    def run_step(self, step):
        """HTTP-handler entry for peer processes."""
        with self._lock:
            return self._run_step_locked(step)

    def _run_step_locked(self, step):
        idx = self.holder.index(step["index"])
        if idx is None:
            raise SpmdError(f"index not found: {step['index']}")
        kind = step.get("kind", "count")
        if kind == "count":
            return self._run_count_step(idx, step)
        if kind == "sum":
            return self._run_sum_step(idx, step)
        if kind == "topn":
            return self._run_topn_step(idx, step)
        raise SpmdError(f"unknown spmd step kind: {kind}")

    def _local_block(self, idx, step, field_name, row_id,
                     view_name=None):
        """This process's [seg_len, W] block of one row over its owned
        shards (zero planes for shards/fragments it doesn't hold)."""
        from ..core.view import VIEW_STANDARD

        seg_len = int(step["seg_len"])
        my_shards = step["segments"].get(self.cluster.local_id, [])
        if len(my_shards) > seg_len:
            raise SpmdError("segment exceeds seg_len")
        local = np.zeros((seg_len, WORDS_PER_ROW), dtype=np.uint32)
        field = idx.field(field_name)
        view = field.view(view_name or VIEW_STANDARD)             if field is not None else None
        if view is not None:
            for j, shard in enumerate(my_shards):
                frag = view.fragment(shard)
                if frag is not None:
                    plane = frag.row_plane(row_id)
                    if plane is not None:
                        local[j] = np.asarray(plane)
        return local

    def _run_count_step(self, idx, step):
        import jax

        call = parse(step["pql"]).calls[0]
        sig_leaves = self._signature(idx, call)
        if sig_leaves is None:
            raise SpmdError(
                f"step tree not coverable on this node: {step['pql']}")
        sig, leaf_keys = sig_leaves

        n_proc = self._num_processes()
        seg_len = int(step["seg_len"])
        sharding = self._global_sharding()
        global_shape = (n_proc * seg_len, WORDS_PER_ROW)

        arrays = []
        for field_name, row_id in leaf_keys:
            local = self._local_block(idx, step, field_name, row_id)
            arrays.append(jax.make_array_from_process_local_data(
                sharding, local, global_shape=global_shape))

        fn = self._count_fn(sig, len(arrays))
        hi, lo = fn(*arrays)
        self.steps_run += 1
        from ..ops.bitplane import combine_hi_lo

        return combine_hi_lo(hi, lo)

    def _run_sum_step(self, idx, step):
        """BSI Sum over globally-sharded bit planes (reference per-shard
        algorithm: fragment.sum fragment.go:1068; the cross-node merge is
        the all-reduce XLA inserts over the [*, shards, words] arrays)."""
        import jax

        from ..core.fragment import (
            BSI_EXISTS_BIT,
            BSI_OFFSET_BIT,
            BSI_SIGN_BIT,
        )
        from ..ops.bitplane import combine_hi_lo

        field = idx.field(step["field"])
        if field is None:
            raise SpmdError(f"field not found: {step['field']}")
        depth = int(step["depth"])
        # A write racing this step can grow the local bit_depth past the
        # validated step depth. We still MUST enter the collective (a
        # missing participant stalls every process), so the racing
        # value's planes above step depth are simply not read this query
        # — an ordinary read/write race outcome, not corruption.
        bsi_view = field.bsi_view_name()

        n_proc = self._num_processes()
        seg_len = int(step["seg_len"])
        plane_sh = self._global_sharding(shard_axis=1, ndim=3)
        row_sh = self._global_sharding()
        row_shape = (n_proc * seg_len, WORDS_PER_ROW)

        # zero-extension to the cluster-wide max depth is exact: absent
        # magnitude planes contribute 0 to every popcount
        local_planes = np.stack([
            self._local_block(idx, step, step["field"],
                              BSI_OFFSET_BIT + i, view_name=bsi_view)
            for i in range(depth)])
        planes = jax.make_array_from_process_local_data(
            plane_sh, local_planes,
            global_shape=(depth,) + row_shape)
        sign = jax.make_array_from_process_local_data(
            row_sh, self._local_block(idx, step, step["field"],
                                      BSI_SIGN_BIT, view_name=bsi_view),
            global_shape=row_shape)
        exists = jax.make_array_from_process_local_data(
            row_sh, self._local_block(idx, step, step["field"],
                                      BSI_EXISTS_BIT, view_name=bsi_view),
            global_shape=row_shape)

        sig = None
        stacks = []
        if step["pql"]:
            sig_leaves = self._signature(idx, parse(step["pql"]).calls[0])
            if sig_leaves is None:
                raise SpmdError("filter not coverable on this node")
            sig, leaf_keys = sig_leaves
            for field_name, row_id in leaf_keys:
                stacks.append(jax.make_array_from_process_local_data(
                    row_sh,
                    self._local_block(idx, step, field_name, row_id),
                    global_shape=row_shape))

        fn = self._sum_fn(sig, len(stacks))
        res = [np.asarray(r) for r in fn(planes, sign, exists, *stacks)]
        p_hi, p_lo, n_hi, n_lo, c_hi, c_lo = res
        total = 0
        for i in range(depth):
            total += combine_hi_lo(p_hi[i], p_lo[i]) << i
            total -= combine_hi_lo(n_hi[i], n_lo[i]) << i
        self.steps_run += 1
        return total, combine_hi_lo(c_hi, c_lo)

    def _run_topn_step(self, idx, step):
        """Candidate-row counts over a globally-sharded [rows, shards,
        words] stack (reference per-shard scan: fragment.top
        fragment.go:1570; the heap merge becomes the all-reduce)."""
        import jax

        from ..ops.bitplane import combine_hi_lo

        rows = [int(r) for r in step["rows"]]
        n_proc = self._num_processes()
        seg_len = int(step["seg_len"])
        rows_sh = self._global_sharding(shard_axis=1, ndim=3)
        leaf_sh = self._global_sharding()
        row_shape = (n_proc * seg_len, WORDS_PER_ROW)

        local = np.stack([
            self._local_block(idx, step, step["field"], r) for r in rows])
        stack = jax.make_array_from_process_local_data(
            rows_sh, local, global_shape=(len(rows),) + row_shape)

        sig = None
        stacks = []
        if step["pql"]:
            sig_leaves = self._signature(idx, parse(step["pql"]).calls[0])
            if sig_leaves is None:
                raise SpmdError("filter not coverable on this node")
            sig, leaf_keys = sig_leaves
            for field_name, row_id in leaf_keys:
                stacks.append(jax.make_array_from_process_local_data(
                    leaf_sh,
                    self._local_block(idx, step, field_name, row_id),
                    global_shape=row_shape))

        fn = self._topn_fn(sig, len(stacks))
        hi, lo = fn(stack, *stacks)
        self.steps_run += 1
        totals = combine_hi_lo(hi, lo)
        return [int(t) for t in totals]

    def _topn_fn(self, sig, arity):
        """(rows [R,S,W], *filter leaves) -> per-row (hi [R], lo [R])
        counts of row ∩ filter, all-reduced across processes."""
        import jax
        import jax.numpy as jnp

        from ..exec.stacked import StackedEvaluator
        from ..ops.bitplane import hi_lo

        key = ("topn", sig, arity)
        fn = self._fns.get(key)
        if fn is None:
            @jax.jit
            def fn(stack, *stacks):
                x = stack
                if sig is not None:
                    filt = StackedEvaluator._tree_eval(sig, stacks)
                    x = x & filt[None]
                per_shard = jnp.sum(
                    jax.lax.population_count(x).astype(jnp.int32),
                    axis=-1)
                return hi_lo(per_shard, axis=-1)

            self._fns[key] = fn
        return fn

    def _sum_fn(self, sig, arity):
        """(planes [D,S,W], sign, exists, *filter leaves) -> per-plane
        pos/neg popcounts + consider count as (hi, lo) int32 pairs, with
        XLA inserting the cross-process reduce."""
        import jax
        import jax.numpy as jnp

        from ..exec.stacked import StackedEvaluator
        from ..ops.bitplane import hi_lo

        key = ("sum", sig, arity)
        fn = self._fns.get(key)
        if fn is None:
            @jax.jit
            def fn(planes, sign, exists, *stacks):
                consider = exists
                if sig is not None:
                    consider = consider & StackedEvaluator._tree_eval(
                        sig, stacks)
                pos = consider & ~sign
                neg = consider & sign
                pc = jnp.sum(jax.lax.population_count(
                    planes & pos[None]).astype(jnp.int32), axis=-1)
                nc = jnp.sum(jax.lax.population_count(
                    planes & neg[None]).astype(jnp.int32), axis=-1)
                cc = jnp.sum(jax.lax.population_count(
                    consider).astype(jnp.int32), axis=-1)
                return (*hi_lo(pc, axis=-1), *hi_lo(nc, axis=-1),
                        *hi_lo(cc))

            self._fns[key] = fn
        return fn

    def _count_fn(self, sig, arity):
        import jax
        import jax.numpy as jnp

        from ..exec.stacked import StackedEvaluator
        from ..ops.bitplane import hi_lo

        fn = self._fns.get((sig, arity))
        if fn is None:
            @jax.jit
            def fn(*stacks):
                acc = StackedEvaluator._tree_eval(sig, stacks)
                per_shard = jnp.sum(
                    jax.lax.population_count(acc).astype(jnp.int32),
                    axis=-1)
                return hi_lo(per_shard)

            self._fns[(sig, arity)] = fn
        return fn

    def stats(self):
        return {"steps": self.steps_run,
                "initialized": type(self)._initialized}
