"""Pod-scale SPMD data plane: cross-node query merge over collectives.

The reference merges cross-node partial results over HTTP/protobuf
(executor.remoteExec executor.go:2414, http/client.go:268) — the
coordinator POSTs per-node shard lists and sums JSON/proto responses. In
SPMD mode that data plane is replaced by the accelerator fabric: every
server process joins ONE global JAX distributed system
(`jax.distributed.initialize` — gloo across CPU hosts, ICI/DCN collectives
on TPU pods), each query leaf materializes as a single globally-sharded
[shards, words] array whose per-process blocks come from that node's own
fragments, and one jit-compiled program runs on every process in lockstep —
XLA inserts the cross-process all-reduce, so merges ride the fabric instead
of JSON over REST. Covered merges: Count, Sum, Min/Max, TopN, GroupBy —
every cross-node aggregate the reference reduces (executor.go:925-1237).

HTTP remains the CONTROL plane (SURVEY §2 "distributed communication
backend": control over DCN, data merge over ICI): the cluster coordinator
announces each step via POST /internal/spmd/step, every process (including
the coordinator) executes the identical program, and the replicated scalar
result is read locally — no result bytes cross HTTP.

Execution model (multi-controller SPMD):
- Only the cluster coordinator node initiates steps, and it serializes
  them under a local lock; peer processes execute steps from their HTTP
  handler thread under the same per-process lock. With a single initiator
  this yields an identical step order on every process — the requirement
  for collectives to rendezvous correctly.
- Queries arriving at NON-coordinator nodes forward eligible calls to the
  coordinator in one internal hop (POST /internal/spmd/initiate) so every
  node serves the collective path — matching the reference, where any node
  coordinates the merge (executor.Execute executor.go:113) — while step
  initiation stays single-sourced.
- Steps carry a FULLY-RESOLVED plan (operator signature + leaf list,
  candidate rows, bit depth): peers never re-derive signatures from their
  own possibly-racing schema. Combined with defensive block gathering
  (anything missing locally contributes zero planes — count-neutral for
  every covered op), a peer that validated CANNOT fail to enter the
  collective, which closes the validate-to-collective wedge window (a peer
  raising before the jitted program runs would block the coordinator
  inside the step with the lock held).
- Steps are gated on every node being READY: a process that never joins a
  collective would hang the others, so degraded clusters fall back to the
  HTTP path (which has per-replica retry).

Count totals use the framework-wide (hi, lo) int32 split reduce
(ops.bitplane.hi_lo) — exact past 2^31 bits without x64.

Mesh observatory (PR 19): every process runs a per-step phase clock
(_StepClock, mirroring the PR-6 dispatch _PhaseClock contract:
residual-folded so per-phase seconds sum EXACTLY to the step wall) and
records each step into a bounded ring. The coordinator assembles the
rings into one skew-corrected cross-node timeline
(GET /debug/spmd/steps) with per-phase straggler attribution — the
evidence layer the spmd_never_entered / spmd_collective_hung wedge
classes were missing.
"""

import itertools
import statistics
import threading
import time
from collections import OrderedDict, deque

import numpy as np

from ..core.view import VIEW_BSI_GROUP_PREFIX, VIEW_STANDARD
from ..pql import Call, call_to_pql, parse
from ..shardwidth import WORDS_PER_ROW
from ..utils.logger import NopLogger


class SpmdError(Exception):
    pass


# -- plan wire encoding -------------------------------------------------------

def sig_to_wire(sig):
    """Operator signature -> JSON-able nested lists (steps carry the plan
    so every process evaluates the IDENTICAL program; see module doc)."""
    if sig is None:
        return None
    if sig[0] == "leaf":
        return ["leaf", sig[1]]
    op, subs = sig
    return [op, [sig_to_wire(s) for s in subs]]


def sig_from_wire(wire):
    if wire is None:
        return None
    if wire[0] == "leaf":
        return ("leaf", int(wire[1]))
    return (wire[0], tuple(sig_from_wire(s) for s in wire[1]))


# -- mesh observatory ---------------------------------------------------------

#: step-phase taxonomy (GET /debug/spmd/steps; docs/architecture.md):
#: announce_recv — announcement receipt to collective entry (stream-queue
#: wait + step-lock wait on peers; fan-out time on the coordinator);
#: stack_gather — host fragment gather + make_array_from_process_local_data
#: for every leaf/BSI/row stack; device_enter — the jitted collective
#: program call returning its (possibly async) output handles; psum —
#: block_until_ready on those handles, i.e. the collective rendezvous +
#: execution (a straggling peer shows up HERE on everyone else); result_
#: fetch — device-to-host conversion of the replicated outputs; exit —
#: residual-folded terminal phase (decode + lifecycle bookkeeping), which
#: absorbs the fold so the phases sum EXACTLY to the step wall.
STEP_PHASES = ("announce_recv", "stack_gather", "device_enter", "psum",
               "result_fetch", "exit")


class _StepClock:
    """Phase marks within one collective step — the PR-6 _PhaseClock
    contract (exec/stacked.py) lifted to the step plane: `mark(phase)`
    attributes the time since the previous mark (or the announcement
    receipt) to `phase`; `close()` folds any residual into the terminal
    phase so the per-phase seconds sum EXACTLY to the step wall (the
    bench meshobs leg asserts the 5% version of this cross-process)."""

    __slots__ = ("t0", "_t", "phases")

    def __init__(self, t0=None):
        now = time.perf_counter()
        self.t0 = self._t = now if t0 is None else t0
        self.phases = []

    def mark(self, phase):
        now = time.perf_counter()
        self.phases.append([phase, now - self._t])
        self._t = now

    def close(self, phase="exit"):
        """Fold the residual into `phase` and return the step wall."""
        self.mark(phase)
        return self._t - self.t0


def envelope_skew(t_send, t_recv, remote_now):
    """NTP-style clock-offset estimate (remote - local, seconds) from one
    RPC envelope: the peer stamped `remote_now` (its wall clock) while
    handling a request we sent at local wall time `t_send` and answered
    at `t_recv`. Assuming symmetric network delay (the same assumption
    as tracing.estimate_skew, which derives theta from span pairs), the
    remote stamp corresponds to the local midpoint of the envelope."""
    return remote_now - (t_send + t_recv) / 2.0


def attribute_stragglers(peers_phases, factor, noise_floor):
    """Per-phase straggler attribution for ONE step's merged per-peer
    phase walls. `peers_phases`: {node_id: {phase: seconds}}. A node is
    the phase's straggler when its wall is the slowest AND exceeds the
    median of the OTHER peers by `factor` (excluding the candidate —
    on a 2-node mesh a median over both would dilute the straggler's
    own wall into the baseline) AND by more than `noise_floor` seconds
    in absolute terms (so microsecond jitter between healthy peers
    never flags). Returns [{phase, node, seconds, median_seconds,
    ratio}]."""
    flags = []
    phases = set()
    for ph in peers_phases.values():
        phases.update(ph)
    for phase in sorted(phases):
        walls = {node: ph[phase] for node, ph in peers_phases.items()
                 if phase in ph}
        if len(walls) < 2:
            continue
        worst_node = max(walls, key=walls.get)
        worst = walls[worst_node]
        med = statistics.median(v for n, v in walls.items()
                                if n != worst_node)
        if worst > med * factor and worst - med > noise_floor:
            flags.append({
                "phase": phase,
                "node": worst_node,
                "seconds": round(worst, 6),
                "median_seconds": round(med, 6),
                "ratio": round(worst / med, 2) if med > 0 else None,
            })
    return flags


#: the serving process's data plane (set by cli.cmd_server) — what the
#: incident-autopsy `spmd` collector snapshots into EVERY postmortem
#: bundle without holding an instance handle (utils/incident.py)
_active_plane = None


def set_active_plane(plane):
    global _active_plane
    _active_plane = plane
    return plane


def active_plane():
    return _active_plane


def observatory_snapshot():
    """Incident-bundle collector payload: the active plane's full
    observatory state (step ring + phase tables + a best-effort
    cross-node timeline), or the disabled stub."""
    plane = _active_plane
    if plane is None:
        return {"enabled": False}
    try:
        return dict(plane.incident_snapshot(), enabled=True)
    except Exception as e:  # noqa: BLE001 — never fail the bundle
        return {"enabled": True, "error": str(e)}


class SpmdDataPlane:
    #: process-wide init guard (jax.distributed.initialize is once-only)
    _initialized = False

    @classmethod
    def initialize(cls, coordinator_address, num_processes, process_id,
                   cpu_collectives=None):
        """Join the global JAX distributed system. MUST run before any JAX
        backend initializes in this process (same constraint as platform
        selection; see cli._honor_jax_platforms_env).

        cpu_collectives="gloo" opts the CPU backend into real
        cross-process collectives (the 2-process CPU harness and any
        gloo-capable CPU cluster); without it multi-process CPU programs
        raise "Multiprocess computations aren't implemented on the CPU
        backend". Must be set before the backend initializes, same as the
        distributed init itself."""
        if cls._initialized:
            return
        import jax

        if cpu_collectives == "gloo":
            jax.config.update("jax_cpu_collectives_implementation", "gloo")
        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id)
        cls._initialized = True

    #: seconds a step announcement may block (first-query jit compile +
    #: collective rendezvous on a cold pod can far exceed the default 30s)
    STEP_TIMEOUT = 300
    #: seconds for the cheap pre-flight validation round
    VALIDATE_TIMEOUT = 5
    #: compiled-program cache bound (mirrors exec.stacked.MAX_FNS: tiny
    #: functions, but unbounded distinct shapes would accumulate)
    MAX_FNS = 128
    #: serve-mode values settable at runtime (POST /debug/spmd). "http"
    #: is runtime-only: it forces maybe_execute to decline so the SAME
    #: cluster can run the HTTP fan-out path for an A/B bench comparison.
    SERVE_MODES = ("off", "on", "shadow", "http")
    #: seconds a peer's stream runner waits on a sequence gap before
    #: resyncing to the lowest queued step (a lost announcement must not
    #: wedge the stream forever; the coordinator's collective for the
    #: lost step fails via the distributed-runtime timeout and falls back)
    STREAM_GAP_TIMEOUT = 30
    #: bounded per-node step ring (mesh observatory): most recent steps
    #: with per-phase walls, what GET /debug/spmd/steps merges cross-node
    STEP_RING_SIZE = 256
    #: a node is a phase's straggler when its wall exceeds the peer
    #: median by this factor AND by STRAGGLER_NOISE_FLOOR seconds in
    #: absolute terms (2x of a 50us gather is jitter, not a straggler)
    STRAGGLER_FACTOR = 2.0
    STRAGGLER_NOISE_FLOOR = 0.025
    #: edge-trigger memory: (seq, node, phase) keys already counted /
    #: flightrec'd, so repeated GET /debug/spmd/steps scrapes of the same
    #: ring don't re-fire events (bounded FIFO)
    STRAGGLER_FLAGS_MAX = 1024

    def __init__(self, holder, cluster, client_factory, logger=None,
                 serve_mode="off", stream_gap_timeout=None):
        self.holder = holder
        self.cluster = cluster
        self.client_factory = client_factory
        self.logger = logger or NopLogger()
        self._lock = threading.Lock()  # one step at a time per process
        self._mesh = None
        self._fns = OrderedDict()
        self._step_id = 0
        # --spmd-serve: "off" keeps the pre-mesh data plane byte-identical
        # (no cache, blocking step announcements); "on" enables the
        # mesh-resident cache + step-stream + batched/fused steps;
        # "shadow" serves legacy while probing the cache for divergence.
        self.serve_mode = serve_mode if serve_mode in self.SERVE_MODES \
            else "off"
        from .meshstacks import MeshStackCache

        self.mesh_cache = MeshStackCache(logger=self.logger)
        # step-stream control plane (serve_mode == "on"): peers execute
        # announced steps in sequence order from a runner thread instead
        # of the announcing HTTP handler thread, so the coordinator can
        # pipeline announcement N+1 while step N executes.
        self._stream_cond = threading.Condition()
        self._stream_queue = {}  # seq -> step
        self._stream_next = None  # next seq to execute (set by first recv)
        self._stream_thread = None
        self._stream_closed = False
        # outbound stream sequence: SEPARATE from _step_id so legacy-mode
        # steps (serve off/shadow) never open gaps in the stream — a gap
        # costs the peer a STREAM_GAP_TIMEOUT resync stall
        self._stream_seq_out = 0
        self.stream_errors = 0
        self.stream_resyncs = 0
        # --spmd-stream-gap-timeout override (satellite: a 30s silent
        # stall was invisible until resync; ops can now shorten the fuse)
        if stream_gap_timeout is not None and stream_gap_timeout > 0:
            self.STREAM_GAP_TIMEOUT = float(stream_gap_timeout)
        # -- mesh observatory state ------------------------------------
        # Separate lock from self._lock: the whole point of the step ring
        # is reading it WHILE a collective is wedged holding _lock.
        self._obs_lock = threading.Lock()
        self._step_ring = deque(maxlen=self.STEP_RING_SIZE)
        self._phase_totals = {}  # phase -> [count, seconds]
        # the in-flight step's clock; only the step-executing thread
        # writes it (one step at a time per process under _lock)
        self._step_clock = None
        # last completed step record, thread-local: the coordinator's
        # query thread IS its step-executing thread, so ANALYZE/profile
        # grafting reads its own step's phases race-free under load
        self._step_tls = threading.local()
        self.gap_onsets = 0
        self.gap_stall_seconds = 0.0
        self._straggler_flags = OrderedDict()  # (seq, node, phase) -> 1
        self.straggler_flags_total = 0
        # per-node step lifecycle counters (satellite: wedge root-cause —
        # announced>entered means a peer never reached the collective,
        # entered>exited means the collective itself hung)
        self.steps_announced = 0
        self.steps_entered = 0
        self.steps_exited = 0
        self.last_seq = 0
        # batched/fused collective accounting
        self.batch_steps = 0
        self.batched_queries = 0
        self.fused_steps = 0
        self.fused_queries = 0
        # Count pre-flight epochs: {index: membership epoch} of the last
        # successful validation round. Steps carry resolved plans, so the
        # per-query peer checks are all membership/boot-constant — one
        # validation round per epoch suffices (steady-state count = ONE
        # HTTP round per query). Node state changes form a new epoch.
        self._count_epochs = OrderedDict()
        # observability: /internal/spmd/stats
        self.steps_run = 0
        self.validations = 0
        self.validations_skipped = 0
        self.forwarded = 0
        self.forward_errors = 0
        self.fallbacks = 0  # eligible calls declined past the gate (caps…)
        self._local_exec = None  # set by API (shared serving executor)
        # The JAX process set is fixed at startup (initialize is
        # once-only); if the cluster later grows or shrinks, SPMD must
        # decline — new nodes are not mesh participants.
        self._boot_node_ids = tuple(sorted(n.id for n in cluster.nodes)) \
            if cluster is not None else ()

    # -- mesh ----------------------------------------------------------------

    def _global_sharding(self, shard_axis=0, ndim=2):
        """NamedSharding over the GLOBAL device list, process-major, so
        each process's addressable block is contiguous along the shard
        axis (what make_array_from_process_local_data fills)."""
        if self._mesh is None:
            from ..parallel.sharded import build_global_mesh

            self._mesh = build_global_mesh()
        import jax

        spec = [None] * ndim
        spec[shard_axis] = "shards"
        return jax.sharding.NamedSharding(
            self._mesh, jax.sharding.PartitionSpec(*spec))

    def _local_device_count(self):
        import jax

        return len(jax.local_devices())

    def _num_processes(self):
        import jax

        return jax.process_count()

    def mesh_shape(self):
        """(processes, devices per process) — the mesh-key component and
        the shape EXPLAIN reports."""
        return [self._num_processes(), self._local_device_count()]

    def set_serve_mode(self, mode):
        """Runtime serve-mode switch (POST /debug/spmd). Raises on an
        unknown mode; the caller maps that to a 400."""
        if mode not in self.SERVE_MODES:
            raise SpmdError(f"unknown spmd serve mode: {mode!r}")
        self.serve_mode = mode
        return self.serve_mode

    # -- signature helper ----------------------------------------------------

    def _signature(self, idx, call):
        """Tree signature for SPMD coverage (coordinator side only — the
        resolved plan ships IN the step). Same shape rules as the stacked
        evaluator (shared walk: exec.stacked.tree_signature) but leaf
        checks consult only REPLICATED state (the schema): local
        view/fragment existence differs per node, and a node that owns no
        shards of a field simply contributes zero planes."""
        from ..exec.bsicond import normalize_bsi_condition
        from ..exec.stacked import tree_signature

        def leaf(idx, field_name, row_id, leaves):
            if idx.field(field_name) is None:
                return None
            key = ("row", field_name, int(row_id))
            if key not in leaves:
                leaves[key] = len(leaves)
            return ("leaf", leaves[key])

        def bsi_leaf(idx, field_name, cond, leaves):
            field = idx.field(field_name)
            if field is None or field.options.type != "int":
                return None
            norm = normalize_bsi_condition(cond)
            if norm is None:
                return None
            op, vals = norm
            key = ("bsicond", field_name, op, vals)
            if key not in leaves:
                leaves[key] = len(leaves)
            return ("leaf", leaves[key])

        from ..exec.stacked import intern_time_leaf

        leaves = {}
        sig = tree_signature(idx, call, leaves, leaf, bsi_leaf,
                             intern_time_leaf)
        if sig is None or not leaves:
            return None
        ordered = sorted(leaves.items(), key=lambda kv: kv[1])
        return sig, [key for key, _ in ordered]

    @staticmethod
    def _leaf_to_wire(key):
        """Leaf key -> JSON-able tagged entry: ["row", field, row_id] or
        ["bsicond", field, op, values]."""
        if key[0] == "bsicond":
            _, field_name, op, vals = key
            return ["bsicond", field_name, op,
                    list(vals) if isinstance(vals, tuple) else vals]
        if key[0] == "timerow":
            _, field_name, row_id, views = key
            return ["timerow", field_name, row_id, list(views)]
        _, field_name, row_id = key
        return ["row", field_name, row_id]

    def _plan_filter(self, idx, step, filter_call):
        """Attach an optional filter plan to a step; False when the filter
        tree isn't coverable (caller falls back to HTTP)."""
        if filter_call is None:
            step["sig"] = None
            step["leaves"] = []
            return True
        sig_leaves = self._signature(idx, filter_call)
        if sig_leaves is None:
            return False
        sig, leaf_keys = sig_leaves
        step["sig"] = sig_to_wire(sig)
        step["leaves"] = [self._leaf_to_wire(k) for k in leaf_keys]
        return True

    # -- entry (any node) ----------------------------------------------------

    def _call_kind(self, call):
        if call.name == "Count" and len(call.children) == 1:
            return "count"
        if call.name == "Sum":
            return "sum"
        if call.name == "TopN":
            return "topn"
        if call.name in ("Min", "Max"):
            return "minmax"
        if call.name == "GroupBy":
            return "groupby"
        return None

    def maybe_execute(self, idx, call, shards, forwarded=False):
        """THE ClusterExecutor entry: (used, result). used=False means the
        caller should take the HTTP merge path. Runs on ANY node: the
        coordinator initiates directly; other nodes forward eligible calls
        to the coordinator in one hop (reference: any node coordinates,
        executor.go:113)."""
        if self.serve_mode == "http":
            return False, None  # bench A/B: force the HTTP fan-out path
        kind = self._call_kind(call)
        if kind is None:
            return False, None
        cluster = self.cluster
        if cluster is None or len(cluster.nodes) < 2:
            return False, None
        from .node import NODE_STATE_READY

        if any(n.state != NODE_STATE_READY for n in cluster.nodes):
            return False, None  # a hung participant would stall the mesh
        if tuple(sorted(n.id for n in cluster.nodes)) != self._boot_node_ids:
            return False, None  # membership changed since distributed init
        coord = cluster.coordinator
        if coord is None:
            return False, None
        if coord.id != cluster.local_id:
            if forwarded:
                return False, None  # never bounce a forwarded call again
            # schema-level pre-check so a call the coordinator would
            # refuse anyway never pays the forward hop (the coordinator
            # itself skips this: its _try_* handlers re-derive the same
            # signatures as part of building the step plan)
            if not self._eligible(idx, call, kind):
                return False, None
            return self._forward(idx, call, shards, coord)
        try_fn = {
            "count": self._try_count,
            "sum": self._try_sum,
            "topn": self._try_topn,
            "minmax": self._try_minmax,
            "groupby": self._try_groupby,
        }[kind]
        from ..utils import tracing

        before = getattr(self._step_tls, "rec", None)
        try:
            # the collective data plane is otherwise invisible to a query
            # profile — this span records that the query went over SPMD
            # (and how long the collective step took) instead of HTTP
            with tracing.start_span("spmd.step", kind=kind,
                                    shards=len(shards)) as span:
                result = try_fn(idx, call, list(shards))
                self._graft_span(span, before=before)
        except Exception as e:
            # Watchdog: a wedged/failed collective (e.g. a peer that died
            # inside the amortized-validation window while still marked
            # READY) surfaces here once the distributed runtime times out.
            # Invalidate the epoch so the next query re-probes peers, and
            # fall back to the HTTP merge instead of erroring the query.
            self.fallbacks += 1
            self._count_epochs.pop(idx.name, None)
            self.logger.printf(
                "spmd: %s step failed (%s); epoch invalidated, falling "
                "back to HTTP merge", kind, e)
            return False, None
        if result is None:
            return False, None
        return True, result

    def _eligible(self, idx, call, kind):
        """Replicated-schema eligibility shared by the forward pre-check
        and the coordinator: every check here depends only on state all
        nodes agree on, so a non-coordinator can decline locally instead
        of paying a wasted hop for a call the coordinator would refuse."""
        if kind == "count":
            return self._signature(idx, call.children[0]) is not None
        if kind in ("sum", "minmax"):
            if self._agg_field(idx, call, want_int=True) is None:
                return False
            filter_call = call.children[0] if call.children else None
            return filter_call is None \
                or self._signature(idx, filter_call) is not None
        if kind == "topn":
            field_name = call.args.get("_field") or call.field_arg()
            field = idx.field(field_name) if field_name else None
            if field is None or field.options.type == "int":
                return False
            if call.args.get("tanimotoThreshold") \
                    or call.args.get("attrName") is not None \
                    or call.args.get("ids") is not None \
                    or len(call.children) > 1:
                return False
            filter_call = call.children[0] if call.children else None
            return filter_call is None \
                or self._signature(idx, filter_call) is not None
        if kind == "groupby":
            from ..core.field import FIELD_TYPE_INT, FIELD_TYPE_TIME

            if not call.children:
                return False
            for child in call.children:
                if child.name != "Rows":
                    return False
                if "column" in child.args or "from" in child.args \
                        or "to" in child.args:
                    return False
                fname = child.args.get("_field") \
                    or child.args.get("field") or child.field_arg()
                field = idx.field(fname) if fname else None
                if field is None or field.type in (FIELD_TYPE_INT,
                                                   FIELD_TYPE_TIME):
                    return False
            filter_call = call.args.get("filter")
            if filter_call is None:
                return True
            return isinstance(filter_call, Call) \
                and self._signature(idx, filter_call) is not None
        return False

    def _forward(self, idx, call, shards, coord):
        """Non-coordinator hop: hand the eligible call to the coordinator
        for step initiation (single initiator keeps step order global)."""
        try:
            client = self.client_factory(coord.uri)
            client.timeout = self.STEP_TIMEOUT + 30
            resp = client.spmd_initiate({
                "index": idx.name,
                "pql": call_to_pql(call),
                "shards": list(shards),
            })
        except Exception as e:
            self.forward_errors += 1
            self.logger.printf(
                "spmd: initiate forward to coordinator failed "
                "(falling back to HTTP merge): %s", e)
            return False, None
        if not resp.get("used"):
            return False, None
        self.forwarded += 1
        from .executor import result_from_json

        return True, result_from_json(resp.get("result"))

    def initiate(self, payload):
        """Coordinator-side handler for POST /internal/spmd/initiate."""
        idx = self.holder.index(payload["index"])
        if idx is None:
            return {"used": False}
        call = parse(payload["pql"]).calls[0]
        used, result = self.maybe_execute(
            idx, call, [int(s) for s in payload["shards"]], forwarded=True)
        if not used:
            return {"used": False}
        return {"used": True, "result": self._wire_result(result)}

    @staticmethod
    def _wire_result(result):
        from ..exec.result import GroupCount, Pair, ValCount

        if isinstance(result, ValCount):
            return result.to_json()
        if isinstance(result, list):
            if result and isinstance(result[0], (Pair, GroupCount)):
                return [r.to_json() for r in result]
            return list(result)
        return int(result)  # count

    # -- coordinator gating --------------------------------------------------

    def _gate(self, idx, shards):
        """Shard-segment skeleton for a step (padding so every process
        contributes an equal-shaped block). Cluster-health checks live in
        maybe_execute; this only derives shapes."""
        cluster = self.cluster
        by_node = cluster.shards_by_node(idx.name, list(shards))
        segments = {node.id: sorted(s) for node, s in by_node.items()}
        # every process contributes an equal-shaped block (zero planes for
        # nodes with fewer/no shards), padded to its device multiple
        dev_pp = self._local_device_count()
        longest = max((len(s) for s in segments.values()), default=0)
        seg_len = max(dev_pp, ((longest + dev_pp - 1) // dev_pp) * dev_pp)
        return {
            "index": idx.name,
            "segments": segments,
            "seg_len": seg_len,
            "dev_pp": dev_pp,
            "nodes": list(self._boot_node_ids),
        }

    def _execute_step(self, step):
        """Announce + run one validated step (coordinator side).

        Legacy (serve != on): blocking POST /internal/spmd/step per peer,
        joined around the local collective — byte-identical to the
        pre-mesh control plane.

        Streamed (serve == on): fire-and-ack POST /internal/spmd/stream —
        the peer enqueues the step by sequence number and acks before
        executing, so this call returns as soon as the LOCAL collective
        completes and the coordinator can announce step N+1 while a slow
        peer is still inside step N (the collective itself is the
        synchronization; the old blocking join double-paid it in HTTP
        round-trip time)."""
        from ..utils import flightrec, tracing

        streamed = self.serve_mode == "on"
        # carry the coordinator's trace id so every node's step record —
        # and the merged /debug/spmd/steps timeline — joins back to the
        # query (?profile=true span graft, --metrics-exemplars buckets)
        span = tracing.current_span()
        if span is not None and "trace" not in step:
            step["trace"] = span.trace_id
        with self._lock:
            # announce_recv t0 on the coordinator: announcement fan-out +
            # own step-lock wait (peers overwrite with their receipt time)
            step["_recv_t"] = time.perf_counter()
            self._step_id += 1
            step["step"] = self._step_id
            if streamed:
                self._stream_seq_out += 1
                step["seq"] = self._stream_seq_out
            self.steps_announced += 1
            flightrec.record(
                "spmd.step_announce", index=step.get("index", ""),
                op=step.get("kind", "count"),
                seq=step.get("seq", self._step_id), streamed=streamed)
            errors = []

            def post(node):
                try:
                    client = self.client_factory(node.uri)
                    client.timeout = self.STEP_TIMEOUT
                    if streamed:
                        client.spmd_stream(step)
                    else:
                        client.spmd_step(step)
                except Exception as e:  # surfaced after the collective
                    errors.append((node.id, e))

            threads = [threading.Thread(target=post, args=(n,),
                                        daemon=True)
                       for n in self.cluster.peers()]
            for t in threads:
                t.start()
            # join the collective ourselves — peers are inside run_step
            # (legacy) or their stream runner (streamed) now
            result = self._enter_exit_run(step)
            if not streamed:
                for t in threads:
                    t.join()
        if streamed:
            # acks raced the collective; collect without holding the lock
            for t in threads:
                t.join(timeout=self.VALIDATE_TIMEOUT)
        if errors:
            # We hold a replicated result: for validated-this-query steps
            # every process joined the collective and these are
            # post-collective transport errors (lost responses / lost
            # stream acks). For epoch-skipped count steps a dead peer
            # instead fails the collective itself, which raises out of
            # _run_step_locked and is handled by the maybe_execute
            # watchdog (epoch invalidated, HTTP fallback). Log, don't
            # fail the query.
            if streamed:
                self.stream_errors += len(errors)
            self.logger.printf(
                "spmd: post-collective peer errors (result kept): %s",
                errors)
        return result

    def _enter_exit_run(self, step):
        """_run_step_locked bracketed by the step-lifecycle events the
        wedge classifier reads (bench._classify_wedge): a node whose
        flightrec shows announce-without-enter never reached the
        collective (control-plane loss); enter-without-exit means the
        collective itself hung. Caller holds self._lock.

        Mesh observatory: runs the step under a _StepClock (t0 = the
        step's announcement-receipt stamp, so announce_recv covers
        stream-queue + lock wait) and under a flightrec watchdog — a
        collective stuck past STEP_TIMEOUT now trips a collective_stall
        incident bundle instead of hanging silently."""
        from ..utils import flightrec

        seq = int(step.get("seq") or step.get("step") or 0)
        kind = step.get("kind", "count")
        started = time.time()
        clk = _StepClock(t0=step.pop("_recv_t", None))
        clk.mark("announce_recv")
        self._step_clock = clk
        self.steps_entered += 1
        self.last_seq = max(self.last_seq, seq)
        flightrec.record("spmd.step_enter", index=step.get("index", ""),
                         op=kind, seq=seq)
        token = flightrec.watch_begin("spmd.step", seq=seq, op=kind,
                                      index=step.get("index", ""))
        ok = False
        try:
            result = self._run_step_locked(step)
            ok = True
            return result
        finally:
            flightrec.watch_end(token)
            self._step_clock = None
            wall = clk.close("exit")
            self.steps_exited += 1
            flightrec.record("spmd.step_exit",
                             index=step.get("index", ""),
                             op=kind, seq=seq,
                             ok=ok)
            self._note_step(step, seq, started, wall, clk.phases, ok)

    def _mark_phase(self, phase):
        """Attribute time-since-last-mark to `phase` on the in-flight
        step's clock (no-op outside a step; the clock is only ever set
        by the thread holding self._lock)."""
        clk = self._step_clock
        if clk is not None:
            clk.mark(phase)

    def _note_step(self, step, seq, started, wall, phase_marks, ok):
        """Fold one finished step into the observatory: the bounded step
        ring + per-phase totals (under _obs_lock so /debug readers never
        touch the step lock) and spmd_step_seconds{phase} timings with
        the step's trace id as the exemplar."""
        phases = {}
        for name, secs in phase_marks:
            phases[name] = phases.get(name, 0.0) + secs
        rec = {
            "seq": seq,
            "step": step.get("step", 0),
            "kind": step.get("kind", "count"),
            "index": step.get("index", ""),
            "start": started,
            "wall_seconds": round(wall, 6),
            "ok": ok,
            "phases": {p: round(s, 6) for p, s in phases.items()},
        }
        trace = step.get("trace")
        if trace:
            rec["trace"] = trace
        with self._obs_lock:
            self._step_ring.append(rec)
            for name, secs in phases.items():
                tot = self._phase_totals.get(name)
                if tot is None:
                    tot = self._phase_totals[name] = [0, 0.0]
                tot[0] += 1
                tot[1] += secs
        self._step_tls.rec = rec
        try:
            from ..utils.stats import global_stats

            for name, secs in phases.items():
                global_stats.timing("spmd_step_seconds", secs,
                                    tags={"phase": name}, trace_id=trace)
            global_stats.timing("spmd_step_wall_seconds", wall,
                                trace_id=trace)
        except Exception:  # noqa: BLE001 — stats must never fail a step
            pass

    def _graft_span(self, span, before=None):
        """Tag the query's spmd.step span with the per-phase walls of
        the step THIS thread just executed, so ?profile=true shows where
        collective wall went. `before` (the thread-local rec prior to
        execution) guards the forwarded case, where no local step ran."""
        if span is None:
            return
        rec = getattr(self._step_tls, "rec", None)
        if rec is None or rec is before:
            return
        span.set_tag("phases_ms", {p: round(s * 1000, 3)
                                   for p, s in rec["phases"].items()})
        span.set_tag("step_seq", rec["seq"])

    def _try_count(self, idx, call, shards):
        """Count(call) merged over the global mesh, or None to fall back
        to the HTTP merge path."""
        sig_leaves = self._signature(idx, call.children[0])
        if sig_leaves is None:
            return None
        step = self._gate(idx, shards)
        sig, leaf_keys = sig_leaves
        step["kind"] = "count"
        step["sig"] = sig_to_wire(sig)
        step["leaves"] = [self._leaf_to_wire(k) for k in leaf_keys]
        # Pre-flight, amortized: the step carries its whole plan, so the
        # per-peer checks (spmd enabled, index present, device count,
        # membership) are constant within a membership epoch — validate
        # once per epoch, not per query (VERDICT r3: steady-state SPMD
        # count costs one HTTP round).
        if not self._ensure_count_epoch(step):
            return None
        return self._execute_step(step)

    # -- batched collective steps (PR-9 coalescer x mesh) --------------------

    def _cluster_ready(self, forwarded=False):
        """The maybe_execute cluster gates, shared by the batch and fused
        entries: coordinator-only (they are called from the coalescer /
        executor on the serving node), every node READY, membership
        unchanged since distributed init."""
        cluster = self.cluster
        if cluster is None or len(cluster.nodes) < 2:
            return False
        from .node import NODE_STATE_READY

        if any(n.state != NODE_STATE_READY for n in cluster.nodes):
            return False
        if tuple(sorted(n.id for n in cluster.nodes)) \
                != self._boot_node_ids:
            return False
        coord = cluster.coordinator
        return coord is not None and coord.id == cluster.local_id

    def _count_plans(self, idx, calls):
        """Wire plans for a list of Count calls, or None when any call
        isn't coverable (the whole batch falls back — splitting would
        break the one-announcement contract)."""
        plans = []
        for call in calls:
            if self._call_kind(call) != "count":
                return None
            sig_leaves = self._signature(idx, call.children[0])
            if sig_leaves is None:
                return None
            sig, leaf_keys = sig_leaves
            plans.append({"sig": sig_to_wire(sig),
                          "leaves": [self._leaf_to_wire(k)
                                     for k in leaf_keys]})
        return plans

    def maybe_execute_batch(self, idx, calls, shards):
        """K eligible Count calls as ONE collective step: (used, counts).
        The PR-9 coalescer's cluster adapter (SpmdBatchRunner) lands
        here; serve_mode must be on — batching changes the control-plane
        shape, so it never runs on the byte-identical legacy path."""
        if self.serve_mode != "on" or not calls:
            return False, None
        if not self._cluster_ready():
            return False, None
        plans = self._count_plans(idx, calls)
        if plans is None:
            return False, None
        from ..exec.stacked import batch_bucket

        step = self._gate(idx, shards)
        step["kind"] = "count_batch"
        k = len(plans)
        bucket = batch_bucket(k)
        # pad to the bucket by repeating plan 0 — the mesh cache serves
        # the repeats from device memory and the vmapped group evaluates
        # them in the same walk, so padding is near-free (PR-9 contract)
        step["plans"] = plans + [plans[0]] * (bucket - k)
        step["bucket"] = bucket
        if not self._ensure_count_epoch(step):
            return False, None
        from ..utils import tracing

        try:
            with tracing.start_span("spmd.step", kind="count_batch",
                                    shards=len(shards), batch=k) as span:
                counts = self._execute_step(step)
                self._graft_span(span)
        except Exception as e:
            self.fallbacks += 1
            self._count_epochs.pop(idx.name, None)
            self.logger.printf(
                "spmd: count_batch step failed (%s); epoch invalidated, "
                "falling back to per-query path", e)
            return False, None
        self.batched_queries += k
        return True, counts[:k]

    # -- fused collective programs (PR-16 fusion x mesh) ---------------------

    def maybe_execute_fused(self, idx, query, shards):
        """Whole multi-call cluster query as ONE fused collective program:
        (used, counts). Gated by the PR-16 fusion admission rules (a cold
        fingerprint never pays a collective compile) and ledgered under
        the mesh-shaped program key, so /debug/fusion shows which fabric
        each collective program was traced for. Warm path: one jitted
        program per process, one announcement, zero result bytes over
        HTTP."""
        from ..exec import fusion as fusion_mod

        if self.serve_mode != "on" or not fusion_mod.acting():
            return False, None
        calls = list(query.calls)
        if not calls or any(self._call_kind(c) != "count" for c in calls):
            return False, None
        if not self._cluster_ready():
            return False, None
        from ..utils import workload as workload_mod

        fp = workload_mod.current_fingerprint()
        if fp is None:
            fp, _ = workload_mod.fingerprint(idx.name, query)
        if not fusion_mod.admit(fp):
            return False, None
        plans = self._count_plans(idx, calls)
        if plans is None:
            return False, None
        from ..exec.stacked import batch_bucket

        step = self._gate(idx, shards)
        step["kind"] = "count_batch"
        k = len(plans)
        bucket = batch_bucket(k)
        step["plans"] = plans + [plans[0]] * (bucket - k)
        step["bucket"] = bucket
        if not self._ensure_count_epoch(step):
            return False, None
        sigs = tuple(sig_from_wire(p["sig"]) for p in step["plans"])
        arities = tuple(len(p["leaves"]) for p in step["plans"])
        fn_key = ("count_batch", sigs, arities)
        compiled = fn_key not in self._fns
        import time as _time

        from ..utils import tracing

        t0 = _time.perf_counter()
        try:
            with tracing.start_span("spmd.step", kind="fused",
                                    shards=len(shards), batch=k) as span:
                counts = self._execute_step(step)
                self._graft_span(span)
        except Exception as e:
            self.fallbacks += 1
            self._count_epochs.pop(idx.name, None)
            self.logger.printf(
                "spmd: fused step failed (%s); epoch invalidated, "
                "falling back to per-call path", e)
            return False, None
        wall = _time.perf_counter() - t0
        # ledger AFTER _execute_step released self._lock: fusion eviction
        # re-enters ev._lock (ours) to drop the jitted collective
        key = fusion_mod.mesh_program_key(fp, sigs, bucket,
                                          self.mesh_shape())
        fusion_mod.touch_mesh_program(
            key, self, fn_key,
            compile_ms=wall * 1000 if compiled else None)
        fusion_mod.note_fused(k)
        workload_mod.note_batch(k)
        self.fused_steps += 1
        self.fused_queries += 1
        return True, counts[:k]

    # -- EXPLAIN (plan + analyze) --------------------------------------------

    def plan_eligible(self, idx, call):
        """Would the normal serving path take the collective plane for
        this call? The ?explain=true annotation gate — nothing executes."""
        if self.serve_mode != "on":
            return False
        kind = self._call_kind(call)
        if kind is None:
            return False
        cluster = self.cluster
        if cluster is None or len(cluster.nodes) < 2:
            return False
        from .node import NODE_STATE_READY

        if any(n.state != NODE_STATE_READY for n in cluster.nodes):
            return False
        if tuple(sorted(n.id for n in cluster.nodes)) \
                != self._boot_node_ids:
            return False
        if cluster.coordinator is None:
            return False
        return self._eligible(idx, call, kind)

    def plan_node(self, idx, call, shards):
        """Serialized mesh plan entry for ?explain=true: the collective
        path runs ZERO per-node dispatches from the coordinator's view —
        one globally-sharded program replaces the fan-out."""
        return {
            "op": call.name,
            "strategy": "spmd-collective",
            "annotations": {
                "spmd": True,
                "mesh": self.mesh_shape(),
                "dispatches": 0,
                "shards": len(shards or []),
            },
            "children": [],
        }

    @staticmethod
    def _psum_bytes(kind, result):
        """Replicated all-reduce output payload per process — the bytes
        the collective moved in place of an HTTP result body. Count is
        the (hi, lo) int32 pair; vector kinds scale by output length."""
        if isinstance(result, (list, tuple)):
            return 8 * max(1, len(result))
        return 8

    def maybe_execute_analyze(self, idx, call, shards):
        """?explain=analyze through the collective plane: really execute
        (PR-16 fused-analyze contract: analyze reports the path that
        serves), then graft the step's single dispatch + psum bytes onto
        a mesh plan entry. (used, result, plan_entry)."""
        if self.serve_mode != "on":
            return False, None, None
        import time as _time

        before = getattr(self._step_tls, "rec", None)
        t0 = _time.perf_counter()
        used, result = self.maybe_execute(idx, call, shards)
        if not used:
            return False, None, None
        wall = _time.perf_counter() - t0
        kind = self._call_kind(call)
        entry = {
            "node": "mesh",
            "shards": len(shards or []),
            "plan": {
                "op": call.name,
                "strategy": "spmd-collective",
                "annotations": {
                    "spmd": True,
                    "mesh": self.mesh_shape(),
                    "dispatches": 1,
                    "psum_bytes": self._psum_bytes(kind, result),
                    "wall_ms": round(wall * 1000, 3),
                },
                "children": [],
            },
        }
        # mesh observatory: this thread just executed the coordinator's
        # half of the step (the query thread IS the step thread), so its
        # thread-local step record carries the per-phase walls — graft
        # them under the collective node's annotations. `rec is before`
        # means no local step ran (the call was forwarded): skip.
        rec = getattr(self._step_tls, "rec", None)
        if rec is not None and rec is not before:
            entry["plan"]["annotations"]["phases_ms"] = {
                p: round(s * 1000, 3) for p, s in rec["phases"].items()}
            entry["plan"]["annotations"]["step_seq"] = rec["seq"]
        return True, result, entry

    def _membership_epoch(self):
        return tuple((n.id, n.state) for n in self.cluster.nodes)

    def _ensure_count_epoch(self, step):
        epoch = self._membership_epoch()
        if self._count_epochs.get(step["index"]) == epoch:
            self.validations_skipped += 1
            return True
        if self._validate_on_peers(step) is None:
            return False
        self._count_epochs[step["index"]] = epoch
        while len(self._count_epochs) > 64:
            self._count_epochs.popitem(last=False)
        return True

    def _agg_field(self, idx, call, want_int):
        field_name = call.args.get("field") or call.args.get("_field") \
            or call.field_arg()
        field = idx.field(field_name) if field_name else None
        if field is None:
            return None
        if want_int != (field.options.type == "int"):
            return None
        return field

    def _try_sum(self, idx, call, shards):
        """Sum(filter?, field=f) merged over the global mesh: the BSI
        bit planes form [depth, shards, words] globally-sharded arrays and
        the per-plane popcounts all-reduce over the fabric. Returns the
        final ValCount with the field base applied (field.go:1583),
        or None to fall back."""
        from ..exec.result import ValCount

        field = self._agg_field(idx, call, want_int=True)
        if field is None:
            return None
        filter_call = call.children[0] if call.children else None
        step = self._gate(idx, shards)
        step["kind"] = "sum"
        step["field"] = field.name
        if not self._plan_filter(idx, step, filter_call):
            return None
        resps = self._validate_on_peers(step)
        if resps is None:
            return None
        # depth can differ per node (it grows with out-of-range writes);
        # the step uses the cluster-wide max, peers zero-extend
        step["depth"] = max(
            [field.options.bit_depth]
            + [int(r.get("bit_depth", 0)) for r in resps])
        total, count = self._execute_step(step)
        return ValCount(total + field.options.base * count, count)

    def _try_minmax(self, idx, call, shards):
        """Min/Max over globally-sharded BSI planes: the narrowing
        bit-plane walk (ops.bsi min/max_unsigned) runs ONCE over the
        global [depth, shards, words] arrays — its any() reductions become
        cross-process collectives, so the global extremum and its count
        come out replicated (reference merge: ValCount.Smaller/Larger over
        per-node partials, executor.go:380-474)."""
        from ..exec.result import ValCount

        field = self._agg_field(idx, call, want_int=True)
        if field is None:
            return None
        filter_call = call.children[0] if call.children else None
        step = self._gate(idx, shards)
        step["kind"] = "minmax"
        step["field"] = field.name
        step["is_max"] = call.name == "Max"
        if not self._plan_filter(idx, step, filter_call):
            return None
        resps = self._validate_on_peers(step)
        if resps is None:
            return None
        step["depth"] = max(
            [field.options.bit_depth]
            + [int(r.get("bit_depth", 0)) for r in resps])
        empty, use_neg, bits, count = self._execute_step(step)
        if empty:
            return ValCount()
        mag = sum(int(b) << i for i, b in enumerate(bits))
        if use_neg:
            mag = -mag
        return ValCount(mag + field.options.base, count)

    #: candidate-row cap for SPMD TopN: [rows, shards, words] blocks must
    #: stay bounded per process; larger candidate sets fall back to HTTP
    TOPN_MAX_ROWS = 4096

    def _try_topn(self, idx, call, shards):
        """TopN merged over the global mesh: candidate rows are unioned
        across nodes in the validation round, then one [rows, shards,
        words] globally-sharded stack counts every candidate with the
        cross-process all-reduce. Returns the final trimmed pair list
        (reference merge: Pairs.Add cache.go:356 + executor.go:925), or
        None to fall back (attr filters / tanimoto / oversized candidate
        sets use the HTTP path)."""
        field_name = call.args.get("_field") or call.field_arg()
        field = idx.field(field_name) if field_name else None
        if field is None or field.options.type == "int":
            return None
        # tanimoto needs per-row plain counts + src count; attr filters
        # need the attr store; ids restricts the candidate set to exactly
        # the requested rows (restrict_ids semantics, executor.go:947) —
        # all stay on the HTTP/local path
        if call.args.get("tanimotoThreshold") \
                or call.args.get("attrName") is not None \
                or call.args.get("ids") is not None:
            return None
        if len(call.children) > 1:
            return None
        filter_call = call.children[0] if call.children else None
        step = self._gate(idx, shards)
        step["kind"] = "topn"
        step["field"] = field.name
        if not self._plan_filter(idx, step, filter_call):
            return None
        resps = self._validate_on_peers(step)
        if resps is None:
            return None
        # global candidate set = union of every node's cache/row ids
        rows = set(self._topn_candidates(idx, field.name))
        for r in resps:
            rows.update(int(x) for x in r.get("rows", []))
        rows = sorted(rows)
        if not rows:
            return []
        if len(rows) > self.TOPN_MAX_ROWS:
            # NOT silent (VERDICT r3 weak#4): a wide field crossing this
            # cliff shifts the query to the HTTP merge path.
            self.fallbacks += 1
            self.logger.printf(
                "spmd: TopN(%s) candidate set %d exceeds cap %d; "
                "falling back to HTTP merge", field.name, len(rows),
                self.TOPN_MAX_ROWS)
            return None
        step["rows"] = rows
        counts = self._execute_step(step)

        from ..exec.result import Pair

        threshold = max(int(call.args.get("threshold") or 1), 1)
        pairs = [Pair(r, c) for r, c in zip(rows, counts)
                 if c >= threshold]
        pairs.sort(key=lambda p: (-p.count, p.id))
        n = call.args.get("n")
        if n is not None:
            pairs = pairs[:int(n)]
        return pairs

    #: group-cell cap for SPMD GroupBy: the counting stack gathers
    #: [cells, shards, words] blocks — same budget shape as TopN rows
    GROUPBY_MAX_CELLS = 4096

    def _try_groupby(self, idx, call, shards):
        """GroupBy merged over the global mesh: per-child candidate rows
        union across nodes in the validation round, then ONE jitted
        program counts the full row cross-product with the cross-process
        all-reduce (reference merge: mergeGroupCounts over per-node
        partials, executor.go:1098-1237). Falls back on time fields,
        column/range-scoped Rows children, uncoverable filters, or
        oversized cross-products."""
        from ..core.field import FIELD_TYPE_INT, FIELD_TYPE_TIME
        from ..exec.result import FieldRow, GroupCount

        if not call.children:
            return None
        fields = []
        for child in call.children:
            if child.name != "Rows":
                return None
            if "column" in child.args or "from" in child.args \
                    or "to" in child.args:
                return None  # shard/time-scoped Rows: HTTP path
            fname = child.args.get("_field") or child.args.get("field") \
                or child.field_arg()
            field = idx.field(fname) if fname else None
            if field is None or field.type in (FIELD_TYPE_INT,
                                               FIELD_TYPE_TIME):
                return None
            fields.append(field)
        # Call-level `previous` list cursor (one row id per child), same
        # validation + seeding as the local executor. Validated BEFORE
        # the collective round: a malformed cursor must not cost a mesh
        # step just to fall back to HTTP and raise the same error there.
        from ..exec.executor import groupby_previous

        previous = groupby_previous(call, len(call.children))
        prev_t = tuple(previous) if previous is not None else None
        filter_call = call.args.get("filter")
        step = self._gate(idx, shards)
        step["kind"] = "groupby"
        step["fields"] = [f.name for f in fields]
        if not self._plan_filter(idx, step, filter_call):
            return None
        resps = self._validate_on_peers(step)
        if resps is None:
            return None
        child_rows = []
        for i, (child, field) in enumerate(zip(call.children, fields)):
            rows = set(self._rows_candidates(idx, field.name))
            for r in resps:
                per_child = r.get("rows", [])
                if i < len(per_child):
                    rows.update(int(x) for x in per_child[i])
            # Over-cap decline happens BEFORE previous/limit pruning: the
            # per-node candidate lists are truncated at the cap, so a
            # merged set past it may be missing rows — pruning first could
            # shrink an incomplete set under the cap and return a silently
            # wrong (partial) result instead of falling back to HTTP.
            if len(rows) > self.GROUPBY_MAX_CELLS:
                self.fallbacks += 1
                self.logger.printf(
                    "spmd: GroupBy child %s has %d candidate rows "
                    "(cap %d); falling back to HTTP merge", field.name,
                    len(rows), self.GROUPBY_MAX_CELLS)
                return None
            rows = sorted(rows)
            # child Rows() args apply to the GLOBAL merged set (exactly
            # executor._exec_rows semantics)
            previous = child.args.get("previous")
            if previous is not None:
                rows = [r for r in rows if r > int(previous)]
            limit = child.args.get("limit")
            if limit is not None:
                rows = rows[:int(limit)]
            child_rows.append(rows)
        # Seed the outermost child from the cursor (its iterator never
        # wraps); groups at or before the cursor are dropped
        # lexicographically below.
        if previous is not None:
            lo = previous[0] + (1 if len(child_rows) == 1 else 0)
            child_rows[0] = [r for r in child_rows[0] if r >= lo]
        cells = 1
        for rows in child_rows:
            cells *= len(rows)
        if cells == 0:
            return []
        if cells > self.GROUPBY_MAX_CELLS:
            self.fallbacks += 1
            self.logger.printf(
                "spmd: GroupBy cross-product %d cells exceeds cap %d; "
                "falling back to HTTP merge", cells,
                self.GROUPBY_MAX_CELLS)
            return None
        step["rows"] = child_rows
        counts = self._execute_step(step)

        # cell order == itertools.product order == lexicographic by row-id
        # tuple (child_rows are sorted), so the output is already in the
        # local executor's sorted-group order — no re-sort needed
        out = []
        for group, cnt in zip(itertools.product(*child_rows), counts):
            if cnt > 0 and (prev_t is None or group > prev_t):
                out.append(GroupCount(
                    [FieldRow(f.name, rid)
                     for f, rid in zip(fields, group)], cnt))
        limit = call.args.get("limit")
        if limit is not None:
            out = out[:int(limit)]
        # offset after the limit-bounded merge, no-op when past the end
        # (reference parity: executeGroupBy executor.go:1134-1143)
        offset = call.args.get("offset")
        if offset is not None and int(offset) < len(out):
            out = out[int(offset):]
        return out

    def _topn_candidates(self, idx, field_name):
        """This node's TopN candidate rows (shared policy:
        exec.executor.fragment_topn_candidates), capped at
        TOPN_MAX_ROWS+1: a single node already past the cap forces the
        HTTP fallback regardless of the union, so shipping more ids in
        the validate response would be pure wasted payload."""
        from ..exec.executor import fragment_topn_candidates

        field = idx.field(field_name)
        view = field.view(VIEW_STANDARD) if field is not None else None
        if view is None:
            return []
        rows = set()
        for frag in list(view.fragments.values()):
            rows.update(fragment_topn_candidates(frag))
        return sorted(rows)[:self.TOPN_MAX_ROWS + 1]

    def _rows_candidates(self, idx, field_name):
        """This node's present rows of a field (GroupBy child candidates;
        reference: fragment.rows via executeRowsShard executor.go:1319).
        Capped at GROUPBY_MAX_CELLS+1 — one over-cap child pushes the
        cross-product over the cell cap by itself (unless another child is
        empty, in which case the product is 0 either way), so the decline
        decision is preserved while the validate payload stays bounded."""
        field = idx.field(field_name)
        view = field.view(VIEW_STANDARD) if field is not None else None
        if view is None:
            return []
        rows = set()
        for frag in list(view.fragments.values()):
            rows.update(frag.row_ids())
        return sorted(rows)[:self.GROUPBY_MAX_CELLS + 1]

    def _validate_on_peers(self, step):
        """Pre-flight every peer; returns the list of OK responses, or
        None when any peer declined/was unreachable."""
        self.validations += 1
        resps = []

        def probe(node):
            try:
                client = self.client_factory(node.uri)
                client.timeout = self.VALIDATE_TIMEOUT
                resps.append(client.spmd_validate(step))
            except Exception:
                resps.append({"ok": False})

        threads = [threading.Thread(target=probe, args=(n,))
                   for n in self.cluster.peers()]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        if len(resps) != len(self.cluster.peers()) \
                or not all(r.get("ok") for r in resps):
            return None
        return resps

    def validate(self, step):
        """Peer-side pre-flight check (POST /internal/spmd/validate).
        Static-compatibility checks only — the step carries its whole
        plan, so there is nothing tree-shaped to re-derive here. Aggregate
        kinds also contribute per-node data the coordinator merges:
        bit_depth for sum/minmax (depth grows locally past the declared
        range, field.set_value), candidate rows for topn/groupby."""
        idx = self.holder.index(step["index"])
        if idx is None:
            return {"ok": False, "reason": "index not found"}
        if int(step["dev_pp"]) != self._local_device_count():
            return {"ok": False, "reason": "device count mismatch"}
        if tuple(step.get("nodes", ())) != self._boot_node_ids:
            return {"ok": False, "reason": "membership mismatch"}
        out = {"ok": True}
        kind = step.get("kind", "count")
        if kind in ("sum", "minmax"):
            field = idx.field(step["field"])
            if field is None or field.options.type != "int":
                return {"ok": False, "reason": "not an int field"}
            out["bit_depth"] = field.options.bit_depth
        elif kind == "topn":
            field = idx.field(step["field"])
            if field is None or field.options.type == "int":
                return {"ok": False, "reason": "not a set field"}
            # contribute this node's candidate rows to the global union
            out["rows"] = self._topn_candidates(idx, step["field"])
        elif kind == "groupby":
            out["rows"] = [self._rows_candidates(idx, f)
                           for f in step["fields"]]
        return out

    # -- step execution (every process) --------------------------------------

    def run_step(self, step):
        """HTTP-handler entry for peer processes (blocking legacy
        announcements, serve_mode != on)."""
        # observatory t0: overwrite unconditionally — any coordinator
        # stamp that leaked over the wire is from a different process's
        # perf_counter and meaningless here; announce_recv then measures
        # this node's step-lock wait
        step["_recv_t"] = time.perf_counter()
        with self._lock:
            return self._enter_exit_run(step)

    def run_stream(self, step):
        """HTTP-handler entry for STREAMED announcements (serve == on):
        enqueue by sequence number and ack immediately — the stream
        runner thread executes steps in seq order, so the coordinator's
        announcing thread never blocks on this peer's collective."""
        seq = int(step["seq"])
        # observatory t0 at ENQUEUE: announce_recv then measures the
        # stream-queue wait + step-lock wait (pipeline occupancy per step)
        step["_recv_t"] = time.perf_counter()
        with self._stream_cond:
            self._stream_queue[seq] = step
            if self._stream_next is None:
                self._stream_next = seq
            if self._stream_thread is None \
                    or not self._stream_thread.is_alive():
                self._stream_thread = threading.Thread(
                    target=self._stream_loop, name="spmd-stream",
                    daemon=True)
                self._stream_thread.start()
            self._stream_cond.notify_all()
        return {"ok": True, "seq": seq, "queued": len(self._stream_queue)}

    def close(self):
        """Stop the stream runner (server shutdown)."""
        with self._stream_cond:
            self._stream_closed = True
            self._stream_cond.notify_all()

    def _stream_loop(self):
        """Peer-side stream runner: executes queued steps strictly in
        sequence order. A gap (announcement lost while later steps keep
        arriving) times out after STREAM_GAP_TIMEOUT and resyncs to the
        lowest queued seq — the coordinator's collective for the lost
        step already failed via the distributed-runtime timeout and fell
        back to HTTP, so skipping it here preserves the identical
        program order on every process for the steps that DID run."""
        from ..utils import flightrec, incident

        while True:
            with self._stream_cond:
                deadline = None
                gap_started = None
                while not self._stream_closed:
                    nxt = self._stream_next
                    if nxt is not None and nxt in self._stream_queue:
                        break
                    if self._stream_queue:
                        now = time.monotonic()
                        if deadline is None:
                            # gap ONSET: later steps queued but the
                            # expected seq is missing. Announce it NOW —
                            # a silent STREAM_GAP_TIMEOUT stall was
                            # previously invisible until the resync —
                            # and trigger the collective_stall autopsy
                            # so every peer's step ring is captured
                            # while the gap is still open.
                            deadline = now + self.STREAM_GAP_TIMEOUT
                            gap_started = now
                            self.gap_onsets += 1
                            flightrec.record(
                                "spmd.stream_gap", expected=nxt,
                                queued=len(self._stream_queue),
                                timeout_seconds=self.STREAM_GAP_TIMEOUT)
                            incident.maybe_trigger(
                                "collective_stall", cause="stream_gap",
                                expected_seq=nxt if nxt is not None
                                else -1,
                                queued=len(self._stream_queue))
                        if now >= deadline:
                            resync = min(self._stream_queue)
                            self.stream_resyncs += 1
                            self.gap_stall_seconds += now - gap_started
                            gap_started = None
                            flightrec.record(
                                "spmd.stream_resync",
                                expected=nxt, resync=resync)
                            self.logger.printf(
                                "spmd: stream gap at seq %s; resyncing "
                                "to %s", nxt, resync)
                            self._stream_next = resync
                            break
                        self._stream_cond.wait(deadline - now)
                    else:
                        deadline = None
                        gap_started = None
                        self._stream_cond.wait(1.0)
                if gap_started is not None:
                    # gap closed by arrival (or shutdown): account the
                    # stall time the pipeline spent blocked on it
                    self.gap_stall_seconds += time.monotonic() \
                        - gap_started
                if self._stream_closed:
                    return
                step = self._stream_queue.pop(self._stream_next)
                self._stream_next += 1
            try:
                with self._lock:
                    # result discarded: the collective output is
                    # replicated, only the coordinator reads it
                    self._enter_exit_run(step)
            except Exception as e:
                # the coordinator saw the same collective failure and
                # fell back; keep this runner alive for the next step
                self.stream_errors += 1
                self.logger.printf(
                    "spmd: streamed step %s failed on this node: %s",
                    step.get("seq"), e)

    def _run_step_locked(self, step):
        # A validated peer MUST enter the collective: every failure mode
        # past this point (index/field dropped by a racing DDL, fragment
        # churn) degrades to zero planes inside _local_block — never an
        # exception that would leave the other processes blocked in the
        # rendezvous (the ADVICE r3 wedge). steps_run increments are under
        # self._lock (held here by both entry paths).
        idx = self.holder.index(step["index"])
        kind = step.get("kind", "count")
        if kind == "count":
            return self._run_count_step(idx, step)
        if kind == "count_batch":
            return self._run_count_batch_step(idx, step)
        if kind == "sum":
            return self._run_sum_step(idx, step)
        if kind == "minmax":
            return self._run_minmax_step(idx, step)
        if kind == "topn":
            return self._run_topn_step(idx, step)
        if kind == "groupby":
            return self._run_groupby_step(idx, step)
        raise SpmdError(f"unknown spmd step kind: {kind}")

    def _local_block(self, idx, step, field_name, row_id,
                     view_name=None):
        """This process's [seg_len, W] block of one row over its owned
        shards. DEFENSIVE by design: zero planes for shards, fragments,
        fields, views — or a whole index — this process doesn't hold
        (including anything lost to a racing DDL after validation); zeros
        are count-neutral for every covered op, and a throw here would
        wedge the collective (see _run_step_locked)."""
        seg_len = int(step["seg_len"])
        my_shards = step["segments"].get(self.cluster.local_id, [])
        if len(my_shards) > seg_len:
            # cannot happen with a correct coordinator (seg_len is the
            # padded max segment); truncate loudly rather than wedge the
            # rendezvous by raising
            self.logger.printf(
                "spmd: segment length %d exceeds seg_len %d on step %s; "
                "truncating", len(my_shards), seg_len, step.get("step"))
            my_shards = my_shards[:seg_len]
        local = np.zeros((seg_len, WORDS_PER_ROW), dtype=np.uint32)
        try:
            field = idx.field(field_name) if idx is not None else None
            view = field.view(view_name or VIEW_STANDARD) \
                if field is not None else None
            if view is not None:
                for j, shard in enumerate(my_shards):
                    frag = view.fragment(shard)
                    if frag is not None:
                        plane = frag.row_plane(row_id)
                        if plane is not None:
                            local[j] = np.asarray(plane)
        except Exception as e:
            self.logger.printf(
                "spmd: local block gather failed (%s row %s): %s — "
                "contributing zero planes", field_name, row_id, e)
        return local

    def _local_cond_block(self, idx, step, field_name, op, vals):
        """This process's [seg_len, W] block of one BSI condition leaf
        (e.g. v > 10): evaluated per owned shard against LOCAL planes with
        the shared condition plan — per-node clamping against local bit
        depth is exact for local data, since a node's values were written
        within its own depth. Defensive like _local_block."""
        from ..exec.bsicond import condition_from_key

        seg_len = int(step["seg_len"])
        my_shards = step["segments"].get(self.cluster.local_id, [])
        local = np.zeros((seg_len, WORDS_PER_ROW), dtype=np.uint32)
        try:
            call = Call("Row", args={
                field_name: condition_from_key(op, vals)})
            ex = self._local_executor()
            for j, shard in enumerate(my_shards[:seg_len]):
                plane = ex.bitmap_call_shard(idx, call, shard)
                if plane is not None:
                    local[j] = np.asarray(plane)
        except Exception as e:
            self.logger.printf(
                "spmd: local condition gather failed (%s %s %s): %s — "
                "contributing zero planes", field_name, op, vals, e)
        return local

    def _local_executor(self):
        """Executor for per-shard condition-leaf evaluation. The API
        shares its serving executor here (server/api.py) so no second
        evaluator is built; standalone/test construction falls back to a
        lazy private instance."""
        if self._local_exec is None:
            from ..exec.executor import Executor

            self._local_exec = Executor(self.holder)
        return self._local_exec

    def _local_leaf_block(self, idx, step, entry):
        """This process's [seg_len, W] host block for one wire leaf
        (defensive: zeros for anything missing locally)."""
        if entry[0] == "bsicond":
            _, field_name, op, vals = entry
            return self._local_cond_block(idx, step, field_name, op, vals)
        if entry[0] == "timerow":
            # union across the quantum-view cover, host-side (each
            # view's block is defensive zeros when absent locally)
            _, field_name, row_id, views = entry
            local = np.zeros((int(step["seg_len"]), WORDS_PER_ROW),
                             dtype=np.uint32)
            for view_name in views:
                local |= self._local_block(
                    idx, step, field_name, int(row_id),
                    view_name=view_name)
            return local
        _, field_name, row_id = entry
        return self._local_block(idx, step, field_name, int(row_id))

    def _leaf_array(self, idx, step, entry, sharding, global_shape):
        """ONE globally-sharded leaf array, mesh-cache aware.

        serve == on: probe the mesh-resident cache first — a hit returns
        the device-placed global-array handle without touching host
        fragments or re-uploading (the tentpole win). Per-process cache
        divergence is safe: this handle only feeds this process's
        addressable shards (meshstacks module doc).
        serve == shadow: legacy gather serves; the fresh block feeds the
        cache's divergence detector.
        serve == off/http: byte-identical legacy path, cache untouched.
        """
        import jax

        from .meshstacks import entry_key

        seg_len = int(step["seg_len"])
        my_shards = tuple(step["segments"].get(self.cluster.local_id, []))
        key = (step["index"], entry_key(entry), seg_len, my_shards)
        gens = None
        if self.serve_mode in ("on", "shadow"):
            gens = self.mesh_cache.gens(idx, entry, my_shards)
        if self.serve_mode == "on" and gens is not None:
            arr = self.mesh_cache.get(key, gens)
            if arr is not None:
                return arr
        local = self._local_leaf_block(idx, step, entry)
        arr = jax.make_array_from_process_local_data(
            sharding, local, global_shape=global_shape)
        if gens is not None:
            if self.serve_mode == "on":
                self.mesh_cache.put(key, gens, arr, local)
            else:
                self.mesh_cache.shadow_probe(key, gens, local)
        return arr

    def _leaf_arrays(self, idx, step):
        """Globally-sharded [S, W] arrays for a step's plan leaves
        (tagged wire entries: ["row", f, r] | ["bsicond", f, op, vals] |
        ["timerow", f, r, views])."""
        n_proc = self._num_processes()
        seg_len = int(step["seg_len"])
        sharding = self._global_sharding()
        global_shape = (n_proc * seg_len, WORDS_PER_ROW)
        arrays = [self._leaf_array(idx, step, entry, sharding,
                                   global_shape)
                  for entry in step.get("leaves", [])]
        return arrays, global_shape

    def _run_count_step(self, idx, step):
        import jax

        from ..ops.bitplane import combine_hi_lo

        sig = sig_from_wire(step["sig"])
        arrays, _ = self._leaf_arrays(idx, step)
        self._mark_phase("stack_gather")
        fn = self._count_fn(sig, len(arrays))
        out = fn(*arrays)
        self._mark_phase("device_enter")  # compile lands here (cold key)
        jax.block_until_ready(out)
        self._mark_phase("psum")
        self.steps_run += 1
        hi, lo = out
        result = int(combine_hi_lo(hi, lo))
        self._mark_phase("result_fetch")
        return result

    def _run_count_batch_step(self, idx, step):
        """K Count plans in ONE collective step: gather every plan's
        leaf arrays (the mesh cache dedups the bucket-padding repeats and
        shared leaves across plans), evaluate all trees in one jitted
        program — same-signature plans vmapped over a stacked leaf axis —
        and all-reduce all K per-shard popcounts together. One
        announcement, one program, one psum for the whole batch."""
        import jax

        from ..ops.bitplane import combine_hi_lo

        sigs = []
        arities = []
        all_arrays = []
        for plan in step["plans"]:
            sigs.append(sig_from_wire(plan["sig"]))
            sub = dict(step)
            sub["leaves"] = plan["leaves"]
            arrays, _ = self._leaf_arrays(idx, sub)
            arities.append(len(arrays))
            all_arrays.extend(arrays)
        self._mark_phase("stack_gather")
        fn = self._count_batch_fn(tuple(sigs), tuple(arities))
        out = fn(*all_arrays)
        self._mark_phase("device_enter")
        jax.block_until_ready(out)
        self._mark_phase("psum")
        self.steps_run += 1
        self.batch_steps += 1
        hilo = np.asarray(out)  # [2, K]: one host transfer
        result = [int(combine_hi_lo(int(h), int(l)))
                  for h, l in zip(hilo[0], hilo[1])]
        self._mark_phase("result_fetch")
        return result

    def _bsi_arrays(self, idx, step):
        """Globally-sharded (planes [D,S,W], sign [S,W], exists [S,W]) for
        a sum/minmax step. Zero-extension to the cluster-wide max depth is
        exact: absent magnitude planes contribute 0 to every popcount.
        A write racing this step can grow the local bit_depth past the
        validated step depth; the racing value's planes above step depth
        are simply not read this query — an ordinary read/write race
        outcome, not corruption."""
        import jax

        from ..core.fragment import (
            BSI_EXISTS_BIT,
            BSI_OFFSET_BIT,
            BSI_SIGN_BIT,
        )

        # at least one magnitude plane so the [D,S,W] stack is never empty
        # (an all-zero plane is exact: it adds 0 to every popcount)
        depth = max(1, int(step["depth"]))
        bsi_view = VIEW_BSI_GROUP_PREFIX + step["field"]
        n_proc = self._num_processes()
        seg_len = int(step["seg_len"])
        plane_sh = self._global_sharding(shard_axis=1, ndim=3)
        row_sh = self._global_sharding()
        row_shape = (n_proc * seg_len, WORDS_PER_ROW)

        local_planes = np.stack([
            self._local_block(idx, step, step["field"],
                              BSI_OFFSET_BIT + i, view_name=bsi_view)
            for i in range(depth)])
        planes = jax.make_array_from_process_local_data(
            plane_sh, local_planes,
            global_shape=(depth,) + row_shape)
        sign = jax.make_array_from_process_local_data(
            row_sh, self._local_block(idx, step, step["field"],
                                      BSI_SIGN_BIT, view_name=bsi_view),
            global_shape=row_shape)
        exists = jax.make_array_from_process_local_data(
            row_sh, self._local_block(idx, step, step["field"],
                                      BSI_EXISTS_BIT, view_name=bsi_view),
            global_shape=row_shape)
        return planes, sign, exists

    def _run_sum_step(self, idx, step):
        """BSI Sum over globally-sharded bit planes (reference per-shard
        algorithm: fragment.sum fragment.go:1068; the cross-node merge is
        the all-reduce XLA inserts over the [*, shards, words] arrays)."""
        import jax

        from ..ops.bitplane import combine_hi_lo

        depth = int(step["depth"])
        planes, sign, exists = self._bsi_arrays(idx, step)
        sig = sig_from_wire(step["sig"])
        stacks, _ = self._leaf_arrays(idx, step)
        self._mark_phase("stack_gather")

        fn = self._sum_fn(sig, len(stacks))
        out = fn(planes, sign, exists, *stacks)
        self._mark_phase("device_enter")
        jax.block_until_ready(out)
        self._mark_phase("psum")
        res = [np.asarray(r) for r in out]
        p_hi, p_lo, n_hi, n_lo, c_hi, c_lo = res
        total = 0
        for i in range(depth):
            total += combine_hi_lo(p_hi[i], p_lo[i]) << i
            total -= combine_hi_lo(n_hi[i], n_lo[i]) << i
        self.steps_run += 1
        result = total, int(combine_hi_lo(c_hi, c_lo))
        self._mark_phase("result_fetch")
        return result

    def _run_minmax_step(self, idx, step):
        """Min/Max narrowing walk over globally-sharded planes; the
        replicated outputs (empty, use_neg, bits, count) decode on the
        coordinator (reference sign rules: fragment.go:1110-1227)."""
        import jax

        from ..ops.bitplane import combine_hi_lo

        planes, sign, exists = self._bsi_arrays(idx, step)
        sig = sig_from_wire(step["sig"])
        stacks, _ = self._leaf_arrays(idx, step)
        self._mark_phase("stack_gather")

        fn = self._minmax_fn(sig, len(stacks), bool(step["is_max"]))
        out = fn(planes, sign, exists, *stacks)
        self._mark_phase("device_enter")
        jax.block_until_ready(out)
        self._mark_phase("psum")
        empty, use_neg, bits, c_hi, c_lo = out
        self.steps_run += 1
        result = (bool(empty), bool(use_neg),
                  [int(b) for b in np.asarray(bits)],
                  int(combine_hi_lo(c_hi, c_lo)))
        self._mark_phase("result_fetch")
        return result

    def _run_topn_step(self, idx, step):
        """Candidate-row counts over a globally-sharded [rows, shards,
        words] stack (reference per-shard scan: fragment.top
        fragment.go:1570; the heap merge becomes the all-reduce)."""
        import jax

        from ..ops.bitplane import combine_hi_lo

        rows = [int(r) for r in step["rows"]]
        n_proc = self._num_processes()
        seg_len = int(step["seg_len"])
        rows_sh = self._global_sharding(shard_axis=1, ndim=3)
        row_shape = (n_proc * seg_len, WORDS_PER_ROW)

        local = np.stack([
            self._local_block(idx, step, step["field"], r) for r in rows])
        stack = jax.make_array_from_process_local_data(
            rows_sh, local, global_shape=(len(rows),) + row_shape)

        sig = sig_from_wire(step["sig"])
        stacks, _ = self._leaf_arrays(idx, step)
        self._mark_phase("stack_gather")

        fn = self._topn_fn(sig, len(stacks))
        out = fn(stack, *stacks)
        self._mark_phase("device_enter")
        jax.block_until_ready(out)
        self._mark_phase("psum")
        hi, lo = out
        self.steps_run += 1
        totals = combine_hi_lo(hi, lo)
        result = [int(t) for t in totals]
        self._mark_phase("result_fetch")
        return result

    def _run_groupby_step(self, idx, step):
        """Cross-product counts over per-field globally-sharded [rows,
        shards, words] stacks: ONE jitted program gathers each cell's row
        combination, intersects, popcounts, and all-reduces across
        processes (reference per-(shard×cell) scan: executeGroupByShard
        executor.go:1238)."""
        import jax

        from ..ops.bitplane import combine_hi_lo

        n_proc = self._num_processes()
        seg_len = int(step["seg_len"])
        rows_sh = self._global_sharding(shard_axis=1, ndim=3)
        row_shape = (n_proc * seg_len, WORDS_PER_ROW)

        field_stacks = []
        lens = []
        for field_name, rows in zip(step["fields"], step["rows"]):
            rows = [int(r) for r in rows]
            lens.append(len(rows))
            local = np.stack([
                self._local_block(idx, step, field_name, r) for r in rows])
            field_stacks.append(jax.make_array_from_process_local_data(
                rows_sh, local, global_shape=(len(rows),) + row_shape))

        sig = sig_from_wire(step["sig"])
        stacks, _ = self._leaf_arrays(idx, step)
        self._mark_phase("stack_gather")

        fn = self._groupby_fn(tuple(lens), sig, len(stacks))
        out = fn(*field_stacks, *stacks)
        self._mark_phase("device_enter")
        jax.block_until_ready(out)
        self._mark_phase("psum")
        hi, lo = out
        self.steps_run += 1
        totals = combine_hi_lo(hi, lo)
        result = [int(t) for t in totals]
        self._mark_phase("result_fetch")
        return result

    # -- compiled programs ----------------------------------------------------

    def _get_fn(self, key, build):
        fn = self._fns.get(key)
        if fn is None:
            fn = build()
            self._fns[key] = fn
            while len(self._fns) > self.MAX_FNS:
                self._fns.popitem(last=False)
        else:
            self._fns.move_to_end(key)
        return fn

    def _count_fn(self, sig, arity):
        import jax
        import jax.numpy as jnp

        from ..exec.stacked import StackedEvaluator
        from ..ops.bitplane import hi_lo

        def build():
            @jax.jit
            def fn(*stacks):
                acc = StackedEvaluator._tree_eval(sig, stacks)
                per_shard = jnp.sum(
                    jax.lax.population_count(acc).astype(jnp.int32),
                    axis=-1)
                return hi_lo(per_shard)

            return fn

        return self._get_fn(("count", sig, arity), build)

    def _count_batch_fn(self, sigs, arities):
        """K Count trees in one program. Runs of IDENTICAL (sig, arity)
        — the common case after bucket padding repeats plans[0] — are
        stacked on a new leading axis and evaluated with ONE vmapped
        tree walk (PR-9's batching shape, lifted to the collective
        plane); distinct signatures evaluate inline in the same trace.
        Either way XLA sees a single program and inserts ONE
        cross-process reduce for all K outputs. Returns a single
        stacked [2, K] array — row 0 the hi halves, row 1 the lo
        halves, in plan order — so the warm path costs one reduce pair
        and one host fetch total."""
        import jax
        import jax.numpy as jnp

        from ..exec.stacked import tree_eval
        from ..ops.bitplane import hi_lo

        def build():
            # group plan positions by identical (sig, arity) runs
            groups = OrderedDict()
            for pos, sa in enumerate(zip(sigs, arities)):
                groups.setdefault(sa, []).append(pos)
            offsets = []
            off = 0
            for a in arities:
                offsets.append(off)
                off += a

            @jax.jit
            def fn(*stacks):
                def count(sig, leaf_stacks):
                    acc = tree_eval(sig, leaf_stacks)
                    return jnp.sum(
                        jax.lax.population_count(acc).astype(jnp.int32),
                        axis=-1)

                per_plan = [None] * len(sigs)
                for (sig, arity), positions in groups.items():
                    if len(positions) > 1 and arity > 0:
                        # [G, S, W] per leaf slot -> one vmapped walk
                        batched = [
                            jnp.stack([stacks[offsets[p] + i]
                                       for p in positions])
                            for i in range(arity)]
                        per_shard = jax.vmap(
                            lambda *ls, _sig=sig: count(_sig, ls))(
                                *batched)
                        for g, p in enumerate(positions):
                            per_plan[p] = per_shard[g]
                    else:
                        for p in positions:
                            ls = stacks[offsets[p]:offsets[p] + arity]
                            per_plan[p] = count(sig, ls)
                # ONE reduce + ONE fetch for the whole batch: per-plan
                # hi_lo in a Python loop would emit 2K separate
                # cross-process all-reduces (each pays a full gloo
                # sync); stacking the [S] per-shard counts to [K, S]
                # first makes the hi/lo sums a single pair of
                # collectives regardless of K, and stacking hi over lo
                # makes the host transfer a single [2, K] array
                return jnp.stack(hi_lo(jnp.stack(per_plan), axis=-1))

            return fn

        return self._get_fn(("count_batch", sigs, arities), build)

    def _sum_fn(self, sig, arity):
        """(planes [D,S,W], sign, exists, *filter leaves) -> per-plane
        pos/neg popcounts + consider count as (hi, lo) int32 pairs, with
        XLA inserting the cross-process reduce."""
        import jax
        import jax.numpy as jnp

        from ..exec.stacked import StackedEvaluator
        from ..ops.bitplane import hi_lo

        def build():
            @jax.jit
            def fn(planes, sign, exists, *stacks):
                consider = exists
                if sig is not None:
                    consider = consider & StackedEvaluator._tree_eval(
                        sig, stacks)
                pos = consider & ~sign
                neg = consider & sign
                pc = jnp.sum(jax.lax.population_count(
                    planes & pos[None]).astype(jnp.int32), axis=-1)
                nc = jnp.sum(jax.lax.population_count(
                    planes & neg[None]).astype(jnp.int32), axis=-1)
                cc = jnp.sum(jax.lax.population_count(
                    consider).astype(jnp.int32), axis=-1)
                return (*hi_lo(pc, axis=-1), *hi_lo(nc, axis=-1),
                        *hi_lo(cc))

            return fn

        return self._get_fn(("sum", sig, arity), build)

    def _minmax_fn(self, sig, arity, is_max):
        """Global Min/Max in one program over globally-sharded planes —
        both sign-branch walks computed branchlessly, selected per the
        reference's rules (same kernel shape as the local stacked
        evaluator's _minmax_fn; its any() reductions become collectives
        here)."""
        import jax
        import jax.numpy as jnp

        from ..exec.stacked import StackedEvaluator
        from ..ops import bsi as bsi_ops
        from ..ops.bitplane import hi_lo

        def build():
            @jax.jit
            def fn(planes, sign, exists, *stacks):
                consider = exists
                if sig is not None:
                    consider = consider & StackedEvaluator._tree_eval(
                        sig, stacks)
                pos = consider & ~sign
                neg = consider & sign
                has_pos = jnp.any(pos != 0)
                has_neg = jnp.any(neg != 0)
                empty = ~(has_pos | has_neg)
                if is_max:
                    b_pos, f_pos = bsi_ops.max_unsigned(planes, pos)
                    b_neg, f_neg = bsi_ops.min_unsigned(planes, neg)
                    use_neg = ~has_pos
                else:
                    b_neg, f_neg = bsi_ops.max_unsigned(planes, neg)
                    b_pos, f_pos = bsi_ops.min_unsigned(planes, pos)
                    use_neg = has_neg
                bits = jnp.where(use_neg, b_neg, b_pos)
                final = jnp.where(use_neg, f_neg, f_pos)
                per_shard = jnp.sum(
                    jax.lax.population_count(final).astype(jnp.int32),
                    axis=-1)
                return (empty, use_neg, bits, *hi_lo(per_shard))

            return fn

        return self._get_fn(("minmax", sig, arity, is_max), build)

    def _topn_fn(self, sig, arity):
        """(rows [R,S,W], *filter leaves) -> per-row (hi [R], lo [R])
        counts of row ∩ filter, all-reduced across processes."""
        import jax
        import jax.numpy as jnp

        from ..exec.stacked import StackedEvaluator
        from ..ops.bitplane import hi_lo

        def build():
            @jax.jit
            def fn(stack, *stacks):
                x = stack
                if sig is not None:
                    filt = StackedEvaluator._tree_eval(sig, stacks)
                    x = x & filt[None]
                per_shard = jnp.sum(
                    jax.lax.population_count(x).astype(jnp.int32),
                    axis=-1)
                return hi_lo(per_shard, axis=-1)

            return fn

        return self._get_fn(("topn", sig, arity), build)

    def _groupby_fn(self, lens, sig, arity):
        """(field stacks [R_i,S,W]..., *filter leaves) -> per-cell
        (hi [C], lo [C]) counts of the full cross-product. The cell index
        arrays derive from `lens` alone INSIDE the trace (meshgrid of
        iotas), so every process compiles the identical program with no
        host-data divergence; cell order = itertools.product order
        (meshgrid indexing='ij')."""
        import jax
        import jax.numpy as jnp

        from ..exec.stacked import StackedEvaluator
        from ..ops.bitplane import hi_lo

        def build():
            @jax.jit
            def fn(*arrays):
                field_stacks = arrays[:len(lens)]
                stacks = arrays[len(lens):]
                grids = jnp.meshgrid(
                    *[jnp.arange(n) for n in lens], indexing="ij")
                idxs = [g.reshape(-1) for g in grids]
                x = field_stacks[0][idxs[0]]  # [C, S, W]
                for s, ix in zip(field_stacks[1:], idxs[1:]):
                    x = x & s[ix]
                if sig is not None:
                    filt = StackedEvaluator._tree_eval(sig, stacks)
                    x = x & filt[None]
                per_shard = jnp.sum(
                    jax.lax.population_count(x).astype(jnp.int32),
                    axis=-1)
                return hi_lo(per_shard, axis=-1)

            return fn

        return self._get_fn(("groupby", lens, sig, arity), build)

    def stats(self):
        return {"steps": self.steps_run,
                "initialized": type(self)._initialized,
                "serve_mode": self.serve_mode,
                "validations": self.validations,
                "validations_skipped": self.validations_skipped,
                "forwarded": self.forwarded,
                "forward_errors": self.forward_errors,
                "fallbacks": self.fallbacks,
                "batch_steps": self.batch_steps,
                "batched_queries": self.batched_queries,
                "fused_steps": self.fused_steps,
                "fused_queries": self.fused_queries}

    def debug_snapshot(self):
        """GET /debug/spmd: serve mode + mesh shape, the step-lifecycle
        counters the wedge classifier reads (announced vs entered vs
        exited per node), stream state, mesh-cache stats, and the HTTP
        data-plane byte counter (zero while collectives serve)."""
        from ..server import client as client_mod

        with self._stream_cond:
            stream = {
                "next": self._stream_next,
                "queued": len(self._stream_queue),
                "errors": self.stream_errors,
                "resyncs": self.stream_resyncs,
            }
        try:
            mesh = self.mesh_shape()
        except Exception:  # backend not initialized yet
            mesh = None
        return {
            "serve_mode": self.serve_mode,
            "initialized": type(self)._initialized,
            "mesh": mesh,
            "steps": {
                "run": self.steps_run,
                "announced": self.steps_announced,
                "entered": self.steps_entered,
                "exited": self.steps_exited,
                "last_seq": self.last_seq,
                "batch": self.batch_steps,
                "fused": self.fused_steps,
            },
            "queries": {
                "batched": self.batched_queries,
                "fused": self.fused_queries,
                "forwarded": self.forwarded,
                "fallbacks": self.fallbacks,
            },
            "stream": stream,
            "stream_gap_timeout": self.STREAM_GAP_TIMEOUT,
            "observatory": self.observatory_stats(),
            "mesh_cache": self.mesh_cache.stats(),
            "http_data_plane_bytes": client_mod.data_plane_bytes(),
        }

    # -- mesh observatory (read side) -----------------------------------------

    def observatory_stats(self):
        """Compact observatory counters (no ring contents): per-phase
        totals, pipeline occupancy, gap + straggler tallies."""
        with self._obs_lock:
            totals = {p: {"count": c, "seconds": round(s, 6)}
                      for p, (c, s) in self._phase_totals.items()}
            ring = len(self._step_ring)
        return {
            "steps_recorded": ring,
            "ring_size": self.STEP_RING_SIZE,
            "phase_totals": totals,
            "occupancy": self.occupancy(),
            "straggler_flags": self.straggler_flags_total,
        }

    def occupancy(self):
        """Step-stream pipeline occupancy: queue depth, how far this
        node's execution lags the highest announced seq it has seen, and
        cumulative time the runner spent blocked on sequence gaps."""
        with self._stream_cond:
            queued = len(self._stream_queue)
            head = max(self._stream_queue) if self._stream_queue else None
            nxt = self._stream_next
        return {
            "queue_depth": queued,
            "seq_lag": max(0, (head or self.last_seq) - self.last_seq),
            "stream_next": nxt,
            "last_seq": self.last_seq,
            "gap_onsets": self.gap_onsets,
            "gap_stall_seconds": round(self.gap_stall_seconds, 6),
        }

    def register_gauges(self):
        """Scrape-time pipeline-occupancy gauges on the process-global
        stats client (called once from cli.cmd_server — NOT __init__, so
        short-lived test planes never leak gauge closures)."""
        from ..utils.stats import global_stats

        if not hasattr(global_stats, "gauge_fn"):
            return
        global_stats.gauge_fn(
            "spmd_stream_queue_depth",
            lambda: len(self._stream_queue))
        global_stats.gauge_fn(
            "spmd_stream_seq_lag",
            lambda: max(0, (max(self._stream_queue)
                            if self._stream_queue else self.last_seq)
                        - self.last_seq))
        global_stats.gauge_fn(
            "spmd_stream_gap_stall_seconds",
            lambda: self.gap_stall_seconds)

    def _local_node_id(self):
        if self.cluster is not None:
            return self.cluster.local_id
        return "local"

    def steps_local(self, seq=None, limit=None):
        """This node's slice of the step timeline (what the coordinator
        fans out for with ?local=true): recent step records with
        per-phase walls, stamped with this node's wall clock so the
        caller can skew-correct from the RPC envelope."""
        with self._obs_lock:
            steps = list(self._step_ring)
        if seq is not None:
            steps = [r for r in steps if r["seq"] == seq]
        elif limit is not None and limit > 0:
            steps = steps[-int(limit):]
        return {
            "node": self._local_node_id(),
            "time": time.time(),
            "steps": steps,
            "occupancy": self.occupancy(),
        }

    def steps_timeline(self, seq=None, limit=32, local_only=False):
        """GET /debug/spmd/steps[/{seq}]: the cross-node step timeline.

        Fans out to mesh peers for their local slices (?local=true, the
        PR-17 debug_trace pattern), estimates each peer's clock offset
        from the RPC envelope (envelope_skew — same symmetric-delay
        assumption as tracing.estimate_skew), shifts every peer's step
        starts onto this node's clock, and merges per-seq into one
        timeline with per-phase straggler attribution. Straggler flags
        are edge-triggered: each (seq, node, phase) counts toward
        spmd_step_straggler_total{node,phase} and fires the
        spmd.straggler flightrec event exactly once, no matter how often
        the timeline is scraped."""
        local_id = self._local_node_id()
        payloads = {local_id: (self.steps_local(seq=seq, limit=limit),
                               0.0)}
        if not local_only and self.cluster is not None \
                and len(self.cluster.nodes) > 1:
            from ..utils import tracing

            with tracing.with_span(None):  # debug plumbing: never trace
                for node in self.cluster.peers():
                    try:
                        client = self.client_factory(node.uri)
                        t_send = time.time()
                        remote = client.debug_spmd_steps(seq=seq,
                                                         limit=limit)
                        t_recv = time.time()
                    except Exception:  # best-effort: peer down/old
                        continue
                    if not remote or remote.get("steps") is None:
                        continue
                    theta = envelope_skew(
                        t_send, t_recv,
                        float(remote.get("time") or t_recv))
                    payloads[remote.get("node", node.id)] = (remote,
                                                             theta)
        merged = {}
        for node, (payload, theta) in payloads.items():
            for rec in payload.get("steps", []):
                s = merged.setdefault(rec["seq"], {
                    "seq": rec["seq"],
                    "kind": rec.get("kind", "count"),
                    "index": rec.get("index", ""),
                    "peers": {},
                })
                if rec.get("trace") and not s.get("trace"):
                    s["trace"] = rec["trace"]
                s["peers"][node] = {
                    # peer wall-clock start shifted onto OUR clock
                    "start": round(rec["start"] - theta, 6),
                    "wall_seconds": rec["wall_seconds"],
                    "phases": rec.get("phases", {}),
                    "ok": rec.get("ok", True),
                }
        steps = [merged[k] for k in sorted(merged)]
        for s in steps:
            s["stragglers"] = attribute_stragglers(
                {n: p["phases"] for n, p in s["peers"].items()},
                self.STRAGGLER_FACTOR, self.STRAGGLER_NOISE_FLOOR)
            self._flag_stragglers(s["seq"], s["stragglers"])
        return {
            "node": local_id,
            "skew_seconds": {n: round(th, 6)
                             for n, (_, th) in payloads.items()},
            "straggler_factor": self.STRAGGLER_FACTOR,
            "noise_floor_seconds": self.STRAGGLER_NOISE_FLOOR,
            "steps": steps,
        }

    def _flag_stragglers(self, seq, flags):
        """Edge-triggered straggler accounting (see steps_timeline)."""
        if not flags:
            return
        from ..utils import flightrec
        from ..utils.stats import global_stats

        for flag in flags:
            key = (seq, flag["node"], flag["phase"])
            with self._obs_lock:
                if key in self._straggler_flags:
                    continue
                self._straggler_flags[key] = 1
                while len(self._straggler_flags) \
                        > self.STRAGGLER_FLAGS_MAX:
                    self._straggler_flags.popitem(last=False)
                self.straggler_flags_total += 1
            try:
                global_stats.count(
                    "spmd_step_straggler_total",
                    tags={"node": str(flag["node"]),
                          "phase": flag["phase"]})
            except Exception:  # noqa: BLE001
                pass
            flightrec.record(
                "spmd.straggler", seq=seq, node=str(flag["node"]),
                phase=flag["phase"], ratio=flag.get("ratio") or 0,
                seconds=flag["seconds"])

    def summary(self):
        """Compact roll-up for /status?observability=true: serve mode,
        step-lifecycle counters, stream health, mesh-cache stats."""
        occ = self.occupancy()
        return {
            "serve_mode": self.serve_mode,
            "steps": {
                "announced": self.steps_announced,
                "entered": self.steps_entered,
                "exited": self.steps_exited,
                "last_seq": self.last_seq,
                "batch": self.batch_steps,
                "fused": self.fused_steps,
            },
            "queries": {
                "batched": self.batched_queries,
                "fused": self.fused_queries,
                "forwarded": self.forwarded,
                "fallbacks": self.fallbacks,
            },
            "stream": {
                "errors": self.stream_errors,
                "resyncs": self.stream_resyncs,
                "queue_depth": occ["queue_depth"],
                "seq_lag": occ["seq_lag"],
                "gap_onsets": occ["gap_onsets"],
                "gap_stall_seconds": occ["gap_stall_seconds"],
            },
            "straggler_flags": self.straggler_flags_total,
            "mesh_cache": self.mesh_cache.stats(),
        }

    def incident_snapshot(self):
        """Postmortem-bundle payload (utils/incident.py `spmd`
        collector): the full debug snapshot plus this node's step ring
        and, best-effort, the merged cross-node timeline — captured
        while a collective_stall is still open, so the bundle shows
        WHERE every peer was when the stream wedged."""
        snap = self.debug_snapshot()
        snap["steps_local"] = self.steps_local(limit=64)
        try:
            snap["timeline"] = self.steps_timeline(limit=16)
        except Exception as e:  # noqa: BLE001 — never fail the bundle
            snap["timeline_error"] = str(e)
        return snap


class SpmdBatchRunner:
    """PR-9 coalescer adapter for cluster coordinators (serve == on):
    presents Executor.launch_batch/resolve_batch's (handle, state) ->
    [(results, error, batch, fingerprint)] contract, but resolves
    eligible Count batches as ONE collective step
    (SpmdDataPlane.maybe_execute_batch) instead of local vmapped
    dispatches — one announcement, one program, one psum for K queries.
    Launch is deliberately cheap: the collective IS the fused dispatch
    (there is no device enqueue to overlap), so the coalescer's
    double-buffering degenerates to serial resolution without waste.
    Anything ineligible or declined re-runs on the ordinary cluster
    path per member (per-query error isolation, PR-9 contract)."""

    #: what server.api._try_coalesce admits on a cluster coordinator —
    #: only Count merges collectively; other batchable families stay on
    #: the per-query cluster path
    BATCHABLE_CALLS = frozenset(("Count",))

    def __init__(self, api):
        self.api = api
        self.spmd = api.spmd

    def launch_batch(self, index_name, queries, shards=None,
                     options=None):
        return None, (index_name, list(queries))

    def resolve_batch(self, handle, state):
        import copy
        import time as _time

        from ..exec.executor import validate_uint_args
        from ..exec.stacked import BATCH_BUCKETS
        from ..exec.translate import translate_calls, translate_results
        from ..utils import workload as workload_mod

        index_name, queries = state
        executor = self.api.executor
        idx = executor.holder.index(index_name)
        entries = []
        for query in queries:
            # e["raw"] is the untranslated form every fallback must
            # re-execute from — translation mutates the call tree in
            # place and is not idempotent (exec.executor.launch_batch)
            e = {"query": query, "raw": query, "error": None,
                 "eligible": False, "out": None}
            entries.append(e)
            if idx is None:
                e["error"] = SpmdError(f"index not found: {index_name}")
                continue
            try:
                if isinstance(query, str):
                    query = e["query"] = parse(query)
                calls = query.calls
                if len(calls) == 1 and calls[0].name == "Count" \
                        and len(calls[0].children) == 1 \
                        and not calls[0].writes():
                    if not isinstance(e["raw"], str):
                        e["raw"] = copy.deepcopy(query)
                    translate_calls(idx, query.calls)
                    validate_uint_args(calls[0])
                    e["eligible"] = True
            except Exception as exc:  # noqa: BLE001 — per-query isolation
                e["error"] = exc
        eligible = [e for e in entries
                    if e["eligible"] and e["error"] is None]
        if eligible:
            cluster_shards = executor.cluster_shards(idx)
            cap = BATCH_BUCKETS[-1]
            for i in range(0, len(eligible), cap):
                chunk = eligible[i:i + cap]
                calls = [e["query"].calls[0] for e in chunk]
                t0 = _time.perf_counter()
                used, counts = self.spmd.maybe_execute_batch(
                    idx, calls, cluster_shards)
                if not used:
                    continue  # whole chunk re-runs per-query below
                wall = _time.perf_counter() - t0
                k = len(chunk)
                for j, (e, count) in enumerate(zip(chunk, counts)):
                    try:
                        wctx = workload_mod.begin_query(
                            idx.name, e["query"])
                        wctx.strategies.append("Count=spmd-collective")
                        workload_mod.note_batch(k)
                        # charge the step's one dispatch to exactly ONE
                        # member (exec.executor.resolve_batch rule)
                        workload_mod.end_query(wctx, wall / k, deltas={
                            "dispatches": 1 if j == 0 else 0,
                            "cache_hits": 0, "cache_misses": 0,
                            "bytes_materialized": 0})
                        results = translate_results(
                            idx, e["query"].calls, [int(count)])
                        e["out"] = (results, None, k, wctx.fingerprint)
                    except Exception as exc:  # noqa: BLE001
                        e["out"] = (None, exc, 0, None)
        outs = []
        for e in entries:
            if e["out"] is not None:
                outs.append(e["out"])
            elif e["error"] is not None:
                outs.append((None, e["error"], 0, None))
            else:
                try:
                    results = executor.execute(index_name, e["raw"])
                    outs.append((results, None, 0,
                                 workload_mod.last_fingerprint()))
                except Exception as exc:  # noqa: BLE001
                    outs.append((None, exc, 0, None))
        return outs
