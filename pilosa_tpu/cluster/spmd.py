"""Pod-scale SPMD data plane: cross-node query merge over collectives.

The reference merges cross-node partial results over HTTP/protobuf
(executor.remoteExec executor.go:2414, http/client.go:268) — the
coordinator POSTs per-node shard lists and sums JSON/proto responses. In
SPMD mode that data plane is replaced by the accelerator fabric: every
server process joins ONE global JAX distributed system
(`jax.distributed.initialize` — gloo across CPU hosts, ICI/DCN collectives
on TPU pods), each query leaf materializes as a single globally-sharded
[shards, words] array whose per-process blocks come from that node's own
fragments, and one jit-compiled count program runs on every process in
lockstep — XLA inserts the cross-process all-reduce, so counts merge as a
psum riding the fabric instead of JSON over REST.

HTTP remains the CONTROL plane (SURVEY §2 "distributed communication
backend": control over DCN, data merge over ICI): the cluster coordinator
announces each step via POST /internal/spmd/step, every process (including
the coordinator) executes the identical program, and the replicated scalar
result is read locally — no result bytes cross HTTP.

Execution model (multi-controller SPMD):
- Only the cluster coordinator node initiates steps, and it serializes
  them under a local lock; peer processes execute steps from their HTTP
  handler thread under the same per-process lock. With a single initiator
  this yields an identical step order on every process — the requirement
  for collectives to rendezvous correctly.
- Queries arriving at non-coordinator nodes (and calls the stacked
  signature can't express) use the HTTP merge path unchanged; SPMD is a
  fast path, never a correctness dependency.
- Steps are gated on every node being READY: a process that never joins a
  collective would hang the others, so degraded clusters fall back to the
  HTTP path (which has per-replica retry).

Count totals use the framework-wide (hi, lo) int32 split reduce
(ops.bitplane.hi_lo) — exact past 2^31 bits without x64.
"""

import threading

import numpy as np

from ..pql import call_to_pql, parse
from ..shardwidth import WORDS_PER_ROW


class SpmdError(Exception):
    pass


class SpmdDataPlane:
    #: process-wide init guard (jax.distributed.initialize is once-only)
    _initialized = False

    @classmethod
    def initialize(cls, coordinator_address, num_processes, process_id):
        """Join the global JAX distributed system. MUST run before any JAX
        backend initializes in this process (same constraint as platform
        selection; see cli._honor_jax_platforms_env)."""
        if cls._initialized:
            return
        import jax

        jax.distributed.initialize(
            coordinator_address=coordinator_address,
            num_processes=num_processes,
            process_id=process_id)
        cls._initialized = True

    #: seconds a step announcement may block (first-query jit compile +
    #: collective rendezvous on a cold pod can far exceed the default 30s)
    STEP_TIMEOUT = 300
    #: seconds for the cheap pre-flight validation round
    VALIDATE_TIMEOUT = 5

    def __init__(self, holder, cluster, client_factory):
        self.holder = holder
        self.cluster = cluster
        self.client_factory = client_factory
        self._lock = threading.Lock()  # one step at a time per process
        self._mesh = None
        self._fns = {}
        self._step_id = 0
        self.steps_run = 0  # observability: /internal/spmd/stats
        # The JAX process set is fixed at startup (initialize is
        # once-only); if the cluster later grows or shrinks, SPMD must
        # decline — new nodes are not mesh participants.
        self._boot_node_ids = tuple(sorted(n.id for n in cluster.nodes)) \
            if cluster is not None else ()

    # -- mesh ----------------------------------------------------------------

    def _global_sharding(self):
        """NamedSharding over the GLOBAL device list, process-major, so
        each process's addressable block is contiguous along the shard
        axis (what make_array_from_process_local_data fills)."""
        if self._mesh is None:
            import jax

            devices = sorted(jax.devices(),
                             key=lambda d: (d.process_index, d.id))
            self._mesh = jax.sharding.Mesh(np.array(devices), ("shards",))
        import jax

        return jax.sharding.NamedSharding(
            self._mesh, jax.sharding.PartitionSpec("shards"))

    def _local_device_count(self):
        import jax

        return len(jax.local_devices())

    def _num_processes(self):
        import jax

        return jax.process_count()

    # -- signature helper ----------------------------------------------------

    def _signature(self, idx, call):
        """Tree signature for SPMD coverage. Same shape rules as the
        stacked evaluator (shared walk: exec.stacked.tree_signature) but
        leaf checks consult only REPLICATED state (the schema): every
        process must derive the IDENTICAL signature or the collective
        desyncs, and local view/fragment existence differs per node (a node
        that owns no shards of a field simply contributes zero planes)."""
        from ..exec.stacked import tree_signature

        def leaf(idx, field_name, row_id, leaves):
            if idx.field(field_name) is None:
                return None
            key = (field_name, int(row_id))
            if key not in leaves:
                leaves[key] = len(leaves)
            return ("leaf", leaves[key])

        leaves = {}
        sig = tree_signature(idx, call, leaves, leaf)
        if sig is None or not leaves:
            return None
        ordered = sorted(leaves.items(), key=lambda kv: kv[1])
        return sig, [key for key, _ in ordered]

    # -- coordinator entry ---------------------------------------------------

    def try_count(self, idx, call, shards):
        """Count(call) merged over the global mesh, or None to fall back
        to the HTTP merge path."""
        cluster = self.cluster
        if cluster is None or len(cluster.nodes) < 2:
            return None
        coord = cluster.coordinator
        if coord is None or coord.id != cluster.local_id:
            return None  # single initiator keeps step order global
        from .node import NODE_STATE_READY

        if any(n.state != NODE_STATE_READY for n in cluster.nodes):
            return None  # a hung participant would stall the collective
        if tuple(sorted(n.id for n in cluster.nodes)) != self._boot_node_ids:
            return None  # membership changed since jax.distributed init
        if self._signature(idx, call) is None:
            return None

        by_node = cluster.shards_by_node(idx.name, list(shards))
        segments = {node.id: sorted(s) for node, s in by_node.items()}
        # every process contributes an equal-shaped block (zero planes for
        # nodes with fewer/no shards), padded to its device multiple
        dev_pp = self._local_device_count()
        longest = max((len(s) for s in segments.values()), default=0)
        seg_len = max(dev_pp, ((longest + dev_pp - 1) // dev_pp) * dev_pp)

        step = {
            "index": idx.name,
            "pql": call_to_pql(call),
            "segments": segments,
            "seg_len": seg_len,
            "dev_pp": dev_pp,
            "nodes": list(self._boot_node_ids),
        }

        # Pre-flight: every peer must confirm it can execute this step
        # (spmd enabled, schema in sync, matching device count) with a
        # short deadline, BEFORE anyone enters the collective — a peer
        # that never joins would stall the whole mesh with no way out.
        if not self._validate_on_peers(step):
            return None

        with self._lock:
            self._step_id += 1
            step["step"] = self._step_id
            errors = []

            def post(node):
                try:
                    client = self.client_factory(node.uri)
                    client.timeout = self.STEP_TIMEOUT
                    client.spmd_step(step)
                except Exception as e:  # surfaced after the collective
                    errors.append((node.id, e))

            threads = [threading.Thread(target=post, args=(n,))
                       for n in cluster.peers()]
            for t in threads:
                t.start()
            # join the collective ourselves — peers are inside run_step now
            result = self._run_step_locked(step)
            for t in threads:
                t.join()
        if errors:
            # We hold a replicated result, so every process DID join the
            # collective; these are post-collective transport errors (lost
            # responses). Log, don't fail the query.
            import sys

            print(f"spmd: post-collective peer errors (result kept): "
                  f"{errors}", file=sys.stderr)
        return result

    def _validate_on_peers(self, step):
        oks = []

        def probe(node):
            try:
                client = self.client_factory(node.uri)
                client.timeout = self.VALIDATE_TIMEOUT
                resp = client.spmd_validate(step)
                oks.append(bool(resp.get("ok")))
            except Exception:
                oks.append(False)

        threads = [threading.Thread(target=probe, args=(n,))
                   for n in self.cluster.peers()]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        return all(oks) and len(oks) == len(self.cluster.peers())

    def validate(self, step):
        """Peer-side pre-flight check (POST /internal/spmd/validate)."""
        idx = self.holder.index(step["index"])
        if idx is None:
            return {"ok": False, "reason": "index not found"}
        if self._signature(idx, parse(step["pql"]).calls[0]) is None:
            return {"ok": False, "reason": "tree not coverable"}
        if int(step["dev_pp"]) != self._local_device_count():
            return {"ok": False, "reason": "device count mismatch"}
        if tuple(step.get("nodes", ())) != self._boot_node_ids:
            return {"ok": False, "reason": "membership mismatch"}
        return {"ok": True}

    # -- step execution (every process) --------------------------------------

    def run_step(self, step):
        """HTTP-handler entry for peer processes."""
        with self._lock:
            return self._run_step_locked(step)

    def _run_step_locked(self, step):
        import jax

        idx = self.holder.index(step["index"])
        if idx is None:
            raise SpmdError(f"index not found: {step['index']}")
        call = parse(step["pql"]).calls[0]
        sig_leaves = self._signature(idx, call)
        if sig_leaves is None:
            raise SpmdError(
                f"step tree not coverable on this node: {step['pql']}")
        sig, leaf_keys = sig_leaves

        my_shards = step["segments"].get(self.cluster.local_id, [])
        seg_len = int(step["seg_len"])
        if len(my_shards) > seg_len:
            raise SpmdError("segment exceeds seg_len")
        n_proc = self._num_processes()
        sharding = self._global_sharding()
        global_shape = (n_proc * seg_len, WORDS_PER_ROW)

        from ..core.view import VIEW_STANDARD

        arrays = []
        for field_name, row_id in leaf_keys:
            local = np.zeros((seg_len, WORDS_PER_ROW), dtype=np.uint32)
            field = idx.field(field_name)
            view = field.view(VIEW_STANDARD) if field is not None else None
            if view is not None:
                for j, shard in enumerate(my_shards):
                    frag = view.fragment(shard)
                    if frag is not None:
                        plane = frag.row_plane(row_id)
                        if plane is not None:
                            local[j] = np.asarray(plane)
            arrays.append(jax.make_array_from_process_local_data(
                sharding, local, global_shape=global_shape))

        fn = self._count_fn(sig, len(arrays))
        hi, lo = fn(*arrays)
        self.steps_run += 1
        from ..ops.bitplane import combine_hi_lo

        return combine_hi_lo(hi, lo)

    def _count_fn(self, sig, arity):
        import jax
        import jax.numpy as jnp

        from ..exec.stacked import StackedEvaluator
        from ..ops.bitplane import hi_lo

        fn = self._fns.get((sig, arity))
        if fn is None:
            @jax.jit
            def fn(*stacks):
                acc = StackedEvaluator._tree_eval(sig, stacks)
                per_shard = jnp.sum(
                    jax.lax.population_count(acc).astype(jnp.int32),
                    axis=-1)
                return hi_lo(per_shard)

            self._fns[(sig, arity)] = fn
        return fn

    def stats(self):
        return {"steps": self.steps_run,
                "initialized": type(self)._initialized}
