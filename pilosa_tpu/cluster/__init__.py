"""Cluster layer: node membership, shard placement, cross-node query
fan-out, replication, anti-entropy, and resize.

Reference: cluster.go (placement + resize), broadcast.go (messaging),
gossip/ (membership). The TPU-native redesign keeps the same placement
algebra (FNV-1a partitions + jump consistent hashing + replicaN successors)
but replaces SWIM gossip with a static bootstrap + HTTP health monitor —
the JAX-distributed model where hosts are known up front — and carries the
control plane as JSON messages over HTTP (the reference's 16-type protobuf
taxonomy, broadcast.go:55-72).
"""

from .hash import JmpHasher, ModHasher, fnv1a64, partition_hash
from .node import (
    Node,
    NODE_STATE_READY,
    NODE_STATE_DOWN,
    CLUSTER_STATE_STARTING,
    CLUSTER_STATE_NORMAL,
    CLUSTER_STATE_DEGRADED,
    CLUSTER_STATE_RESIZING,
)
from .cluster import Cluster, DEFAULT_PARTITION_N
from .broadcast import (
    MessageType,
    Serializer,
    NopBroadcaster,
    HTTPBroadcaster,
)
from .membership import HealthMonitor
from .executor import ClusterExecutor, result_from_json
from .resize import ResizeError, ResizeJob, ResizeManager, clean_holder

__all__ = [
    "Cluster",
    "ClusterExecutor",
    "DEFAULT_PARTITION_N",
    "HTTPBroadcaster",
    "HealthMonitor",
    "JmpHasher",
    "MessageType",
    "ModHasher",
    "Node",
    "NopBroadcaster",
    "ResizeError",
    "ResizeJob",
    "ResizeManager",
    "clean_holder",
    "Serializer",
    "fnv1a64",
    "partition_hash",
    "result_from_json",
    "NODE_STATE_READY",
    "NODE_STATE_DOWN",
    "CLUSTER_STATE_STARTING",
    "CLUSTER_STATE_NORMAL",
    "CLUSTER_STATE_DEGRADED",
    "CLUSTER_STATE_RESIZING",
]
