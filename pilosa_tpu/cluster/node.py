"""Cluster node identity and states.

Reference: Node struct (pilosa.go), cluster states cluster.go:45-50, node
states (STARTING/READY/DOWN)."""

# Cluster states (reference: cluster.go:45-50)
CLUSTER_STATE_STARTING = "STARTING"
CLUSTER_STATE_NORMAL = "NORMAL"
CLUSTER_STATE_DEGRADED = "DEGRADED"
CLUSTER_STATE_RESIZING = "RESIZING"

# Node states
NODE_STATE_READY = "READY"
NODE_STATE_DOWN = "DOWN"


class Node:
    __slots__ = ("id", "uri", "is_coordinator", "state")

    def __init__(self, id, uri, is_coordinator=False, state=NODE_STATE_READY):
        self.id = id
        self.uri = uri.rstrip("/")
        self.is_coordinator = is_coordinator
        self.state = state

    def to_json(self):
        return {"id": self.id, "uri": self.uri,
                "isCoordinator": self.is_coordinator, "state": self.state}

    @classmethod
    def from_json(cls, d):
        return cls(d["id"], d["uri"],
                   is_coordinator=d.get("isCoordinator", False),
                   state=d.get("state", NODE_STATE_READY))

    def __eq__(self, other):
        return isinstance(other, Node) and self.id == other.id

    def __hash__(self):
        return hash(self.id)

    def __repr__(self):
        flags = " coordinator" if self.is_coordinator else ""
        return f"<Node {self.id} {self.uri} {self.state}{flags}>"
