"""Cluster resize: node join/leave with fragment streaming.

Reference: §3.5 — unprotectedGenerateResizeJob cluster.go:1196,
followResizeInstruction cluster.go:1297, distributeResizeInstructions
cluster.go:1545, holderCleaner holder.go:1126, abort api.go:1250.

Flow (coordinator-driven, matching the reference):
  1. Coordinator diffs old->new topology; per destination node it lists
     every shard the node must fetch and a live source that owned it
     (Cluster.frag_sources).
  2. Cluster state -> RESIZING, broadcast to old+new nodes.
  3. Each node with sources gets a RESIZE_INSTRUCTION (includes the
     schema, like the reference's NodeStatus piggyback) and executes it
     on a background thread: apply schema, then for each (index, shard,
     source) stream every field/view fragment via
     /internal/fragment/data and merge it locally (import-roaring path).
  4. Nodes report RESIZE_INSTRUCTION_COMPLETE to the coordinator; when
     all have, the coordinator installs the new topology and broadcasts
     CLUSTER_STATUS NORMAL with the node list; every node installs it and
     drops fragments it no longer owns (holderCleaner).
"""

import logging
import threading
import uuid

from ..utils import faultpoints, flightrec
from .broadcast import MessageType, Serializer
from .node import CLUSTER_STATE_NORMAL, CLUSTER_STATE_RESIZING, Node

logger = logging.getLogger("pilosa_tpu.resize")


class ResizeError(Exception):
    pass


def clean_holder(holder, cluster):
    """Drop fragments whose shard this node no longer owns (reference:
    holderCleaner.CleanHolder holder.go:1126). Returns removed count."""
    import os

    removed = 0
    for idx in list(holder.indexes.values()):
        for field in list(idx.fields.values()):
            for view in list(field.views.values()):
                for shard in list(view.fragments):
                    if cluster.owns_shard(cluster.local_id, idx.name, shard):
                        continue
                    frag = view.remove_fragment(shard)
                    frag.close()
                    for p in (frag.path, frag.cache_path):
                        if os.path.exists(p):
                            os.remove(p)
                    removed += 1
    return removed


class ResizeJob:
    """Coordinator-side tracking of one resize (reference: resizeJob
    cluster.go:1447)."""

    def __init__(self, id, action, old_nodes, new_nodes, instructions):
        self.id = id
        self.action = action  # "add" | "remove"
        self.old_nodes = old_nodes  # list[Node] — restored on abort
        self.new_nodes = new_nodes  # list[Node]
        self.instructions = instructions  # {node_id: instruction payload}
        self.expected = set(instructions)
        self.completed = set()
        self.state = "RUNNING"  # RUNNING | DONE | ABORTED

    def to_json(self):
        return {"id": self.id, "action": self.action, "state": self.state,
                "expected": sorted(self.expected),
                "completed": sorted(self.completed)}


class ResizeManager:
    """Per-node resize logic; the coordinator role activates on demand."""

    def __init__(self, holder, cluster, client_factory, broadcaster=None):
        from .broadcast import HTTPBroadcaster

        self.holder = holder
        self.cluster = cluster
        self.client_factory = client_factory
        self.broadcaster = broadcaster or HTTPBroadcaster(
            cluster, client_factory)
        self.job = None  # coordinator: current ResizeJob
        self._lock = threading.RLock()
        self.on_complete = None  # test hook
        # Fired on EVERY local RESIZING->NORMAL transition (finalize,
        # revert/abort, follower CLUSTER_STATUS): the API drains its
        # queued-while-resizing writes here.
        self.on_state_normal = None

    # ---------------------------------------------------------- coordinator

    def add_node(self, node):
        """Begin a resize admitting `node` (coordinator only; reference:
        nodeJoin cluster.go:1796)."""
        return self._begin("add", node)

    def remove_node(self, node_id):
        """(reference: api.RemoveNode api.go:1193; like the reference, the
        coordinator cannot remove itself — transfer coordination first)"""
        node = self.cluster.node(node_id)
        if node is None:
            raise ResizeError(f"node not in cluster: {node_id}")
        if node.is_coordinator:
            raise ResizeError(
                "cannot remove the coordinator; set a new coordinator "
                "first (/cluster/resize/set-coordinator)")
        return self._begin("remove", node)

    def _begin(self, action, node):
        if not self.cluster.is_coordinator():
            raise ResizeError("not the coordinator")
        with self._lock:
            if self.job is not None and self.job.state == "RUNNING":
                raise ResizeError("resize already in progress")
            # deep-copy both topologies: Node objects must not be shared
            # between the old snapshot (restored on abort) and the new list
            old_nodes = [Node.from_json(n.to_json())
                         for n in self.cluster.nodes]
            if action == "add":
                if self.cluster.node(node.id) is not None:
                    raise ResizeError(f"node already in cluster: {node.id}")
                new_nodes = sorted(
                    [Node.from_json(n.to_json()) for n in old_nodes]
                    + [Node.from_json(node.to_json())], key=lambda n: n.id)
            else:
                new_nodes = [Node.from_json(n.to_json())
                             for n in old_nodes if n.id != node.id]
                if not new_nodes:
                    raise ResizeError("cannot remove the last node")

            # may raise (unreachable node); nothing mutated yet
            instructions = self._generate_instructions(old_nodes, new_nodes)
            if action == "add" and node.id not in instructions:
                # the joining node always needs the schema, even when no
                # data moves to it (reference: NodeStatus schema sync on
                # join gossip/gossip.go LocalState)
                instructions[node.id] = {
                    "node": node.id, "sources": [],
                    "schema": self.holder.schema()}
            job = ResizeJob(uuid.uuid4().hex[:12], action, old_nodes,
                            new_nodes, instructions)
            self.job = job
            flightrec.record("cluster.resize_begin", job=job.id,
                             action=action, node=node.id,
                             instructions=len(instructions))

            # Block queries BEFORE the new placement becomes visible, so
            # no request routes by the new topology while data is moving.
            self.cluster.state = CLUSTER_STATE_RESIZING
            self.cluster.nodes = sorted(new_nodes, key=lambda n: n.id)
            self.cluster.save_topology()

            # nothing to move: finalize immediately
            if not instructions:
                self._finalize(job)
                return job

            self._broadcast_status(CLUSTER_STATE_RESIZING, new_nodes,
                                   targets=old_nodes + new_nodes)
            try:
                for node_id, instr in instructions.items():
                    self._send_instruction(node_id, instr, new_nodes)
            except Exception as e:
                self._revert(job, "ABORTED")
                raise ResizeError(
                    f"resize instruction delivery failed: {e}") from e
            return job

    def _revert(self, job, state):
        """Restore the pre-resize topology (abort/failure path)."""
        job.state = state
        flightrec.record("cluster.resize_abort", job=job.id, state=state)
        self.cluster.nodes = sorted(job.old_nodes, key=lambda n: n.id)
        self.cluster.state = CLUSTER_STATE_NORMAL
        self.cluster.save_topology()
        self.cluster.invalidate_shard_map()
        self._broadcast_status(CLUSTER_STATE_NORMAL, job.old_nodes,
                               targets=job.old_nodes + job.new_nodes)
        if self.on_state_normal:
            self.on_state_normal()

    def _cluster_shards(self, index_name, old_nodes):
        """Union of available shards across every old node — the
        coordinator's local holder only knows its own fragments
        (reference: Index.AvailableShards is cluster-wide via
        CreateShardMessage broadcasts index.go:292)."""
        idx = self.holder.index(index_name)
        shards = set(idx.available_shards()) if idx else set()
        for node in old_nodes:
            if node.id == self.cluster.local_id:
                continue
            try:
                resp = self.client_factory(node.uri).index_shards(index_name)
                shards.update(resp.get("shards", []))
            except Exception as e:
                raise ResizeError(
                    f"cannot enumerate shards on {node.id}: {e}") from e
        return sorted(shards)

    def _generate_instructions(self, old_nodes, new_nodes):
        """{dest_node_id: instruction} (reference:
        unprotectedGenerateResizeJob cluster.go:1196)."""
        schema = self.holder.schema()
        by_dest = {}
        for idx in self.holder.indexes.values():
            shards = self._cluster_shards(idx.name, old_nodes)
            if not shards:
                continue
            sources = self.cluster.frag_sources(
                old_nodes, new_nodes, idx.name, shards)
            for dest_id, pairs in sources.items():
                for shard, src_id in pairs:
                    src = next(n for n in old_nodes if n.id == src_id)
                    by_dest.setdefault(dest_id, []).append({
                        "index": idx.name, "shard": shard,
                        "sourceID": src.id, "sourceURI": src.uri})
        # jobID is stamped by _send_instruction once the job exists
        return {dest_id: {"node": dest_id, "sources": srcs, "schema": schema}
                for dest_id, srcs in by_dest.items()}

    def _send_instruction(self, node_id, instr, new_nodes):
        instr = dict(instr)
        instr["jobID"] = self.job.id
        instr["coordinatorURI"] = self.cluster.local_node.uri
        target = next((n for n in new_nodes if n.id == node_id), None)
        if target is None:
            raise ResizeError(f"instruction for unknown node {node_id}")
        if node_id == self.cluster.local_id:
            threading.Thread(
                target=self.follow_instruction, args=(instr,),
                daemon=True, name="resize-local").start()
        else:
            self.broadcaster.send_to(
                target, MessageType.RESIZE_INSTRUCTION, instr)

    def mark_complete(self, job_id, node_id, error=None):
        """(reference: markResizeInstructionComplete cluster.go:1413) A
        reported error fails the whole job and reverts the topology —
        leaving the cluster RESIZING forever would reject all traffic."""
        with self._lock:
            job = self.job
            if job is None or job.id != job_id or job.state != "RUNNING":
                return
            if error:
                logger.error("resize job %s failed on %s: %s",
                             job_id, node_id, error)
                self._revert(job, "FAILED")
                return
            job.completed.add(node_id)
            if job.completed >= job.expected:
                self._finalize(job)

    def _finalize(self, job):
        self.cluster.nodes = sorted(job.new_nodes, key=lambda n: n.id)
        self.cluster.state = CLUSTER_STATE_NORMAL
        self.cluster.save_topology()
        self.cluster.invalidate_shard_map()
        self._broadcast_status(CLUSTER_STATE_NORMAL, job.new_nodes,
                               targets=job.old_nodes + job.new_nodes)
        clean_holder(self.holder, self.cluster)
        # DONE only after peers were told NORMAL: a client that polls
        # status DONE must not then hit a follower still rejecting queries
        job.state = "DONE"
        flightrec.record("cluster.resize_finalize", job=job.id,
                         action=job.action, nodes=len(job.new_nodes))
        if self.on_state_normal:
            self.on_state_normal()
        if self.on_complete:
            self.on_complete(job)

    def abort(self):
        """(reference: api.ResizeAbort api.go:1250) Revert to the old
        topology; moved data is reclaimed later by holderCleaner."""
        with self._lock:
            job = self.job
            if job is None or job.state != "RUNNING":
                raise ResizeError("no resize job running")
            self._revert(job, "ABORTED")
            return job

    def _broadcast_status(self, state, nodes, targets):
        """Send CLUSTER_STATUS (state + node list) to every target but
        this node (the joining node isn't in cluster.peers() yet)."""
        payload = {"state": state, "nodes": [n.to_json() for n in nodes]}
        by_id = {n.id: n for n in targets}
        by_id.pop(self.cluster.local_id, None)
        for node in by_id.values():
            try:
                self.broadcaster.send_to(
                    node, MessageType.CLUSTER_STATUS, payload)
            except Exception:
                logger.warning("cluster-status to %s failed", node.id)

    # ----------------------------------------------------------- follower

    def follow_instruction(self, instr):
        """Execute one resize instruction: apply schema, stream each
        source fragment, report completion — or the failure, so the
        coordinator can fail the job instead of hanging RESIZING
        (reference: followResizeInstruction cluster.go:1297)."""
        error = None
        try:
            self.holder.apply_schema(instr.get("schema", []))
            for src in instr.get("sources", []):
                self._retrieve_shard(src)
        except Exception as e:
            logger.exception("resize instruction failed")
            error = str(e) or type(e).__name__
        try:
            self._report_complete(instr, error=error)
        except Exception:
            logger.exception("reporting resize completion failed")

    def _retrieve_shard(self, src):
        """Stream every field/view fragment of (index, shard) from the
        source node and merge locally (reference:
        RetrieveShardFromURI http/client.go:742 + importRoaring). The
        source enumerates its fragments — views are data-dependent, so
        the destination cannot know them from the schema alone."""
        index, shard = src["index"], int(src["shard"])
        # crash-test timing hook: arming a delay here holds the cluster
        # in RESIZING long enough to queue writes deterministically
        faultpoints.reached("resize.fetch")
        client = self.client_factory(src["sourceURI"])
        idx = self.holder.index(index)
        if idx is None:
            return
        listing = client.shard_fragments(index, shard)
        for entry in listing.get("fragments", []):
            field = idx.field(entry["field"])
            if field is None:
                continue  # not in the schema we were sent; skip
            data = client.fragment_data(
                index, entry["field"], entry["view"], shard)
            if not data:
                continue
            view = field.create_view_if_not_exists(entry["view"])
            frag = view.create_fragment_if_not_exists(shard)
            frag.import_roaring(data)

    def _report_complete(self, instr, error=None):
        payload = {"jobID": instr["jobID"], "node": self.cluster.local_id,
                   "error": error}
        coord_uri = instr.get("coordinatorURI")
        if (self.cluster.local_node is not None
                and coord_uri == self.cluster.local_node.uri):
            self.mark_complete(payload["jobID"], payload["node"],
                               error=error)
            return
        self.client_factory(coord_uri).send_message(
            Serializer.marshal(
                MessageType.RESIZE_INSTRUCTION_COMPLETE, payload))

    # ----------------------------------------------------------- dispatch

    def receive(self, msg_type, payload):
        """Handle resize-related control messages; returns True when
        handled."""
        if msg_type == MessageType.RESIZE_INSTRUCTION:
            threading.Thread(
                target=self.follow_instruction, args=(payload,),
                daemon=True, name="resize-follow").start()
            return True
        if msg_type == MessageType.RESIZE_INSTRUCTION_COMPLETE:
            self.mark_complete(payload["jobID"], payload["node"],
                               error=payload.get("error"))
            return True
        if msg_type == MessageType.CLUSTER_STATUS:
            state = payload.get("state")
            nodes = payload.get("nodes")
            with self._lock:
                if nodes:
                    self.cluster.nodes = sorted(
                        (Node.from_json(d) for d in nodes),
                        key=lambda n: n.id)
                    self.cluster.save_topology()
                if state:
                    self.cluster.state = state
            # placement changed (or is about to): everything learned about
            # peers' shards is suspect — force a re-seed on next query
            self.cluster.invalidate_shard_map()
            if state == CLUSTER_STATE_NORMAL and nodes:
                clean_holder(self.holder, self.cluster)
            if state == CLUSTER_STATE_NORMAL and self.on_state_normal:
                self.on_state_normal()
            return True
        if msg_type == MessageType.SET_COORDINATOR:
            with self._lock:
                for n in self.cluster.nodes:
                    n.is_coordinator = (n.id == payload.get("id"))
                self.cluster.save_topology()
            return True
        return False
