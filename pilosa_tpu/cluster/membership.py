"""Membership / failure detection.

Reference: gossip/gossip.go (SWIM memberlist) + cluster.confirmNodeDown
(cluster.go:1724-1752): suspicion from gossip is double-checked with up to
10 direct /status probes before a node is marked down.

TPU-native replacement: hosts are static (the JAX-distributed model), so
membership reduces to a health monitor — every node probes its peers'
/status on an interval; a peer failing `confirm_retries` consecutive
probes is marked DOWN (cluster state recomputed: NORMAL/DEGRADED), and a
recovered peer is marked READY again. Elastic add/remove arrives via the
control plane (node-event messages), not via discovery.
"""

import threading

from ..utils import flightrec
from .node import NODE_STATE_DOWN, NODE_STATE_READY


class HealthMonitor:
    def __init__(self, cluster, client_factory, interval=1.0,
                 confirm_retries=3, on_change=None):
        """confirm_retries: consecutive probe failures before DOWN
        (reference uses 10 fast retries in confirmNodeDown; health probes
        here are already periodic so the default is lower)."""
        self.cluster = cluster
        self.client_factory = client_factory
        self.interval = interval
        self.confirm_retries = confirm_retries
        self.on_change = on_change  # callback(node, new_state)
        self._failures = {}
        self._stop = threading.Event()
        self._thread = None

    def start(self):
        if self._thread is None:
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._run, daemon=True, name="health-monitor")
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    def _run(self):
        while not self._stop.wait(self.interval):
            self.probe_all()

    def probe_all(self):
        for node in self.cluster.peers():
            self.probe(node)

    def probe(self, node):
        ok = self._check(node)
        if ok:
            self._failures[node.id] = 0
            if node.state == NODE_STATE_DOWN:
                self.cluster.set_node_state(node.id, NODE_STATE_READY)
                flightrec.record("cluster.node_up", node=node.id)
                if self.on_change:
                    self.on_change(node, NODE_STATE_READY)
        else:
            n = self._failures.get(node.id, 0) + 1
            self._failures[node.id] = n
            if n >= self.confirm_retries and node.state != NODE_STATE_DOWN:
                self.cluster.set_node_state(node.id, NODE_STATE_DOWN)
                flightrec.record("cluster.node_down", node=node.id,
                                 failures=n)
                if self.on_change:
                    self.on_change(node, NODE_STATE_DOWN)

    def _check(self, node):
        try:
            client = self.client_factory(node.uri)
            # Probes need a tight deadline (reference: memberlist probe
            # timeouts are sub-second); inheriting the default 30s client
            # timeout would stall down-detection by minutes.
            if hasattr(client, "timeout"):
                client.timeout = 2
            status = client.status()
            return isinstance(status, dict)
        except Exception:
            return False
