"""Cross-node query execution: mapReduce over the cluster.

Reference: executor.mapReduce (executor.go:2455) — shards are grouped by
primary owner (shardsByNode), local shards run on this node's devices,
remote groups are forwarded as `Remote:true` queries with an explicit shard
list (remoteExec executor.go:2414), and responses reduce as they arrive
(:2483-2503) with failed nodes' shards retried on their replicas.

Writes route differently: Set/Clear target every replica of the owning
shard (executor.go:2137-2160), attribute writes fan out to all nodes
(attrs are stored on every node), and schema DDL is broadcast by the API
layer before any of this runs.

The TPU-native shape: "local shards" means shards resident in this host's
HBM; the local reduce happens inside fused XLA dispatches (exec.Executor),
and only per-node partial results cross the DCN as JSON.
"""

import os
import threading
import time as _time

from ..core.row import Row
from ..exec.executor import ExecOptions, Executor
from ..exec.result import FieldRow, GroupCount, Pair, RowIdentifiers, ValCount
from ..pql import call_to_pql, parse
from ..shardwidth import SHARD_WIDTH
from ..utils.workpool import get_pool


class ClusterExecError(Exception):
    pass


# ---------------------------------------------------------------- decoding

def _internal_wire():
    """Node-to-node encoding: "proto" (default) or "json". Unknown values
    fail fast rather than silently selecting proto."""
    wire = os.environ.get("PILOSA_TPU_INTERNAL_WIRE", "proto").lower()
    if wire not in ("proto", "json"):
        raise ClusterExecError(
            f"PILOSA_TPU_INTERNAL_WIRE must be 'proto' or 'json', "
            f"got {wire!r}")
    return wire


def result_from_json(d):
    """Decode one remote result by JSON shape (the reference decodes by
    protobuf type tag, http/client.go QueryResponse)."""
    if d is None or isinstance(d, (bool, int, float, str)):
        return d
    if isinstance(d, dict):
        if "columns" in d or "keys" in d and "rows" not in d:
            row = Row.from_columns(d.get("columns", []))
            row.attrs = d.get("attrs") or None
            row.keys = d.get("keys")
            return row
        if "rows" in d:
            return RowIdentifiers(rows=d.get("rows", []), keys=d.get("keys"))
        if "value" in d and "count" in d:
            return ValCount(d["value"], d["count"])
        if "id" in d and "count" in d:
            return Pair(d["id"], d["count"], key=d.get("key"))
        raise ClusterExecError(f"undecodable result dict: {d!r}")
    if isinstance(d, list):
        if not d:
            return []
        if isinstance(d[0], dict) and "group" in d[0]:
            return [
                GroupCount(
                    [FieldRow(fr["field"], fr.get("rowID", 0),
                              row_key=fr.get("rowKey"))
                     for fr in gc["group"]],
                    gc["count"])
                for gc in d
            ]
        if isinstance(d[0], dict) and "id" in d[0]:
            return [Pair(p["id"], p["count"], key=p.get("key")) for p in d]
        raise ClusterExecError(f"undecodable result list: {d!r}")
    raise ClusterExecError(f"undecodable result: {d!r}")


# ---------------------------------------------------------------- reduction

def reduce_results(call, a, b):
    """Merge two per-node partial results for one call (reference: the
    reduceFn closures in executor.go per call type)."""
    if a is None:
        return b
    if b is None:
        return a
    if isinstance(a, bool):
        return a or b
    if isinstance(a, (int, float)) and not isinstance(a, bool):
        return a + b
    if isinstance(a, Row):
        return a.merge(b)
    if isinstance(a, ValCount):
        if call.name == "Min":
            return a.smaller(b)
        if call.name == "Max":
            return a.larger(b)
        return a.add(b)  # Sum
    if isinstance(a, Pair):  # MinRow/MaxRow
        if a.id == b.id:
            return Pair(a.id, a.count + b.count, key=a.key)
        if call.name == "MaxRow":
            return a if a.id > b.id else b
        return a if a.id < b.id else b
    if isinstance(a, RowIdentifiers):
        merged = sorted(set(a.rows) | set(b.rows))
        return RowIdentifiers(rows=merged)
    if isinstance(a, list):
        if not a:
            return b
        if not b:
            return a
        if isinstance(a[0], Pair):  # TopN partials (Pairs.Add cache.go:356)
            counts = {}
            for p in a + b:
                counts[p.id] = counts.get(p.id, 0) + p.count
            out = [Pair(id, cnt) for id, cnt in counts.items()]
            out.sort(key=lambda p: (-p.count, p.id))
            return out
        if isinstance(a[0], GroupCount):
            totals = {}
            for gc in a + b:
                key = tuple((fr.field, fr.row_id) for fr in gc.group)
                if key in totals:
                    totals[key] = GroupCount(gc.group,
                                             totals[key].count + gc.count)
                else:
                    totals[key] = gc
            return [totals[k] for k in sorted(totals)]
        raise ClusterExecError(f"unreducible list result: {type(a[0])}")
    raise ClusterExecError(f"unreducible result type: {type(a)}")


def finalize_result(call, result):
    """Apply coordinator-side trims that remote partials skipped."""
    if call.name == "Options" and call.children:
        return finalize_result(call.children[0], result)
    if isinstance(result, list) and result and isinstance(result[0], Pair):
        n = call.args.get("n")
        if call.name == "TopN" and n is not None \
                and call.args.get("ids") is None:
            return result[:int(n)]
    if isinstance(result, list) and result \
            and isinstance(result[0], GroupCount):
        limit = call.args.get("limit")
        if limit is not None:
            result = result[:int(limit)]
        # offset applies AFTER the limit-bounded merge and is a NO-OP when
        # it reaches past the result set — this matches the reference's
        # effective behavior (`offset < len(results)` guard after the
        # limit-bounded merge, executeGroupBy executor.go:1134-1149), NOT
        # SQL's offset-then-limit; keep in sync with the local-executor
        # copy (exec/executor.py _exec_group_by).
        offset = call.args.get("offset")
        if offset is not None and int(offset) < len(result):
            result = result[int(offset):]
        return result
    if isinstance(result, RowIdentifiers):
        limit = call.args.get("limit")
        if limit is not None and result.keys is None:
            result.rows = result.rows[:int(limit)]
    return result


# ---------------------------------------------------------------- executor

class ClusterExecutor:
    """Coordinating executor: local device execution + remote fan-out.

    Wraps exec.Executor. With a single-node cluster (or none) it degrades
    to purely local execution."""

    #: what the query coalescer may batch THROUGH a cluster coordinator:
    #: only Count merges as one collective step (cluster/spmd.py
    #: SpmdBatchRunner); the local Executor's wider set applies on
    #: single nodes and fan-out legs
    BATCHABLE_CALLS = frozenset(("Count",))

    def __init__(self, holder, cluster, client_factory, spmd=None,
                 logger=None, max_writes_per_request=0):
        from ..utils.logger import NopLogger

        self.holder = holder
        self.cluster = cluster
        self.client_factory = client_factory
        self.spmd = spmd
        self.logger = logger or NopLogger()
        self.local = Executor(
            holder, max_writes_per_request=max_writes_per_request)

    # -- public entry --------------------------------------------------------

    def execute(self, index_name, query, shards=None, options=None):
        idx = self.holder.index(index_name)
        if idx is None:
            raise ClusterExecError(f"index not found: {index_name}")
        if isinstance(query, str):
            query = parse(query)
        opt = options or ExecOptions()
        from ..exec.executor import check_write_limit

        check_write_limit(query, self.local.max_writes_per_request)

        if self.cluster is None or len(self.cluster.nodes) <= 1 or opt.remote:
            # single-node, or we ARE the remote: pure local execution
            return self.local.execute(index_name, query, shards=shards,
                                      options=opt)

        from ..exec.executor import validate_uint_args
        from ..exec.translate import translate_calls, translate_results

        translate_calls(idx, query.calls)
        # negative-arg rejection AFTER translation (keyed args become
        # ints) and BEFORE the SPMD fast path, which reads args raw
        for c in query.calls:
            validate_uint_args(c)
        # fetch the cluster-wide shard list ONCE per query, not per call
        if shards is None and any(not c.writes() for c in query.calls):
            shards = self.cluster_shards(idx)

        explain = getattr(opt, "explain", None)
        if explain == "plan":
            return self._explain_cluster_plan(idx, query, shards, opt)

        # The coordinator fingerprints the whole query; remote legs
        # carry opt.remote so they never record themselves, and local
        # legs go through execute_call (not execute), so this is the
        # single recording site for a fanned-out query.
        from ..utils import workload as workload_mod

        wctx = workload_mod.begin_query(idx.name, query)
        before = self.local._stacked.counters()
        t_query = _time.perf_counter()
        try:
            plan_calls = [] if explain == "analyze" else None
            # Fused collective fast path (mesh serving + fusion on): the
            # WHOLE multi-call Count query runs as one jitted collective
            # program per process — one announcement, one psum, zero
            # result bytes over HTTP. Declines (cold fingerprint,
            # uncoverable tree, degraded mesh) fall through to the
            # per-call loop unchanged.
            if self.spmd is not None and plan_calls is None \
                    and all(not c.writes() for c in query.calls):
                used, counts = self.spmd.maybe_execute_fused(
                    idx, query, shards)
                if used:
                    return translate_results(idx, query.calls, counts)
            results = []
            deadline = getattr(opt, "deadline", None)
            for call in query.calls:
                if deadline is not None \
                        and _time.monotonic() >= deadline:
                    from ..exec.stacked import DeadlineExceededError

                    raise DeadlineExceededError(
                        "request deadline expired between calls")
                if plan_calls is None:
                    results.append(self._execute_call(idx, call, shards, opt))
                    continue
                # ?explain=analyze: every fan-out leg runs its own analyze
                # and hands back a sub-plan; the coordinator node wraps them
                sink = []
                results.append(
                    self._execute_call(idx, call, shards, opt, plan_sink=sink))
                plan_calls.append(
                    self._cluster_plan_node(idx, call, shards, sink))
            if plan_calls is not None:
                self._stash_cluster_plan(idx, "analyze", plan_calls, shards)
            return translate_results(idx, query.calls, results)
        finally:
            if wctx is not None:
                from ..shardwidth import WORDS_PER_ROW

                after = self.local._stacked.counters()
                workload_mod.end_query(
                    wctx, _time.perf_counter() - t_query, deltas={
                        "dispatches": after[0] - before[0],
                        "cache_hits": after[1] - before[1],
                        "cache_misses": after[2] - before[2],
                        "bytes_materialized":
                            (after[3] - before[3]) * WORDS_PER_ROW * 4,
                    })

    def _cluster_plan_node(self, idx, call, shards, children):
        """The coordinator's node for one fanned-out call: per-node
        sub-plans as children (already-serialized dicts)."""
        from ..exec import plan as plan_mod

        node = plan_mod.PlanNode(
            call.name, pql=call_to_pql(call),
            strategy="write" if call.writes() else "cluster-map-reduce")
        node.annotations["nodes"] = len(children)
        node.annotations["shards"] = len(shards or [])
        if self.spmd is not None and not call.writes():
            mesh_child = any(
                isinstance(c, dict) and c.get("node") == "mesh"
                for c in children)
            if mesh_child:
                # the call executed (or would execute) over the
                # collective plane — surface the mesh identity at the
                # call node too, so plan consumers don't have to walk
                # children to see the serving path
                node.strategy = "spmd-collective"
                node.annotations["spmd"] = True
                node.annotations["mesh"] = self.spmd.mesh_shape()
            else:
                # the SPMD collective plane is bypassed under explain so
                # the per-node sub-plans can be captured; record that
                # the normal path may differ
                node.annotations["spmd_bypassed"] = True
        node.children = list(children)
        return node

    def _stash_cluster_plan(self, idx, mode, plan_calls, shards):
        from ..exec import plan as plan_mod
        from ..utils import profile as profile_mod

        prof = profile_mod.current()
        env = plan_mod.envelope(
            idx.name, mode, plan_calls, shards=len(shards or []),
            trace_id=prof.root.trace_id if prof is not None else None)
        if mode == "analyze":
            # the coordinator node itself never flags; the misestimates
            # live inside the per-node sub-plans — roll them up
            mis = sum(
                len(child["plan"].get("misestimates") or [])
                for node in env["calls"]
                for child in node.get("children", [])
                if isinstance(child, dict)
                and isinstance(child.get("plan"), dict))
            env["misestimates"] = mis
            if mis:
                plan_mod.record(env)
        plan_mod.stash(env)
        return env

    def _explain_cluster_plan(self, idx, query, shards, opt):
        """?explain=true on a cluster: per call, gather one sub-plan per
        owning node — the local planner for our shards, an
        explain="plan" fan-out request for peers (host-side planning on
        each node; nothing executes anywhere)."""
        from ..exec import plan as plan_mod

        local_planner = plan_mod.Planner(self.local)
        plan_calls = []
        for call in query.calls:
            if call.writes():
                plan_calls.append(
                    local_planner.plan_call(idx, call, shards, opt))
                continue
            if self.spmd is not None \
                    and self.spmd.plan_eligible(idx, call):
                # the serving path is the collective plane: ONE mesh
                # child with zero dispatches (a globally-sharded program
                # replaces the fan-out), annotated spmd:true + mesh shape
                plan_calls.append(self._cluster_plan_node(
                    idx, call, shards,
                    [{"node": "mesh",
                      "shards": len(shards or []),
                      "plan": self.spmd.plan_node(idx, call, shards)}]))
                continue
            by_node = self.cluster.shards_by_node(idx.name, shards or [])
            children = []
            for node, node_shards in by_node.items():
                entry = {"node": node.id, "shards": len(node_shards)}
                try:
                    if node.id == self.cluster.local_id:
                        entry["plan"] = local_planner.plan_call(
                            idx, call, node_shards,
                            self._remote_opt(opt)).to_dict()
                    else:
                        resp = self._client(node).query(
                            idx.name, call_to_pql(call),
                            shards=node_shards, remote=True,
                            explain="plan")
                        sub = resp.get("plan") or {}
                        calls = sub.get("calls") or [None]
                        entry["plan"] = calls[0]
                except Exception as e:  # degraded, not fatal: a plan
                    entry["error"] = str(e)  # must never fail the query
                children.append(entry)
            plan_calls.append(
                self._cluster_plan_node(idx, call, shards, children))
        self._stash_cluster_plan(idx, "plan", plan_calls, shards)
        return []

    # -- per-call ------------------------------------------------------------

    def _execute_call(self, idx, call, shards, opt, plan_sink=None):
        if call.name in ("Set", "Clear"):
            return self._execute_replicated_write(idx, call)
        if call.name in ("SetRowAttrs", "SetColumnAttrs"):
            return self._execute_attr_write(idx, call)
        return self._map_reduce(idx, call, shards, opt, plan_sink=plan_sink)

    def _remote_opt(self, opt):
        return ExecOptions(
            exclude_columns=opt.exclude_columns,
            column_attrs=opt.column_attrs,
            exclude_row_attrs=opt.exclude_row_attrs,
            remote=True, profile=opt.profile,
            deadline=getattr(opt, "deadline", None))

    def _execute_replicated_write(self, idx, call):
        """Set/Clear: apply on every replica of the owning shard
        (reference: executeSetBitField executor.go:2137)."""
        col = call.args.get("_col")
        if not isinstance(col, int) or isinstance(col, bool):
            raise ClusterExecError(f"{call.name}() requires a column")
        shard = col // SHARD_WIDTH
        pql = call_to_pql(call)
        ret = False
        ok = 0
        errors = []
        for node in self.cluster.shard_nodes(idx.name, shard):
            if node.id == self.cluster.local_id:
                out = self.local.execute_call(
                    idx, call, [shard], ExecOptions(remote=True))
                ret = ret or bool(out)
                ok += 1
            else:
                try:
                    resp = self._client(node).query(
                        idx.name, pql, remote=True)
                    out = resp["results"][0]
                    ret = ret or bool(out)
                    ok += 1
                    # read-your-writes for shard discovery: the owner just
                    # acked this shard; don't wait for its async push.
                    # Set only — Clear never materializes a fragment, so
                    # recording it would register a phantom shard.
                    if call.name == "Set":
                        self.cluster.record_remote_shards(
                            node.id, idx.name, [shard])
                except Exception as e:
                    errors.append((node.id, e))
        if ok == 0:
            raise ClusterExecError(f"write failed on all replicas: {errors}")
        return ret

    def _execute_attr_write(self, idx, call):
        """Attr stores live on every node — apply locally, fan out to all
        peers (reference: executeSetRowAttrs executor.go:2212)."""
        result = self.local.execute_call(
            idx, call, None, ExecOptions(remote=True))
        pql = call_to_pql(call)
        for node in self.cluster.peers():
            try:
                self._client(node).query(idx.name, pql, remote=True)
            except Exception as e:
                # replica divergence heals via the anti-entropy attr diff,
                # but an operator must be able to SEE it happened
                self.logger.printf(
                    "attr write %s diverged on %s (anti-entropy will "
                    "repair): %s", call.name, node.id, e)
        return result

    # -- mapReduce -----------------------------------------------------------

    def _map_reduce(self, idx, call, shards, opt, plan_sink=None):
        if shards is None:
            shards = self.cluster_shards(idx)
        # SPMD data plane: coverable Count/Sum/Min/Max/TopN/GroupBy trees
        # merge over collectives (cluster/spmd.py), initiated from any
        # node (non-coordinators forward in one hop); anything it declines
        # falls through to the HTTP merge below. Bypassed under
        # explain=analyze: per-node sub-plans need per-node execution.
        if self.spmd is not None and plan_sink is None:
            used, result = self.spmd.maybe_execute(idx, call, shards)
            if used:
                return result
        elif self.spmd is not None:
            # ?explain=analyze with the mesh serving: analyze reports
            # the path that actually serves (PR-16 fused-analyze
            # contract), so execute over the collective plane and graft
            # the step's single dispatch + psum bytes onto the plan. A
            # decline falls through to the per-node analyze fan-out.
            used, result, entry = self.spmd.maybe_execute_analyze(
                idx, call, shards)
            if used:
                plan_sink.append(entry)
                return result
        by_node = self.cluster.shards_by_node(idx.name, shards)

        lock = threading.Lock()
        merged = [None]
        merged_any = [False]
        errors = []
        overload_retried = set()  # node ids given their one same-node retry
        deadline = getattr(opt, "deadline", None)

        def merge_in(result):
            with lock:
                if not merged_any[0]:
                    merged[0] = result
                    merged_any[0] = True
                else:
                    merged[0] = reduce_results(call, merged[0], result)

        use_proto = _internal_wire() != "json"
        pql = call_to_pql(call)  # invariant across nodes and retries

        def note_plan(node, node_shards, sub_plan):
            with lock:
                plan_sink.append({"node": node.id,
                                  "shards": len(node_shards),
                                  "plan": sub_plan})

        def run_node(node, node_shards, tried=()):
            from ..exec.stacked import (DeadlineExceededError,
                                        set_thread_deadline)

            try:
                # Deadline at leg start: an expired leg is dropped, never
                # dispatched — locally OR on a peer. Remaining budget is
                # forwarded RELATIVE (the peer's edge re-anchors against
                # its own clock; clock skew never corrupts it).
                remaining = None
                if deadline is not None:
                    remaining = deadline - _time.monotonic()
                    if remaining <= 0:
                        raise DeadlineExceededError(
                            "request deadline expired before fan-out leg")
                if node.id == self.cluster.local_id:
                    # local legs run execute_call on a pool thread — the
                    # coordinator's thread-local dispatch deadline doesn't
                    # travel here, so arm this thread's own
                    if deadline is not None:
                        set_thread_deadline(deadline)
                    try:
                        if plan_sink is not None:
                            result, pnode = self.local.explain_analyze_call(
                                idx, call, node_shards, self._remote_opt(opt))
                            note_plan(node, node_shards, pnode.to_dict())
                        else:
                            result = self.local.execute_call(
                                idx, call, node_shards, self._remote_opt(opt))
                    finally:
                        if deadline is not None:
                            set_thread_deadline(None)
                elif plan_sink is not None:
                    # analyze legs ride the JSON wire regardless of the
                    # configured internal encoding: the proto response has
                    # no plan slot
                    resp = self._client(node).query(
                        idx.name, pql, shards=node_shards, remote=True,
                        exclude_row_attrs=opt.exclude_row_attrs,
                        exclude_columns=opt.exclude_columns,
                        explain="analyze", deadline=remaining)
                    result = result_from_json(resp["results"][0])
                    sub = resp.get("plan") or {}
                    calls = sub.get("calls") or [None]
                    note_plan(node, node_shards, calls[0])
                elif use_proto:
                    # protobuf data plane for node-to-node fan-out
                    # (reference: remoteExec posts proto QueryRequests,
                    # executor.go:2414 + http/client.go:268)
                    results, err = self._client(node).query_proto(
                        idx.name, pql, shards=node_shards, remote=True,
                        exclude_row_attrs=opt.exclude_row_attrs,
                        exclude_columns=opt.exclude_columns,
                        deadline=remaining)
                    if err:
                        raise ClusterExecError(err)
                    if not results:
                        raise ClusterExecError(
                            f"malformed proto response from {node.id}: "
                            "no results and no error")
                    r = results[0]
                    # proto Rows decode to their wire dict; everything else
                    # is already a result object
                    result = result_from_json(r) if isinstance(r, dict) \
                        else r
                else:
                    resp = self._client(node).query(
                        idx.name, pql, shards=node_shards, remote=True,
                        exclude_row_attrs=opt.exclude_row_attrs,
                        exclude_columns=opt.exclude_columns,
                        deadline=remaining)
                    result = result_from_json(resp["results"][0])
                merge_in(result)
            except Exception as e:
                from ..server.client import DeadlineExceeded
                from ..utils import flightrec

                if isinstance(e, (DeadlineExceededError, DeadlineExceeded)) \
                        or getattr(e, "status", None) == 504:
                    # every replica shares the same lapsed deadline —
                    # retrying is pure waste, drop the leg
                    with lock:
                        errors.append((node.id, e))
                    return
                if getattr(e, "status", None) == 503:
                    shed = getattr(e, "shed", None)
                    if shed is not None:
                        # the peer is SHEDDING (X-Pilosa-Shed: admission /
                        # coalesce / ingest back-pressure), not dead:
                        # honor its Retry-After (capped — a fan-out leg
                        # can't idle for seconds) and retry the SAME
                        # replica once before moving on
                        with lock:
                            first = node.id not in overload_retried
                            overload_retried.add(node.id)
                        flightrec.record(
                            "cluster.node_overload", node=node.id,
                            index=idx.name, site=shed,
                            retry_after=getattr(e, "retry_after", None))
                        if first:
                            _time.sleep(min(
                                getattr(e, "retry_after", None) or 0.05,
                                0.5))
                            return run_node(node, node_shards, tried)
                    else:
                        # the peer REJECTED fast (its device-link prober
                        # says DOWN) rather than timing out — name the
                        # node in the recorder so a cluster slowdown is
                        # attributable (the coordinator's
                        # /status?observability=true roll-up shows the
                        # same state via /debug/device)
                        flightrec.record(
                            "cluster.node_unready", node=node.id,
                            index=idx.name, error=str(e))
                # retry each shard on its next replica (reference:
                # mapReduce error path executor.go:2490-2503)
                retried = False
                tried = tuple(tried) + (node.id,)
                regroup = {}
                for shard in node_shards:
                    for replica in self.cluster.shard_nodes(idx.name, shard):
                        if replica.id not in tried:
                            regroup.setdefault(
                                replica.id, (replica, []))[1].append(shard)
                            break
                for replica, rshards in regroup.values():
                    retried = True
                    run_node(replica, rshards, tried)
                if not regroup and node_shards:
                    with lock:
                        errors.append((node.id, e))

        # Fan-out workers must carry the request's trace context (the span
        # is thread-local; reference: client-side inject http/client.go).
        from ..utils import tracing

        parent_span = tracing.current_span()

        def run_node_traced(node, node_shards):
            with tracing.with_span(parent_span):
                # Per-node fan-out span: its duration is this node's whole
                # contribution (local execute or remote RTT + retries), so
                # a profile shows WHICH node a slow fan-out waited on.
                with tracing.start_span(
                        "cluster.mapReduce.node", node=node.id,
                        shards=len(node_shards),
                        remote=node.id != self.cluster.local_id):
                    run_node(node, node_shards)

        # Bounded fan-out on the shared worker pool (was an unbounded
        # thread per node per query). run_node catches its own errors
        # into `errors` and reduces as results arrive via merge_in, so
        # the pool's fail-fast never triggers here and the
        # reduce-as-they-arrive + replica-retry semantics are unchanged.
        get_pool().map_ordered(
            lambda item: run_node_traced(*item), list(by_node.items()))

        # Cross-node trace assembly (?profile=true): each remote leg's
        # spans stayed on the node that recorded them — without this a
        # profiled cluster query shows the fan-out span and nothing
        # underneath it. Pull the peers' slices of the trace and merge
        # them (skew-corrected) into the active profile. Best-effort and
        # profile-gated: the default path never gets here with a profile.
        self._collect_remote_spans(by_node)

        if errors:
            from ..exec.stacked import DeadlineExceededError
            from ..server.client import DeadlineExceeded

            for _nid, e in errors:
                if isinstance(e, DeadlineExceededError):
                    raise e
                if isinstance(e, DeadlineExceeded) \
                        or getattr(e, "status", None) == 504:
                    # a remote leg's budget lapsed (client-side or the
                    # peer's own 504) — same 504 at the coordinator
                    raise DeadlineExceededError(str(e)) from e
            raise ClusterExecError(f"query failed: {errors}")
        if not merged_any[0]:
            # zero shards anywhere: run locally over an empty shard list so
            # the result has the call's natural empty shape (0, empty Row…)
            merged[0] = self.local.execute_call(
                idx, call, [], self._remote_opt(opt))
        result = finalize_result(call, merged[0])
        if isinstance(result, Row):
            # remote partials skip decoration; the coordinator attaches
            # row attrs / applies exclude options once on the merged Row
            # (unwrapping Options so the effective call + flags apply)
            from ..exec.executor import unwrap_options

            eff_call, eff_opt = unwrap_options(call, opt)
            self.local.attach_row_attrs(idx, eff_call, result, eff_opt)
        return result

    def _collect_remote_spans(self, by_node):
        """Merge remote-leg spans into the active query profile.

        Skew correction (utils/tracing.estimate_skew): a remote node's
        http span is the child of this coordinator's
        `cluster.mapReduce.node` span — that request/response envelope
        brackets the remote clock, NTP-style. The peer fetch runs under
        with_span(None) so it neither injects trace headers nor adds
        spans of its own to the trace it is assembling."""
        from ..utils import profile as profile_mod
        from ..utils import tracing

        prof = profile_mod.current()
        if prof is None:
            return
        remote_nodes = [n for n in by_node
                        if n.id != self.cluster.local_id]
        if not remote_nodes:
            return
        trace_id = prof.root.trace_id
        local_dicts = [s.to_dict() for s in prof.spans_snapshot()]
        remote_by_node = {}
        with tracing.with_span(None):
            for node in remote_nodes:
                try:
                    resp = self._client(node).debug_trace(trace_id)
                except Exception:  # noqa: BLE001 — assembly is best-effort
                    continue
                spans = (resp or {}).get("spans") or []
                if spans:
                    remote_by_node[node.id] = spans
        if not remote_by_node:
            return
        merged, skew = tracing.merge_remote_spans(
            local_dicts, remote_by_node)
        local_ids = {s["spanID"] for s in local_dicts}
        added = 0
        for s in merged:
            if s["spanID"] in local_ids:
                continue
            prof.record(tracing.Span.from_dict(s))
            added += 1
        # in-process clusters deliver remote spans through the shared span
        # sink, so `added` can be 0 — the skew estimate is still real
        prof.set_tag("remote_spans",
                     {nid: len(s) for nid, s in remote_by_node.items()})
        prof.set_tag("clock_skew_seconds",
                     {nid: round(th, 6) for nid, th in skew.items()})

    # -- shard discovery -----------------------------------------------------

    def cluster_shards(self, idx):
        """Union of available shards across all live nodes. Steady state:
        ZERO shard-discovery HTTP — peers PUSH their per-index shard sets
        over the control plane on every change (CREATE_SHARD messages;
        the reference gossips availableShards the same way) and this just
        reads the local map. A peer is fetched over HTTP only to SEED the
        map: once per (peer, index), and again after a node-state flap
        (its pushes may have been lost while unreachable)."""
        shards = set(idx.available_shards())

        from .node import NODE_STATE_DOWN

        stale = [n for n in self.cluster.peers()
                 if not self.cluster.shards_synced(n.id, idx.name)]

        def fetch(node):
            try:
                client = self._client(node)
                if node.state == NODE_STATE_DOWN:
                    # Probe DOWN-marked peers with a short deadline: a
                    # healed-but-not-yet-READY node still contributes its
                    # exclusive shards; a truly dead one costs ~2s, not a
                    # full client timeout. Shards it shares with replicas
                    # surface from their fetches regardless.
                    client.timeout = 2
                resp = client.index_shards(idx.name)
                self.cluster.set_remote_shards(
                    node.id, idx.name, resp.get("shards", []))
            except Exception:
                # not marked synced -> retried next query; replicated
                # shards come from its replicas meanwhile
                pass

        if stale:
            # fetch() swallows its own errors, so pool fail-fast is inert
            get_pool().map_ordered(fetch, stale)
        shards |= self.cluster.remote_available_shards(idx.name)
        return sorted(shards)

    def _client(self, node):
        return self.client_factory(node.uri)
