"""Control-plane messaging.

Reference: broadcast.go — the `broadcaster` interface {SendSync, SendAsync,
SendTo} (:30), `Serializer` (:24), and the 16-message taxonomy (:55-72).
The reference carries these as type-prefixed protobuf over gossip/memberlist
or HTTP POST /internal/cluster/message (server.go:695-705).

Here the taxonomy is identical but the wire format is a type-tagged JSON
object POSTed to the same endpoint — schema/membership traffic is tiny and
host-side, so JSON over the DCN control plane is the TPU-native tradeoff
(ICI stays reserved for the data plane).
"""

import json
import threading


class MessageType:
    """(reference: message type constants broadcast.go:55-72)"""

    CREATE_SHARD = "create-shard"
    CREATE_INDEX = "create-index"
    DELETE_INDEX = "delete-index"
    CREATE_FIELD = "create-field"
    DELETE_FIELD = "delete-field"
    CREATE_VIEW = "create-view"
    DELETE_VIEW = "delete-view"
    CLUSTER_STATUS = "cluster-status"
    RESIZE_INSTRUCTION = "resize-instruction"
    RESIZE_INSTRUCTION_COMPLETE = "resize-instruction-complete"
    SET_COORDINATOR = "set-coordinator"
    UPDATE_COORDINATOR = "update-coordinator"
    NODE_STATE = "node-state"
    RECALCULATE_CACHES = "recalculate-caches"
    NODE_EVENT = "node-event"
    NODE_STATUS = "node-status"

    ALL = (
        CREATE_SHARD, CREATE_INDEX, DELETE_INDEX, CREATE_FIELD, DELETE_FIELD,
        CREATE_VIEW, DELETE_VIEW, CLUSTER_STATUS, RESIZE_INSTRUCTION,
        RESIZE_INSTRUCTION_COMPLETE, SET_COORDINATOR, UPDATE_COORDINATOR,
        NODE_STATE, RECALCULATE_CACHES, NODE_EVENT, NODE_STATUS,
    )


class Serializer:
    """Type-tagged JSON encoding (reference: Serializer broadcast.go:24 +
    encoding/proto/proto.go:29)."""

    @staticmethod
    def marshal(msg_type, payload):
        if msg_type not in MessageType.ALL:
            raise ValueError(f"unknown message type: {msg_type}")
        return json.dumps({"type": msg_type, "payload": payload}).encode()

    @staticmethod
    def unmarshal(data):
        d = json.loads(data.decode() if isinstance(data, bytes) else data)
        msg_type = d.get("type")
        if msg_type not in MessageType.ALL:
            raise ValueError(f"unknown message type: {msg_type}")
        return msg_type, d.get("payload")


class NopBroadcaster:
    """(reference: NopBroadcaster broadcast.go:41)"""

    def send_sync(self, msg_type, payload):
        return None

    def send_async(self, msg_type, payload):
        return None

    def send_to(self, node, msg_type, payload):
        return None


class HTTPBroadcaster:
    """Delivers control messages to peers over HTTP POST
    /internal/cluster/message (reference: server.go:695-705 +
    http/client.go:1017 SendMessage).

    send_sync posts to every peer and raises on any failure; send_async
    posts on a background thread per peer, best-effort (the reference's
    gossip queue semantics)."""

    def __init__(self, cluster, client_factory):
        self.cluster = cluster
        self.client_factory = client_factory

    def _post(self, node, data):
        client = self.client_factory(node.uri)
        client.send_message(data)

    def send_to(self, node, msg_type, payload):
        self._post(node, Serializer.marshal(msg_type, payload))

    def send_sync(self, msg_type, payload):
        data = Serializer.marshal(msg_type, payload)
        errors = []
        for node in self.cluster.peers():
            try:
                self._post(node, data)
            except Exception as e:  # collect; sync = all-or-error
                errors.append((node.id, e))
        if errors:
            raise RuntimeError(f"broadcast failures: {errors}")

    def send_async(self, msg_type, payload):
        data = Serializer.marshal(msg_type, payload)
        for node in self.cluster.peers():
            t = threading.Thread(
                target=self._try_post, args=(node, data), daemon=True)
            t.start()

    def _try_post(self, node, data):
        try:
            self._post(node, data)
        except Exception:
            pass
