"""Placement hashing.

Reference: cluster.go:871-960 — FNV-1a over (index, shard-BE8) mod 256
partitions, then Lamping/Veach jump consistent hashing to pick the primary
node for a partition. ModHasher is the deterministic test stand-in
(test/cluster.go)."""

_FNV_OFFSET = 0xcbf29ce484222325
_FNV_PRIME = 0x100000001b3
_MASK64 = (1 << 64) - 1


def fnv1a64(data):
    """64-bit FNV-1a (reference: hash/fnv, cluster.partition)."""
    h = _FNV_OFFSET
    for b in data:
        h ^= b
        h = (h * _FNV_PRIME) & _MASK64
    return h


def partition_hash(index, shard, partition_n):
    """partition = FNV-1a(index ++ shard_be8) % partitionN
    (reference: cluster.partition cluster.go:871)."""
    data = index.encode() + int(shard).to_bytes(8, "big")
    return fnv1a64(data) % partition_n


class JmpHasher:
    """Jump consistent hash (reference: jmphasher cluster.go:948,
    Lamping & Veach 2014)."""

    def hash(self, key, n):
        key = int(key) & _MASK64
        b, j = -1, 0
        while j < n:
            b = j
            key = (key * 2862933555777941757 + 1) & _MASK64
            j = int(float(b + 1) * (float(1 << 31) / float((key >> 33) + 1)))
        return b


class ModHasher:
    """key % n — deterministic placement for tests
    (reference: test/cluster.go ModHasher)."""

    def hash(self, key, n):
        return int(key) % n
