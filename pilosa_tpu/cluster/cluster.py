"""Cluster: node list, shard placement, states, topology persistence, and
resize source planning.

Reference: cluster.go — defaultPartitionN=256 (:44), placement
(:871-960), cluster states (:45-50), Topology persisted in `.topology`
(:1580-1692), resize fragment sources (fragSources :784).

Placement: partition = FNV-1a(index, shard) % partitionN; primary node =
jump_hash(partition, len(nodes)); owners = replicaN successive nodes on the
ring. Nodes sort by ID so every node computes identical placement.
"""

import json
import os
import threading

from .hash import JmpHasher, partition_hash
from .node import (
    CLUSTER_STATE_DEGRADED,
    CLUSTER_STATE_NORMAL,
    CLUSTER_STATE_STARTING,
    NODE_STATE_DOWN,
    NODE_STATE_READY,
    Node,
)

DEFAULT_PARTITION_N = 256  # reference: defaultPartitionN cluster.go:44


class ClusterError(Exception):
    pass


class Cluster:
    def __init__(self, nodes=None, local_id=None, replica_n=1,
                 partition_n=DEFAULT_PARTITION_N, hasher=None, path=None):
        """nodes: list[Node]; local_id: this process's node id; path: data
        dir for `.topology` persistence (None = ephemeral)."""
        self.nodes = sorted(nodes or [], key=lambda n: n.id)
        self.local_id = local_id
        self.replica_n = max(1, int(replica_n))
        self.partition_n = int(partition_n)
        self.hasher = hasher or JmpHasher()
        self.path = path
        self.state = CLUSTER_STATE_NORMAL if self.nodes else \
            CLUSTER_STATE_STARTING
        self._lock = threading.RLock()
        if self.nodes and not any(n.is_coordinator for n in self.nodes):
            self.nodes[0].is_coordinator = True
        # Gossiped shard map (reference: availableShards carried in gossip
        # NodeStatus / CreateShardMessage, cluster.go): peers PUSH their
        # per-index available shards over the control plane so queries
        # never do per-peer shard-discovery HTTP in the steady state.
        # Entries MERGE by union (pushes are unordered best-effort async;
        # a reordered older full list must not shrink the set — shrink
        # events, resize/delete, invalidate the whole map instead), and
        # seeds carry a timestamp: a seed older than SHARD_MAP_TTL is
        # re-fetched once, bounding the staleness window of a LOST push.
        self._remote_shards = {}   # node_id -> {index: set(shards)}
        self._shards_synced = {}   # (node_id, index) -> monotonic seed time

    # -- identity ------------------------------------------------------------

    @property
    def local_node(self):
        for n in self.nodes:
            if n.id == self.local_id:
                return n
        return None

    @property
    def coordinator(self):
        for n in self.nodes:
            if n.is_coordinator:
                return n
        return self.nodes[0] if self.nodes else None

    def is_coordinator(self):
        node = self.local_node
        return node is not None and node.is_coordinator

    def node(self, node_id):
        for n in self.nodes:
            if n.id == node_id:
                return n
        return None

    def peers(self):
        """Every node but this one."""
        return [n for n in self.nodes if n.id != self.local_id]

    # -- placement (reference: cluster.go:871-960) ---------------------------

    def partition(self, index, shard):
        return partition_hash(index, shard, self.partition_n)

    def partition_nodes(self, partition_id, nodes=None):
        """replicaN successive owners on the ring for a partition."""
        nodes = self.nodes if nodes is None else nodes
        if not nodes:
            return []
        replica_n = min(self.replica_n, len(nodes))
        primary = self.hasher.hash(partition_id, len(nodes))
        return [nodes[(primary + i) % len(nodes)] for i in range(replica_n)]

    def shard_nodes(self, index, shard, nodes=None):
        """Owner nodes for (index, shard) — primary first
        (reference: cluster.ShardNodes cluster.go:883)."""
        return self.partition_nodes(self.partition(index, shard), nodes)

    def owns_shard(self, node_id, index, shard):
        return any(n.id == node_id for n in self.shard_nodes(index, shard))

    def shards_by_node(self, index, shards):
        """{node: [shards]} using each shard's first NON-DOWN owner (reads
        stay available in DEGRADED state by routing straight to a live
        replica instead of timing out on the primary; reference:
        executor.shardsByNode + the replica-retry path executor.go:2490).
        Falls back to the primary when every owner is down so the caller
        surfaces a clean error."""
        out = {}
        for shard in shards:
            owners = self.shard_nodes(index, shard)
            if not owners:
                continue
            live = [n for n in owners if n.state != NODE_STATE_DOWN]
            out.setdefault((live or owners)[0], []).append(shard)
        return out

    def local_shards(self, index, shards):
        return [s for s in shards if self.owns_shard(self.local_id, index, s)]

    # -- state (reference: determineClusterState cluster.go:571-583) ---------

    def determine_state(self):
        with self._lock:
            if self.state == "RESIZING":
                # only the resize manager may leave RESIZING (finalize,
                # abort, or failure) — health transitions must not unblock
                # queries mid-stream
                return self.state
            down = sum(1 for n in self.nodes if n.state == NODE_STATE_DOWN)
            if down == 0:
                self.state = CLUSTER_STATE_NORMAL
            elif down < self.replica_n:
                # reads still servable from replicas
                self.state = CLUSTER_STATE_DEGRADED
            else:
                self.state = CLUSTER_STATE_STARTING
            return self.state

    def set_node_state(self, node_id, state):
        with self._lock:
            node = self.node(node_id)
            if node is not None and node.state != state:
                node.state = state
                # a node that flapped may have grown shards while its
                # pushes were lost; force one re-seed fetch on next query
                self._shards_synced = {
                    key: ts for key, ts in self._shards_synced.items()
                    if key[0] != node_id}
                self.determine_state()
                return True
        return False

    def live_nodes(self):
        return [n for n in self.nodes if n.state == NODE_STATE_READY]

    # -- gossiped shard map ---------------------------------------------------

    #: seconds before a peer's seed is re-fetched once — bounds how long a
    #: LOST async push can leave the map stale (the reference's gossip
    #: re-converges continuously; this is the pull-side analog)
    SHARD_MAP_TTL = 30.0

    def set_remote_shards(self, node_id, index, shards):
        """Merge a peer's pushed per-index shard list. UNION, not replace:
        async pushes can arrive out of order and an older (smaller) full
        list must not erase shards a newer push already delivered. Shard
        sets only shrink on resize/delete, which invalidate the whole map
        (invalidate_shard_map / drop_remote_index)."""
        import time as _time

        with self._lock:  # RLock: record nests under the same lock
            self.record_remote_shards(node_id, index, shards)
            self._shards_synced[(node_id, index)] = _time.monotonic()

    def record_remote_shards(self, node_id, index, shards):
        """Union shards into a peer's map WITHOUT marking it seeded:
        used by the write path for read-your-writes — a node that just
        forwarded an import slice KNOWS the target now holds that shard
        and must not wait for the target's async push (which can lag the
        ack and leave an immediate query silently missing the shard).
        The seed fetch still runs for peers never fully synced."""
        with self._lock:
            self._remote_shards.setdefault(node_id, {}).setdefault(
                index, set()).update(int(s) for s in shards)

    def shards_synced(self, node_id, index):
        import time as _time

        with self._lock:
            ts = self._shards_synced.get((node_id, index))
            return ts is not None \
                and _time.monotonic() - ts < self.SHARD_MAP_TTL

    def remote_available_shards(self, index):
        """Union of every peer's last-pushed shards for an index."""
        out = set()
        with self._lock:
            for per_index in self._remote_shards.values():
                out |= per_index.get(index, set())
        return out

    def remove_remote_shard(self, index, shard):
        """Drop ONE advertised shard from every peer's record (reference:
        Field.RemoveAvailableShard field.go:513, reached via DELETE
        remote-available-shards handler.go:316 — stale-advertisement
        cleanup). The next gossip push from a peer that really has the
        shard re-adds it."""
        with self._lock:
            for per_index in self._remote_shards.values():
                per_index.get(index, set()).discard(int(shard))

    def drop_remote_index(self, index):
        with self._lock:
            for per_index in self._remote_shards.values():
                per_index.pop(index, None)
            self._shards_synced = {
                key: ts for key, ts in self._shards_synced.items()
                if key[1] != index}

    def invalidate_shard_map(self):
        """Drop everything learned about peers' shards. Called on ANY
        membership/placement change (node join/leave, resize completion):
        a resize re-sorts the node list, so EXISTING nodes can gain shards
        (streamed outside the push hooks) and stale entries would serve
        silently incomplete shard lists. The next query re-seeds each peer
        once."""
        with self._lock:
            self._remote_shards.clear()
            self._shards_synced.clear()

    # -- membership changes ---------------------------------------------------

    def add_node(self, node):
        """(reference: cluster.addNode; triggers resize planning upstream)"""
        with self._lock:
            if self.node(node.id) is not None:
                return False
            self.nodes = sorted(self.nodes + [node], key=lambda n: n.id)
            if not any(n.is_coordinator for n in self.nodes):
                self.nodes[0].is_coordinator = True
            self.save_topology()
            self.invalidate_shard_map()
            return True

    def remove_node(self, node_id):
        with self._lock:
            node = self.node(node_id)
            if node is None:
                return False
            self.nodes = [n for n in self.nodes if n.id != node_id]
            if node.is_coordinator and self.nodes:
                self.nodes[0].is_coordinator = True
            self.save_topology()
            self.invalidate_shard_map()
            return True

    # -- topology persistence (reference: cluster.go:1580-1692) ---------------

    @property
    def topology_path(self):
        return os.path.join(self.path, ".topology") if self.path else None

    def save_topology(self):
        if not self.topology_path:
            return
        os.makedirs(self.path, exist_ok=True)
        tmp = self.topology_path + ".tmp"
        with open(tmp, "w") as f:
            json.dump({"nodeIDs": [n.id for n in self.nodes],
                       "nodes": [n.to_json() for n in self.nodes]}, f)
        os.replace(tmp, self.topology_path)

    def load_topology(self):
        """Returns True when a topology file existed and was loaded."""
        if not self.topology_path or not os.path.exists(self.topology_path):
            return False
        with open(self.topology_path) as f:
            data = json.load(f)
        if data.get("nodes"):
            self.nodes = sorted(
                (Node.from_json(d) for d in data["nodes"]),
                key=lambda n: n.id)
        return True

    # -- resize planning (reference: fragSources cluster.go:784) --------------

    def frag_sources(self, old_nodes, new_nodes, index, shards):
        """For a topology change old->new: {dest_node_id: [(shard,
        source_node_id)]} listing every shard a node must fetch and a live
        node that owned it before. Used by resize jobs (§3.5)."""
        old_sorted = sorted(old_nodes, key=lambda n: n.id)
        new_sorted = sorted(new_nodes, key=lambda n: n.id)
        out = {}
        for shard in shards:
            p = self.partition(index, shard)
            old_owner_ids = {
                n.id for n in self.partition_nodes(p, old_sorted)}
            for dest in self.partition_nodes(p, new_sorted):
                if dest.id in old_owner_ids:
                    continue  # already has it
                sources = [
                    n for n in old_sorted
                    if n.id in old_owner_ids and n.state == NODE_STATE_READY]
                if not sources:
                    raise ClusterError(
                        f"no available source for shard {shard} of {index}")
                out.setdefault(dest.id, []).append((shard, sources[0].id))
        return out

    # -- serialization ---------------------------------------------------------

    def status_json(self):
        return {"state": self.state,
                "nodes": [n.to_json() for n in self.nodes]}

    def nodes_json(self):
        return [n.to_json() for n in self.nodes]
