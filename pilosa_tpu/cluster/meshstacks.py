"""Mesh-resident leaf stacks: the SPMD data plane's device cache.

Legacy SPMD steps (cluster/spmd.py) re-materialize every leaf per query:
gather the [seg_len, W] host block from this node's fragments, upload it,
assemble the globally-sharded array, throw it away. This module keeps the
assembled global-array HANDLE resident per process, validated by the same
per-shard (fragment uid, generation) fingerprint the local stack cache
uses (exec/stacked._fragment_gens), so a warm step re-uses device memory
instead of re-gathering and re-uploading.

Per-process divergence is SAFE by construction: a global array built with
`jax.make_array_from_process_local_data` only materializes this process's
addressable shards — when process A hits its cache and process B rebuilds
after a local write, the collective still reads A's (validated, unchanged)
block and B's fresh one. Only the program sequence and shapes must agree
across processes, and those are carried in the step itself.

Carried per entry, PR-4/8/10 style:
- HBM ledger: device bytes per (index, field, "mesh", repr) flow into the
  `hbm_stack_bytes` gauge, pool-tagged "mesh" so /metrics separates
  mesh-resident bytes from the local serving pools.
- heat: every probe (hit or miss) bumps the PR-8 fragment heat ledger —
  mesh demand makes a fragment an admission candidate like local demand.
- compressed reprs: blocks stay DENSE on device (every process must trace
  the identical collective program, and csigs are per-process state that
  cannot ride it), but each entry records the PR-10 chooser's verdict
  (dense/sparse/RLE + projected bytes) for its own block, so /debug/spmd
  shows what a future compressed collective plane would save per node.

Shadow support: `shadow_probe` compares a freshly gathered block against
the cached entry's content digest without touching the serving path —
the --spmd-serve shadow mode's divergence detector.
"""

import threading
import zlib
from collections import OrderedDict

from ..core.view import VIEW_BSI_GROUP_PREFIX, VIEW_STANDARD
from ..utils.logger import NopLogger
from ..utils.stats import global_stats

#: per-process device-byte budget for mesh-resident blocks (dense
#: [seg_len, W] uint32 arrays; LRU past this)
DEFAULT_MAX_BYTES = 256 * 1024 * 1024


def entry_key(wire_leaf):
    """Hashable cache key component from a step's wire leaf entry
    (["row", f, r] | ["bsicond", f, op, vals] | ["timerow", f, r, views])."""
    kind = wire_leaf[0]
    if kind == "bsicond":
        # vals is a scalar for single-threshold ops (v > 0) and a list
        # for between — hash both forms
        _, field_name, op, vals = wire_leaf
        if isinstance(vals, (list, tuple)):
            vals = tuple(vals)
        return ("bsicond", field_name, op, vals)
    if kind == "timerow":
        _, field_name, row_id, views = wire_leaf
        return ("timerow", field_name, int(row_id), tuple(views))
    _, field_name, row_id = wire_leaf
    return ("row", field_name, int(row_id))


def leaf_views(wire_leaf):
    """(field, view names) a wire leaf reads — its gen-validation
    surface. A bsicond leaf is derived from the field's BSI plane group,
    so that view's fragment generations cover it."""
    kind = wire_leaf[0]
    field_name = wire_leaf[1]
    if kind == "bsicond":
        return field_name, (VIEW_BSI_GROUP_PREFIX + field_name,)
    if kind == "timerow":
        return field_name, tuple(wire_leaf[3])
    return field_name, (VIEW_STANDARD,)


class MeshStackCache:
    """LRU of globally-sharded leaf arrays keyed by
    (index, leaf, seg_len, my_shards), validated per hit against this
    process's fragment generations. One instance per SpmdDataPlane."""

    def __init__(self, logger=None, max_bytes=DEFAULT_MAX_BYTES):
        self.logger = logger or NopLogger()
        self.max_bytes = int(max_bytes)
        self._lock = threading.Lock()
        # key -> [gens, array, nbytes, repr_kind, digest, repr_meta]
        self._entries = OrderedDict()
        self._ledger = {}  # (index, field, repr) -> bytes
        self.bytes = 0
        self.hits = 0
        self.misses = 0
        self.invalidations = 0
        self.evictions = 0
        self.shadow_probes = 0
        self.shadow_hits = 0
        self.shadow_mismatches = 0

    # -- validation ----------------------------------------------------------

    def gens(self, idx, wire_leaf, my_shards):
        """Per-(view, shard) (fragment uid, generation) stamp for this
        process's block of one leaf — exec/stacked._fragment_gens'
        invalidation contract applied to the leaf's whole view surface.
        None when the field vanished (caller skips the cache; the
        defensive gather contributes zero planes either way)."""
        field_name, views = leaf_views(wire_leaf)
        field = idx.field(field_name) if idx is not None else None
        if field is None:
            return None
        gens = []
        for view_name in views:
            view = field.view(view_name)
            for shard in my_shards:
                frag = view.fragment(shard) if view is not None else None
                gens.append((-1, -1) if frag is None
                            else (frag.uid, frag.generation))
        return tuple(gens)

    # -- probe / fill --------------------------------------------------------

    def get(self, key, gens):
        """Cached global array for `key`, or None. A generation mismatch
        invalidates the entry (this process's fragments changed; peers
        validate their own blocks independently)."""
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and entry[0] == gens \
                    and entry[1] is not None:
                self._entries.move_to_end(key)
                self.hits += 1
                arr = entry[1]
            else:
                if entry is not None:
                    # stale gens, or an array-less shadow-parked entry
                    # left behind by a runtime shadow→on switch
                    if entry[0] != gens:
                        self.invalidations += 1
                    self._drop_locked(key, entry)
                self.misses += 1
                arr = None
        self._heat_bump(key)
        return arr

    def put(self, key, gens, array, local_block):
        """Admit one assembled global array. `local_block` is this
        process's host block — analyzed once for the PR-10 repr verdict
        and digested for shadow comparison; device bytes charged are the
        dense block this process holds."""
        repr_kind, repr_meta = self._classify(local_block)
        nbytes = int(local_block.size) * 4
        digest = zlib.crc32(local_block.tobytes())
        with self._lock:
            old = self._entries.pop(key, None)
            if old is not None:
                self._drop_locked(key, old, popped=True)
            self._entries[key] = [gens, array, nbytes, repr_kind,
                                  digest, repr_meta]
            self.bytes += nbytes
            self._ledger_add(key, nbytes, repr_kind)
            while self.bytes > self.max_bytes and len(self._entries) > 1:
                vkey, ventry = self._entries.popitem(last=False)
                self.evictions += 1
                self._drop_locked(vkey, ventry, popped=True)

    def shadow_probe(self, key, gens, local_block):
        """--spmd-serve shadow: would the cache have served this block
        correctly? Populates on miss, digests-compares on hit; the
        serving path keeps using the fresh gather either way."""
        self.shadow_probes += 1
        digest = zlib.crc32(local_block.tobytes())
        with self._lock:
            entry = self._entries.get(key)
            if entry is not None and entry[0] == gens:
                self._entries.move_to_end(key)
                self.shadow_hits += 1
                if entry[4] != digest:
                    self.shadow_mismatches += 1
                    self.logger.printf(
                        "spmd shadow: mesh cache divergence on %s "
                        "(gens matched, content differs)", key[:2])
                return
            if entry is not None:
                self.invalidations += 1
                self._drop_locked(key, entry)
        # miss: park the digest + repr verdict (no device array — shadow
        # must not hold device memory the serving path never reads)
        repr_kind, repr_meta = self._classify(local_block)
        with self._lock:
            self._entries[key] = [gens, None, 0, repr_kind, digest,
                                  repr_meta]

    def invalidate_index(self, index_name):
        """Drop every entry of one index (DDL hook)."""
        with self._lock:
            for key in [k for k in self._entries if k[0] == index_name]:
                entry = self._entries.pop(key)
                self.invalidations += 1
                self._drop_locked(key, entry, popped=True)

    # -- internals -----------------------------------------------------------

    def _drop_locked(self, key, entry, popped=False):
        if not popped:
            self._entries.pop(key, None)
        self.bytes -= entry[2]
        self._ledger_add(key, -entry[2], entry[3])

    def _ledger_add(self, key, delta, repr_kind):
        """(index, field, "mesh", repr) ledger in lockstep with the pool
        bytes, mirrored into the hbm_stack_bytes gauge (caller holds
        self._lock)."""
        if delta == 0:
            return  # shadow-parked entries hold no device bytes
        index_name, leaf = key[0], key[1]
        lkey = (index_name, leaf[1], repr_kind)
        new = self._ledger.get(lkey, 0) + delta
        if new <= 0:
            self._ledger.pop(lkey, None)
            new = 0
        else:
            self._ledger[lkey] = new
        global_stats.gauge("hbm_stack_bytes", new, {
            "index": index_name, "field": leaf[1], "pool": "mesh",
            "repr": repr_kind})

    def _heat_bump(self, key):
        from ..utils import workload as _workload

        leaf = key[1]
        try:
            _, views = leaf_views(leaf)
            _workload.heat_bump(key[0], leaf[1], views[0])
        except Exception:  # noqa: BLE001 — heat is observability only
            pass

    @staticmethod
    def _classify(local_block):
        """PR-10 chooser verdict for this process's dense block: what
        repr it WOULD compress to, and the projected bytes — carried as
        metadata (the device copy stays dense; see module doc)."""
        try:
            from ..ops import containers as _containers

            info = _containers.analyze(local_block)
            s, w = local_block.shape
            kind = _containers.choose(info, s, w)
            return kind, {
                "density": round(info["density"], 6),
                "dense_bytes": info["dense_bytes"],
                "sparse_bytes": info["sparse_bytes"],
                "rle_bytes": info["rle_bytes"],
            }
        except Exception:  # noqa: BLE001 — metadata only
            return "dense", {}

    # -- observability -------------------------------------------------------

    def stats(self):
        with self._lock:
            by_repr = {}
            for entry in self._entries.values():
                by_repr[entry[3]] = by_repr.get(entry[3], 0) + 1
            return {
                "entries": len(self._entries),
                "bytes": self.bytes,
                "max_bytes": self.max_bytes,
                "hits": self.hits,
                "misses": self.misses,
                "invalidations": self.invalidations,
                "evictions": self.evictions,
                "reprs": by_repr,
                "shadow": {
                    "probes": self.shadow_probes,
                    "hits": self.shadow_hits,
                    "mismatches": self.shadow_mismatches,
                },
                "ledger": [
                    {"index": i, "field": f, "repr": r, "bytes": b}
                    for (i, f, r), b in sorted(self._ledger.items())],
            }
