"""Workload observatory: query fingerprints, fragment heat, SLO burn.

PRs 2-6 built the instruments — per-query profiles, latency histograms,
the HBM/kernel ledgers, device-link health — but nothing aggregated them
by WORKLOAD: which query shapes recur, which fragments are actually hot,
and whether serving is inside its latency objectives. This module is
that aggregation layer, the substrate the adaptive-execution work
(ROADMAP item 3) reads its decisions from. Three subsystems:

1. Query fingerprinting. Every parsed PQL query normalizes to a
   literal-free shape (pql/ast.Call.shape: call names, field names,
   condition operators, and nesting survive; row ids, values, and time
   bounds collapse to `_`), prefixed with the index name and hashed.
   `Count(Row(f=3))` and `Count(Row(f=9))` share one fingerprint;
   `Count(Row(g=3))` does not. A bounded LRU table keeps rolling stats
   per fingerprint — count, wall histogram (log buckets shared with
   utils/stats), dispatch/cache deltas, strategy distribution from the
   executor's decision points, misestimate count from exec/plan — served
   at GET /debug/workload ranked by frequency, total wall, and
   misestimate rate.

2. Fragment heat. Every stacked-cache hit/miss and host-fallback access
   bumps an exponentially decayed counter per (index, field, view):
   heat(t) = heat(t0) * 0.5^((t-t0)/half_life) + 1 per touch, decayed
   lazily on touch/read so the hot path is one dict update. GET
   /debug/heat cross-references heat against the PR-4 HBM ledger and
   emits the two lists a cache-admission policy needs: hot-but-not-
   resident (admission/prefetch candidates) and resident-but-cold
   (eviction candidates). Top-N heat exports as fragment_heat gauges.

3. SLO burn rate. `--slo "query=50ms@p99"` declares an objective: 99%
   of the `query` op family under 50ms. The engine samples the EXISTING
   cumulative timing histograms (utils/stats) into a ring of
   (time, total, over-threshold) points and computes the error-budget
   burn rate over a fast and a slow window — burn 1.0 consumes the
   budget exactly at the sustainable rate; burn N consumes it N times
   too fast. Both windows over threshold => one slo.burn_alert flight-
   recorder event (edge-triggered, re-armed when the fast window
   recovers). Served at GET /debug/slo + slo_burn_rate{objective,window}
   gauges. Thresholds snap UP to the nearest histogram bucket bound.

All three are module-level singletons (like exec/plan and flightrec):
the HTTP layer, the API roll-up, and the executor share them without
threading instance handles through every layer. `reset()` restores a
pristine state for tests.
"""

import bisect
import hashlib
import threading
import time
from collections import OrderedDict

from .stats import TIMING_BUCKETS, _quantile, global_stats, tail_count

#: per-fingerprint rolling-stats entries retained (LRU beyond this)
DEFAULT_MAX_FINGERPRINTS = 512
#: fragment heat halves every this many seconds without a touch
DEFAULT_HEAT_HALF_LIFE = 300.0
#: decayed heat at/above which a fragment counts as "hot" (~one touch
#: within the last half-life)
HEAT_HOT_MIN = 1.0
#: top-N heat entries exported as fragment_heat gauges
HEAT_GAUGE_TOP = 10
#: SLO burn-rate windows (seconds): fast catches an active incident,
#: slow filters one-off spikes; an alert needs BOTH over threshold
SLO_FAST_WINDOW = 60.0
SLO_SLOW_WINDOW = 600.0
#: default burn rate that trips slo.burn_alert (budget consumed 6x
#: faster than sustainable)
DEFAULT_BURN_ALERT_THRESHOLD = 6.0
#: successive engine samples closer than this reuse the last one (the
#: gauge_fns would otherwise resample per scrape per objective)
SLO_MIN_SAMPLE_INTERVAL = 1.0


#: shape -> digest memo: a serving workload repeats a small set of
#: shapes, so the blake2b drops out of the steady-state per-query cost.
#: Unbounded growth is a fingerprint-cardinality attack, so it clears
#: wholesale at the cap (dict reads are GIL-atomic; no lock needed).
_FP_CACHE_MAX = 4096
_fp_cache = {}


def fingerprint(index_name, query):
    """(hash, shape) for a parsed Query: the literal-free shape prefixed
    with the index name, hashed to 16 hex chars. Stable across processes
    (content hash, no seed) so fleet-wide logs correlate."""
    global _fp_cache
    shape = f"{index_name}:{query.shape()}"
    fp = _fp_cache.get(shape)
    if fp is None:
        fp = hashlib.blake2b(
            shape.encode("utf-8"), digest_size=8).hexdigest()
        if len(_fp_cache) >= _FP_CACHE_MAX:
            _fp_cache = {}
        _fp_cache[shape] = fp
    return fp, shape


# --------------------------------------------------------------- table


class WorkloadTable:
    """Bounded per-fingerprint rolling stats, LRU-evicted: a burst of
    one-off shapes can displace idle entries but the hot shapes re-enter
    on their next query with only history lost, never correctness."""

    def __init__(self, max_entries=DEFAULT_MAX_FINGERPRINTS):
        self._lock = threading.Lock()
        self._entries = OrderedDict()  # fingerprint -> mutable entry
        self.max_entries = max_entries
        self.evicted = 0
        self.total_queries = 0

    def record(self, fp, shape, index, wall_seconds, deltas=None,
               strategies=None, misestimates=0, batch=0):
        """Fold one finished query into its fingerprint's entry.
        `deltas` carries the per-query stacked-counter diffs
        (dispatches, cache_hits, cache_misses, bytes_materialized);
        `batch` is the fused-batch size the query rode (0 or 1 = solo),
        so the table answers which shapes actually coalesce."""
        deltas = deltas or {}
        with self._lock:
            self.total_queries += 1
            e = self._entries.get(fp)
            if e is None:
                e = self._entries[fp] = {
                    "fingerprint": fp, "shape": shape, "index": index,
                    "count": 0, "wall_sum": 0.0,
                    "buckets": [0] * (len(TIMING_BUCKETS) + 1),
                    "dispatches": 0, "cache_hits": 0, "cache_misses": 0,
                    "bytes_materialized": 0, "misestimates": 0,
                    "strategies": {},
                    "batched_queries": 0, "batch_size_sum": 0,
                }
                while len(self._entries) > self.max_entries:
                    self._entries.popitem(last=False)
                    self.evicted += 1
            else:
                self._entries.move_to_end(fp)
            e["count"] += 1
            e["wall_sum"] += wall_seconds
            e["buckets"][
                bisect.bisect_left(TIMING_BUCKETS, wall_seconds)] += 1
            for k in ("dispatches", "cache_hits", "cache_misses",
                      "bytes_materialized"):
                e[k] += int(deltas.get(k, 0))
            e["misestimates"] += misestimates
            for s in strategies or ():
                e["strategies"][s] = e["strategies"].get(s, 0) + 1
            if batch > 1:
                e["batched_queries"] += 1
                e["batch_size_sum"] += int(batch)
            e["last_seen"] = time.time()

    def _render(self, e):
        hits, misses = e["cache_hits"], e["cache_misses"]
        return {
            "fingerprint": e["fingerprint"],
            "shape": e["shape"],
            "index": e["index"],
            "count": e["count"],
            "total_wall_seconds": round(e["wall_sum"], 6),
            "p50_ms": round(
                _quantile(e["count"], e["buckets"], 0.50) * 1000, 3),
            "p99_ms": round(
                _quantile(e["count"], e["buckets"], 0.99) * 1000, 3),
            "dispatches": e["dispatches"],
            "bytes_materialized": e["bytes_materialized"],
            "cache_hit_ratio": round(hits / (hits + misses), 4)
            if hits + misses else None,
            "strategies": dict(sorted(e["strategies"].items())),
            "batched_queries": e["batched_queries"],
            "avg_batch_size": round(
                e["batch_size_sum"] / e["batched_queries"], 2)
            if e["batched_queries"] else None,
            "misestimates": e["misestimates"],
            "misestimate_rate": round(e["misestimates"] / e["count"], 4),
            "idle_seconds": round(time.time() - e["last_seen"], 1),
        }

    def hits(self, fp):
        """Completed-query count for one fingerprint (0 when unseen or
        evicted). NOT an access (no LRU touch): exec/fusion.py probes
        this on every enabled query for its compile-admission gate, and
        a probe that refreshed recency would let the gate itself keep
        cold shapes resident."""
        with self._lock:
            e = self._entries.get(fp)
            return e["count"] if e is not None else 0

    def snapshot(self, top=20):
        """GET /debug/workload: the three rankings the optimizer loop
        reads — what runs most, what costs most, what the cost model
        gets wrong. top=0 returns counters only (peer roll-up shape)."""
        with self._lock:
            rendered = [self._render(e) for e in self._entries.values()]
        out = {
            "total_queries": self.total_queries,
            "unique_fingerprints": len(rendered),
            "max_fingerprints": self.max_entries,
            "evicted": self.evicted,
        }
        top = max(0, int(top))
        out["by_frequency"] = sorted(
            rendered, key=lambda e: -e["count"])[:top]
        out["by_total_wall"] = sorted(
            rendered, key=lambda e: -e["total_wall_seconds"])[:top]
        out["by_misestimate_rate"] = sorted(
            (e for e in rendered if e["misestimates"]),
            key=lambda e: -e["misestimate_rate"])[:top]
        return out

    def summary(self):
        """Compact roll-up for /status observability."""
        with self._lock:
            top = max(self._entries.values(), key=lambda e: e["count"]) \
                if self._entries else None
            return {
                "total_queries": self.total_queries,
                "unique_fingerprints": len(self._entries),
                "evicted": self.evicted,
                "top": {"fingerprint": top["fingerprint"],
                        "shape": top["shape"], "count": top["count"]}
                if top else None,
            }

    def clear(self):
        with self._lock:
            self._entries.clear()
            self.evicted = 0
            self.total_queries = 0


# ---------------------------------------------------------------- heat


class HeatLedger:
    """Exponentially decayed access counts per (index, field, view).
    Decay is lazy — each entry stores (value, as_of) and decays only
    when touched or read — so a bump is one dict lookup, one pow, one
    store, cheap enough to ride every cache probe."""

    def __init__(self, half_life=DEFAULT_HEAT_HALF_LIFE):
        self._lock = threading.Lock()
        self._heat = {}  # (index, field, view) -> [value, as_of, touches]
        self.half_life = half_life
        self._gauged = set()  # keys currently exported as gauges

    def bump(self, index, field, view, amount=1.0, now=None):
        if now is None:
            now = time.time()
        key = (index, field, view)
        with self._lock:
            e = self._heat.get(key)
            if e is None:
                self._heat[key] = [amount, now, 1]
            else:
                dt = now - e[1]
                # sub-ms gaps skip the pow AND the as_of advance (the
                # un-decayed sliver stays banked in dt); the bias is
                # bounded by 1ms/half_life — unmeasurable at 300s
                if dt > 0.001:
                    e[0] *= 0.5 ** (dt / self.half_life)
                    e[1] = now
                e[0] += amount
                e[2] += 1

    def _decayed(self, e, now):
        dt = now - e[1]
        return e[0] * 0.5 ** (dt / self.half_life) if dt > 0 else e[0]

    def value(self, index, field, view, now=None):
        """Current decayed heat of ONE key (0.0 if untracked) — the
        cache benefit score's read path, so it must stay a single dict
        lookup plus one pow."""
        with self._lock:
            e = self._heat.get((index, field, view))
            if e is None:
                return 0.0
            return self._decayed(e, time.time() if now is None else now)

    def note_admitted(self, index, field, now=None):
        """An admission driven by hot_but_not_resident landed: scale the
        (index, field) group's summed heat down to exactly HEAT_HOT_MIN.
        Below the threshold the group can't re-recommend (the list
        converges, ISSUE 13 satellite); pinning AT the threshold — not
        zero — keeps the fresh admission out of resident_but_cold, which
        would nominate it for instant eviction."""
        if now is None:
            now = time.time()
        with self._lock:
            group = [(k, e) for k, e in self._heat.items()
                     if k[0] == index and k[1] == field]
            total = sum(self._decayed(e, now) for _, e in group)
            if total <= HEAT_HOT_MIN or total <= 0:
                return
            scale = HEAT_HOT_MIN / total
            for _, e in group:
                e[0] = self._decayed(e, now) * scale
                e[1] = now

    def snapshot(self, now=None):
        """All tracked keys with their current (decayed) heat, hottest
        first."""
        if now is None:
            now = time.time()
        with self._lock:
            out = [{"index": k[0], "field": k[1], "view": k[2],
                    "heat": round(self._decayed(e, now), 4),
                    "touches": e[2],
                    "idle_seconds": round(now - e[1], 1)}
                   for k, e in self._heat.items()]
        out.sort(key=lambda e: -e["heat"])
        return out

    def report(self, hbm_snapshot, top=50, now=None):
        """GET /debug/heat: heat joined against the HBM ledger. The two
        derived lists are the optimizer's inputs — hot_but_not_resident
        (demanded but evicted or never admitted: admission/prefetch
        candidates, hottest first) and resident_but_cold (holding HBM
        without recent demand: eviction candidates, largest first). The
        join is at (index, field) — heat per view is summed; residency
        comes from the ledger's by_index_field attribution."""
        entries = self.snapshot(now=now)
        heat_by_if = {}
        for e in entries:
            k = (e["index"], e["field"])
            heat_by_if[k] = heat_by_if.get(k, 0.0) + e["heat"]
        resident = {}
        for r in (hbm_snapshot or {}).get("by_index_field", ()):
            k = (r["index"], r["field"])
            resident[k] = resident.get(k, 0) + r["bytes"]
        hot_not_resident = sorted(
            (self._price_admission(i, f, h)
             for (i, f), h in heat_by_if.items()
             if h >= HEAT_HOT_MIN and (i, f) not in resident),
            key=lambda e: -e["heat"])
        resident_cold = sorted(
            ({"index": i, "field": f, "bytes": b,
              "heat": round(heat_by_if.get((i, f), 0.0), 4)}
             for (i, f), b in resident.items()
             if heat_by_if.get((i, f), 0.0) < HEAT_HOT_MIN),
            key=lambda e: -e["bytes"])
        self._export_gauges(entries[:HEAT_GAUGE_TOP])
        top = max(0, int(top))
        return {
            "half_life_seconds": self.half_life,
            "hot_threshold": HEAT_HOT_MIN,
            "tracked": len(entries),
            "entries": entries[:top],
            "hot_but_not_resident": hot_not_resident[:top],
            "hot_but_not_resident_total": len(hot_not_resident),
            "resident_but_cold": resident_cold[:top],
            "resident_but_cold_total": len(resident_cold),
        }

    @staticmethod
    def _price_admission(index, field, heat):
        """One hot_but_not_resident candidate, priced by what admission
        would ACTUALLY cost in HBM: the container ledger's compressed
        bytes from the fragment's last build (the chooser is
        deterministic in the data, so the last build predicts the
        next). Fragments never built carry no estimate — the candidate
        still lists, unpriced."""
        e = {"index": index, "field": field, "heat": round(heat, 4)}
        try:
            from ..ops import containers

            est = containers.field_estimate(index, field)
        except Exception:  # pragma: no cover - observability only
            est = None
        if est is not None:
            e["est_bytes"] = est["bytes"]
            e["est_dense_bytes"] = est["dense_bytes"]
            e["compression_ratio"] = est["ratio"]
            e["reprs"] = est["reprs"]
        return e

    def _export_gauges(self, hottest):
        """fragment_heat gauges for the current top-N; keys that fell
        out of the top-N zero (a frozen stale gauge reads as hot)."""
        current = set()
        for e in hottest:
            key = (e["index"], e["field"], e["view"])
            current.add(key)
            global_stats.gauge("fragment_heat", e["heat"], {
                "index": key[0], "field": key[1], "view": key[2]})
        for key in self._gauged - current:
            global_stats.gauge("fragment_heat", 0.0, {
                "index": key[0], "field": key[1], "view": key[2]})
        self._gauged = current

    def summary(self):
        entries = self.snapshot()
        return {"tracked": len(entries),
                "hottest": {k: entries[0][k]
                            for k in ("index", "field", "view", "heat")}
                if entries else None}

    def clear(self):
        with self._lock:
            self._heat.clear()
            self._gauged.clear()


# ----------------------------------------------------------------- SLO


class SloObjective:
    """One parsed `name=50ms@p99` spec. `name` selects a timing family:
    `query` = every query_op_seconds series, `query.Count` = one op,
    `http` = every http_request_seconds series, anything else = an exact
    timing-family name in the registry."""

    def __init__(self, name, threshold_seconds, quantile):
        if not 0.0 < quantile < 1.0:
            raise ValueError(f"SLO quantile must be in (0, 1): {quantile}")
        self.name = name
        self.threshold_seconds = threshold_seconds
        self.quantile = quantile
        self.budget = 1.0 - quantile  # allowed over-threshold fraction

    def spec(self):
        t = self.threshold_seconds
        thr = f"{t:g}s" if t >= 1.0 else f"{t * 1000:g}ms"
        return f"{self.name}={thr}@p{self.quantile * 100:g}"


def parse_slo(spec):
    """Parse `query=50ms@p99` / `http=250ms@p99.9` / `query.GroupBy=1s@p95`
    into an SloObjective. Raises ValueError with the offending spec."""
    try:
        name, rest = spec.split("=", 1)
        threshold, q = rest.split("@", 1)
        name = name.strip()
        threshold = threshold.strip().lower()
        if threshold.endswith("ms"):
            seconds = float(threshold[:-2]) / 1000.0
        elif threshold.endswith("us"):
            seconds = float(threshold[:-2]) / 1e6
        elif threshold.endswith("s"):
            seconds = float(threshold[:-1])
        else:
            raise ValueError("threshold needs a unit (us/ms/s)")
        q = q.strip().lower()
        if not q.startswith("p"):
            raise ValueError("quantile must look like p99")
        quantile = float(q[1:]) / 100.0
        if not name or seconds <= 0:
            raise ValueError("empty name or non-positive threshold")
        return SloObjective(name, seconds, quantile)
    except ValueError:
        raise
    except Exception as e:
        raise ValueError(f"bad SLO spec {spec!r} "
                         f"(want name=50ms@p99): {e}") from e


class SloEngine:
    """Multi-window error-budget burn over the cumulative histograms.

    Each sample() reads (total, over-threshold) cumulative counts per
    objective from the stats registry and appends them to a ring; a
    window's burn rate is the over-threshold fraction of the requests
    that arrived inside the window, divided by the objective's budget.
    Cumulative counters mean no per-request work lands here — the engine
    costs one histogram scan per sample, rate-limited to
    SLO_MIN_SAMPLE_INTERVAL."""

    def __init__(self, stats=None):
        self._lock = threading.Lock()
        self._stats = stats or global_stats
        self.objectives = []
        self.burn_threshold = DEFAULT_BURN_ALERT_THRESHOLD
        self._samples = {}   # objective name -> list of (t, total, bad)
        self._alerting = {}  # objective name -> bool
        self._burns = {}     # objective name -> {"fast": x, "slow": y}
        self._last_sample = 0.0
        self.alerts_total = 0
        self._gauges_registered = set()

    def configure(self, objectives, burn_threshold=None):
        with self._lock:
            self.objectives = list(objectives)
            if burn_threshold is not None:
                self.burn_threshold = float(burn_threshold)
            for o in self.objectives:
                self._samples.setdefault(o.name, [])
                self._alerting.setdefault(o.name, False)
        # scrape-time gauges: evaluating one triggers a (rate-limited)
        # sample, so /metrics alone keeps the burn rates fresh
        for o in self.objectives:
            for window in ("fast", "slow"):
                reg_key = (o.name, window)
                if reg_key in self._gauges_registered:
                    continue
                self._gauges_registered.add(reg_key)
                self._stats.gauge_fn(
                    "slo_burn_rate",
                    (lambda name=o.name, w=window:
                     self.sample().get(name, {}).get(w, 0.0)),
                    {"objective": o.name, "window": window})

    def _cumulative(self, objective):
        """(total, over-threshold) requests to date for one objective's
        timing family."""
        hists = self._stats.histograms()
        total = bad = 0
        name = objective.name
        family, op = "query_op_seconds", None
        if name == "http":
            family = "http_request_seconds"
        elif name.startswith("query."):
            op = name.split(".", 1)[1]
        elif name != "query":
            family = name
        for (fam, tags), (count, _sum, buckets) in hists.items():
            if fam != family:
                continue
            if op is not None and ("op", op) not in tags:
                continue
            total += count
            bad += tail_count(buckets, objective.threshold_seconds)
        return total, bad

    def sample(self, now=None, force=False):
        """Take one (rate-limited) sample per objective, update burn
        rates, fire/clear alerts. Returns {objective: {window: burn}}."""
        from . import flightrec

        if now is None:
            now = time.time()
        with self._lock:
            if not self.objectives:
                return {}
            if not force and now - self._last_sample \
                    < SLO_MIN_SAMPLE_INTERVAL:
                return dict(self._burns)
            self._last_sample = now
            objectives = list(self.objectives)
        alerts = []
        for o in objectives:
            total, bad = self._cumulative(o)
            with self._lock:
                ring = self._samples[o.name]
                ring.append((now, total, bad))
                # keep one point older than the slow window as the diff
                # base; everything older than that is dead weight
                while len(ring) > 2 and ring[1][0] <= now - SLO_SLOW_WINDOW:
                    ring.pop(0)
                burns = {
                    "fast": self._burn(ring, o, now, SLO_FAST_WINDOW),
                    "slow": self._burn(ring, o, now, SLO_SLOW_WINDOW)}
                self._burns[o.name] = burns
                firing = (burns["fast"] > self.burn_threshold
                          and burns["slow"] > self.burn_threshold)
                if firing and not self._alerting[o.name]:
                    self._alerting[o.name] = True
                    self.alerts_total += 1
                    alerts.append((o, burns))
                elif not firing and self._alerting[o.name] \
                        and burns["fast"] <= self.burn_threshold:
                    self._alerting[o.name] = False
        for o, burns in alerts:  # outside the lock: recorder, logger
            flightrec.record(
                "slo.burn_alert", objective=o.name, spec=o.spec(),
                burn_fast=round(burns["fast"], 2),
                burn_slow=round(burns["slow"], 2),
                threshold=self.burn_threshold)
            self._stats.count("slo_burn_alerts", 1, {"objective": o.name})
            from . import incident

            incident.maybe_trigger(
                "slo_burn", objective=o.name, spec=o.spec(),
                burn_fast=round(burns["fast"], 2),
                burn_slow=round(burns["slow"], 2))
        with self._lock:
            return dict(self._burns)

    @staticmethod
    def _burn(ring, objective, now, window):
        """Burn over one window: over-threshold fraction of the requests
        inside the window / budget. Caller holds the lock."""
        cutoff = now - window
        base = ring[0]
        for point in ring:
            if point[0] > cutoff:
                break
            base = point
        tip = ring[-1]
        d_total = tip[1] - base[1]
        d_bad = tip[2] - base[2]
        if d_total <= 0:
            return 0.0
        return (d_bad / d_total) / objective.budget

    def _exemplars_for(self, objective):
        """Over-threshold histogram exemplars for one objective — the
        direct link from a burning objective to assembled traces
        (GET /debug/traces/{traceID}). Empty unless the registry has
        exemplar capture enabled (--metrics-exemplars)."""
        from .stats import registry_of

        reg = registry_of(self._stats)
        if not hasattr(reg, "exemplars"):
            return []
        name = objective.name
        family, op = "query_op_seconds", None
        if name == "http":
            family = "http_request_seconds"
        elif name.startswith("query."):
            op = name.split(".", 1)[1]
        elif name != "query":
            family = name
        out = []
        for (_fam, tags), per in reg.exemplars(family).items():
            if op is not None and ("op", op) not in tags:
                continue
            for le, e in per.items():
                if e["value"] > objective.threshold_seconds:
                    out.append({"traceID": e["traceID"],
                                "seconds": round(e["value"], 6),
                                "le": le, "tags": dict(tags),
                                "timestamp": e["timestamp"]})
        out.sort(key=lambda e: -e["seconds"])
        return out[:8]

    def snapshot(self):
        """GET /debug/slo."""
        burns = self.sample()
        exemplars = {o.name: self._exemplars_for(o)
                     for o in list(self.objectives)}
        with self._lock:
            out = {
                "windows": {"fast_seconds": SLO_FAST_WINDOW,
                            "slow_seconds": SLO_SLOW_WINDOW},
                "burn_alert_threshold": self.burn_threshold,
                "alerts_total": self.alerts_total,
                "objectives": [],
            }
            for o in self.objectives:
                ring = self._samples.get(o.name) or []
                tip = ring[-1] if ring else (0.0, 0, 0)
                entry = {
                    "name": o.name,
                    "spec": o.spec(),
                    "threshold_ms": round(o.threshold_seconds * 1000, 3),
                    "quantile": o.quantile,
                    "error_budget": round(o.budget, 6),
                    "total_requests": tip[1],
                    "over_threshold": tip[2],
                    "burn_rate": {
                        k: round(v, 4)
                        for k, v in burns.get(o.name, {}).items()},
                    "alerting": self._alerting.get(o.name, False),
                }
                if exemplars.get(o.name):
                    entry["exemplars"] = exemplars[o.name]
                out["objectives"].append(entry)
        return out

    def summary(self):
        """Compact roll-up for /status observability."""
        burns = self.sample()
        with self._lock:
            worst = max((b.get("fast", 0.0) for b in burns.values()),
                        default=0.0)
            return {
                "objectives": len(self.objectives),
                "alerting": sorted(
                    n for n, a in self._alerting.items() if a),
                "alerts_total": self.alerts_total,
                "worst_fast_burn": round(worst, 4),
            }

    def clear(self):
        with self._lock:
            self.objectives = []
            self._samples.clear()
            self._alerting.clear()
            self._burns.clear()
            self._last_sample = 0.0
            self.alerts_total = 0


# ----------------------------------------------- module state + hot path

_table = WorkloadTable()
_heat = HeatLedger()
_slo = SloEngine()
_local = threading.local()


def table():
    return _table


def heat():
    return _heat


def slo():
    return _slo


def heat_bump(index, field, view, amount=1.0):
    """Per-access hot-path entry (stacked cache probes, host fallbacks).
    Module-level alias so call sites pay one attribute lookup."""
    _heat.bump(index, field, view, amount=amount)


class _QueryCtx:
    __slots__ = ("fingerprint", "shape", "index", "strategies",
                 "misestimates", "batch")

    def __init__(self, fp, shape, index):
        self.fingerprint = fp
        self.shape = shape
        self.index = index
        self.strategies = []
        self.misestimates = 0
        self.batch = 0  # fused-batch size this query rode (0/1 = solo)


def begin_query(index_name, query):
    """Fingerprint one parsed query and open its thread-local recording
    context (exec/executor.py, once per non-remote query). Decision
    points contribute via note_strategy()/note_misestimate() until
    end_query() folds everything into the table."""
    fp, shape = fingerprint(index_name, query)
    ctx = _QueryCtx(fp, shape, index_name)
    _local.ctx = ctx
    return ctx


def end_query(ctx, wall_seconds, deltas=None):
    """Close the context and fold the finished query into the table.
    The fingerprint stays in take-last position for the SLOW QUERY log
    line (same thread, same handoff pattern as utils/profile)."""
    if getattr(_local, "ctx", None) is ctx:
        _local.ctx = None
    _local.last_fingerprint = ctx.fingerprint
    _table.record(ctx.fingerprint, ctx.shape, ctx.index, wall_seconds,
                  deltas=deltas, strategies=ctx.strategies,
                  misestimates=ctx.misestimates, batch=ctx.batch)


def abort_query(ctx):
    """Discard an open context WITHOUT recording: a batch member that
    falls back mid-gather re-enters through the per-query path, which
    opens (and records) its own context — recording both would double
    count the shape."""
    if getattr(_local, "ctx", None) is ctx:
        _local.ctx = None


def note_strategy(op, strategy):
    """Executor decision points report the strategy actually taken; the
    table keeps the distribution per fingerprint."""
    ctx = getattr(_local, "ctx", None)
    if ctx is not None:
        ctx.strategies.append(f"{op}={strategy}")


def note_batch(n):
    """The batch paths report how many queries shared the in-flight
    query's fused dispatch (workload-table batch attribution)."""
    ctx = getattr(_local, "ctx", None)
    if ctx is not None:
        ctx.batch = max(ctx.batch, int(n))


def note_misestimate():
    """exec/plan's misestimate flagging attributes to the in-flight
    query's fingerprint."""
    ctx = getattr(_local, "ctx", None)
    if ctx is not None:
        ctx.misestimates += 1


def current_fingerprint():
    ctx = getattr(_local, "ctx", None)
    return ctx.fingerprint if ctx is not None else None


def current_index():
    """Index of the in-flight query on THIS thread (None outside one) —
    exec/plan's misestimate feedback uses it to strike container-repr
    overrides at (index, field) granularity."""
    ctx = getattr(_local, "ctx", None)
    return ctx.index if ctx is not None else None


def last_fingerprint():
    """The fingerprint of the last query finished on THIS thread (the
    slow-query log reads it after the executor returns)."""
    return getattr(_local, "last_fingerprint", None)


def fingerprint_hits(fp):
    """How many queries of this shape have COMPLETED — the frequency
    signal exec/fusion.py's compile-admission gate reads (a fingerprint
    below --fusion-min-hits never pays a trace+compile)."""
    return _table.hits(fp)


def maybe_sample_slo():
    """Cheap per-query tick (server/api.py): with objectives configured,
    take a rate-limited burn sample so alerts fire from serving traffic
    alone, without waiting for a metrics scrape. The rate-limit check is
    lock-free (GIL-atomic float read) so the common case costs one
    comparison; sample() re-checks under its lock."""
    if _slo.objectives and \
            time.time() - _slo._last_sample >= SLO_MIN_SAMPLE_INTERVAL:
        _slo.sample()


def configure(max_fingerprints=None, heat_half_life=None):
    """Apply server knobs (cli.py)."""
    if max_fingerprints is not None:
        _table.max_entries = max(1, int(max_fingerprints))
    if heat_half_life is not None:
        _heat.half_life = max(0.001, float(heat_half_life))


def configure_slo(specs, burn_threshold=None, logger=None):
    """Parse and install --slo objectives; bad specs raise ValueError
    (a misspelled objective silently tracking nothing is worse than a
    failed boot)."""
    objectives = [parse_slo(s) for s in specs]
    _slo.configure(objectives, burn_threshold=burn_threshold)
    if logger is not None and objectives:
        logger.printf("SLO objectives: %s (burn alert > %gx)",
                      ", ".join(o.spec() for o in objectives),
                      _slo.burn_threshold)
    return objectives


def reset():
    """Pristine module state (tests)."""
    _table.clear()
    _table.max_entries = DEFAULT_MAX_FINGERPRINTS
    _heat.clear()
    _heat.half_life = DEFAULT_HEAT_HALF_LIFE
    _slo.clear()
    _slo.burn_threshold = DEFAULT_BURN_ALERT_THRESHOLD
    _local.ctx = None
    _local.last_fingerprint = None
