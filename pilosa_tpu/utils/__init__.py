"""Cross-cutting utilities: stats, tracing, logging (reference: stats/,
tracing/, logger/)."""

from .stats import StatsClient, global_stats
