"""Per-query profiles: the span tree + counters that explain ONE query.

The stats registry (utils/stats.py) answers "how is the server doing";
the tracer (utils/tracing.py) answers "what happened, globally". Neither
answers the production question "why was THIS query slow" — on this
architecture that means: how many pairwise dispatches, how long the
process-wide dispatch lock was contended, kernel wall time, stacked-cache
hits/misses, bytes materialized to device, and per-node fan-out timings
(Dapper, Sigelman et al. 2010, is the shape; the reference's
long-query-time log is the trigger).

A `QueryProfile` is begun by `api.Query` when the request asked for it
(`?profile=true`) or when the server has a slow-query threshold
configured. While active it is registered by trace id, so finished spans
from ANY thread of the query — executor spans, stacked kernel spans,
cluster fan-out spans (which share the trace id via
`tracing.with_span` / the X-Pilosa-Trace-Id headers) — are captured into
the profile by the tracing span-sink without the tracer needing to be
non-nop. With no profile active and the nop tracer installed, no span
objects are ever allocated: the default hot path is unchanged.

Finished profiles land in a bounded ring (`recent()`, served at
GET /debug/queries) and are stashed per-thread for the HTTP handler to
attach to the response (`take_last()`).
"""

import threading
import time
from collections import deque

from . import tracing

#: spans retained per profile; past this the tree truncates (counted in
#: the `spans_dropped` tag) rather than growing without bound
MAX_PROFILE_SPANS = 512

#: finished profiles retained for GET /debug/queries
MAX_RECENT = 128

_active = {}  # trace_id -> QueryProfile (only while the query runs)
_recent = deque(maxlen=MAX_RECENT)
_recent_lock = threading.Lock()
_local = threading.local()


class QueryProfile:
    """Span tree + counter accumulator for one query."""

    def __init__(self, index, query, slow_threshold=None):
        self.index = index
        self.query = query
        self.slow_threshold = slow_threshold
        self.start = time.time()
        self.duration = None
        self.slow = False
        self._lock = threading.Lock()
        self._spans = []
        self._dropped = 0
        self._tags = {}
        # the query's root span: created unconditionally (even under the
        # nop tracer) so every start_span below it allocates a real child
        self.root = tracing.Span(
            "query", tracing.new_trace_id(), tracing.new_trace_id(),
            None, {"index": index})

    # -- collection (called from arbitrary query threads) --------------------

    def record(self, span):
        with self._lock:
            if len(self._spans) < MAX_PROFILE_SPANS:
                self._spans.append(span)
            else:
                self._dropped += 1

    def add(self, key, value):
        """Accumulate a numeric profile tag (lock waits, dispatch counts,
        byte totals...)."""
        with self._lock:
            self._tags[key] = self._tags.get(key, 0) + value

    def set_tag(self, key, value):
        with self._lock:
            self._tags[key] = value

    def note(self, key, value):
        """Append to a LIST-valued profile tag (e.g. the per-op strategy
        records the executor's decision points emit) — `add` sums and
        `set_tag` overwrites; ordered events need neither."""
        with self._lock:
            self._tags.setdefault(key, []).append(value)

    def tag(self, key, default=None):
        with self._lock:
            return self._tags.get(key, default)

    def spans_snapshot(self):
        """Finished spans recorded so far (cross-node assembly reads the
        local fan-out spans from here to estimate per-node clock skew)."""
        with self._lock:
            return list(self._spans)

    # -- lifecycle -----------------------------------------------------------

    def begin(self):
        """Register so span finishes (any thread) feed this profile."""
        _active[self.root.trace_id] = self
        return self

    def finish(self):
        """Close the root span, unregister, and publish: into the recent
        ring always, and to this thread's `take_last` stash."""
        self.root.finish()
        self.duration = self.root.duration
        _active.pop(self.root.trace_id, None)
        # the root span bypasses start_span, so index it here — this is
        # what lets GET /debug/traces/{trace_id} resolve a profiled query
        # (e.g. from a metrics exemplar) after it finished
        tracing.index_span(self.root)
        if self.slow_threshold is not None \
                and self.duration > self.slow_threshold:
            self.slow = True
        snapshot = self.to_dict()
        with _recent_lock:
            _recent.append(snapshot)
        _local.last = snapshot
        return snapshot

    # -- output --------------------------------------------------------------

    def to_dict(self):
        """JSON shape: flat tags + the span TREE rooted at the query span.
        Spans whose parent was dropped (or finished after the root) attach
        to the root so nothing silently disappears."""
        with self._lock:
            spans = list(self._spans)
            tags = dict(self._tags)
            dropped = self._dropped
        nodes = {}
        for s in spans:
            nodes[s.span_id] = dict(
                name=s.name, start=s.start, duration=s.duration,
                tags=dict(s.tags), children=[])
        root = dict(name=self.root.name, start=self.root.start,
                    duration=self.root.duration, tags=dict(self.root.tags),
                    children=[])
        for s in spans:
            parent = nodes.get(s.parent_id)
            (parent["children"] if parent is not None
             else root["children"]).append(nodes[s.span_id])
        out = {
            "index": self.index,
            "query": self.query[:500],
            "traceID": self.root.trace_id,
            "start": self.start,
            "duration": self.duration,
            "slow": self.slow,
            "tags": tags,
            "spans": root,
        }
        if dropped:
            out["spansDropped"] = dropped
        return out


def begin(index, query, slow_threshold=None):
    return QueryProfile(index, query,
                        slow_threshold=slow_threshold).begin()


def current():
    """The active profile owning this thread's span context, or None.
    Dispatch hot paths call this per device launch; with no profile
    active anywhere it is one empty-dict check."""
    if not _active:
        return None
    span = tracing.current_span()
    if span is None:
        return None
    return _active.get(span.trace_id)


def _deliver(span):
    """tracing span-sink: route a finished span to its query's profile."""
    if not _active:
        return
    prof = _active.get(span.trace_id)
    if prof is not None:
        prof.record(span)


tracing.set_span_sink(_deliver)


def take_last():
    """Pop the profile dict the current thread's last profiled query
    produced (the HTTP handler attaches it to the response)."""
    last = getattr(_local, "last", None)
    _local.last = None
    return last


def recent():
    """Newest-first finished profiles (GET /debug/queries)."""
    with _recent_lock:
        return list(reversed(_recent))


def clear_recent():
    with _recent_lock:
        _recent.clear()
