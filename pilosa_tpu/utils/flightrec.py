"""Black-box flight recorder + stall watchdog.

The serving path can wedge in ways the query-level profiles (PR 2) never
see: a device tunnel hang leaves every attempt "missed the probe/full
deadline" with zero forensic detail (BENCH_r05.json). This module is the
always-on, crash-surviving half of observability:

- `FlightRecorder` — a fixed-size, thread-safe ring of structured events
  (timestamp, kind, tags). Producers call the module-level `record()`
  which is a lock + deque append (~µs); when the ring is full the oldest
  event drops and a counter remembers how many were lost. Served at
  `GET /debug/flightrecorder` and dumped to the log on fatal signals and
  watchdog stalls — the last N things the process did, readable after
  the fact like an aircraft flight recorder.
- `Watchdog` — a registry of in-flight ops (dispatches holding the
  process-wide _DISPATCH_LOCK, whole queries) polled by one daemon
  thread. An op running past its deadline trips ONCE: increments the
  `watchdog_stalls` counter, records a `watchdog.stall` event, and dumps
  every thread stack plus the recorder tail to the log — directly
  targeting the r05-style wedge where the only evidence was silence.
- `install_crash_handler()` — `faulthandler` for C-level fatal signals
  (SIGSEGV/SIGABRT/...: all thread stacks to stderr even when the
  interpreter is wedged) plus a chained Python SIGTERM handler that logs
  the recorder tail before the process dies.
- `start_debug_server()` — a minimal stdlib HTTP server exposing the
  recorder on an ephemeral localhost port, for processes that run no
  PilosaHTTPServer (the bench child): the orchestrator fetches the tail
  BEFORE killing a hung attempt.

Everything is optional and cheap when off: `configure(0)` disables the
ring (record() becomes one attribute check), and with no watchdog
configured `watch_begin()` returns None without taking a lock.

Event taxonomy (kind prefixes; see docs/architecture.md):
  dispatch.*   kernel launches under the dispatch lock (stacked.py)
  cache.*      stack-cache put/evict/invalidate (the HBM ledger's feed)
  workpool.*   pool saturation (every worker busy with a queue backlog)
  query.slow   queries past --long-query-time
  http.5xx     handler failures
  cluster.*    membership transitions, resize lifecycle, replay drops
  watchdog.*   stall trips
  slo.burn_alert  error-budget burn over threshold in BOTH windows
                  (utils/workload.py SloEngine; edge-triggered)
  spmd.*       collective step lifecycle (cluster/spmd.py): step_announce
               when the coordinator assigns a step-seq and fans it out,
               step_enter/step_exit on EVERY process around the collective
               program (tags: seq, ok), stream_gap at the ONSET of a
               step-stream sequence gap (later steps queued, expected seq
               missing — previously invisible until resync), stream_resync
               when the gap times out and the runner skips ahead, and
               straggler (edge-triggered, coordinator-side) when one
               node's per-phase step wall exceeds the peer median by the
               configured factor in the merged /debug/spmd/steps
               timeline. The enter/exit pairing is what lets bench.py
               distinguish "peer never entered the collective" from
               "collective hung".
  fusion.compile  whole-plan (and mesh collective) program compiles with
                  wall time; mesh programs carry a `mesh` tag
"""

import collections
import faulthandler
import http.server
import itertools
import json
import logging
import signal
import sys
import threading
import time
import traceback

from .stats import global_stats

DEFAULT_RING_SIZE = 2048

_log = logging.getLogger("pilosa_tpu.flightrec")


class FlightRecorder:
    """Fixed-size ring of (seq, ts, kind, tags) events.

    One lock, one deque append per event: cheap enough to leave on in
    the dispatch path (µs vs ms-scale kernels). `size=0` disables —
    producers see `enabled` False and skip the call entirely."""

    def __init__(self, size=DEFAULT_RING_SIZE):
        self.size = int(size)
        self.enabled = self.size > 0
        self._lock = threading.Lock()
        self._events = collections.deque(maxlen=self.size or 1)
        self._seq = 0

    def record(self, kind, tags=None):
        if not self.enabled:
            return
        evt = (time.time(), kind, tags or {})
        with self._lock:
            self._seq += 1
            self._events.append((self._seq, ) + evt)

    @property
    def dropped(self):
        with self._lock:
            return self._seq - len(self._events)

    def snapshot(self, limit=None):
        """Events oldest-first as dicts (the exposition format)."""
        with self._lock:
            events = list(self._events)
            total = self._seq
        if limit is not None and limit >= 0:
            events = events[-limit:]
        return {
            "size": self.size,
            "total_events": total,
            "dropped": total - len(self._events) if self.size else total,
            "events": [
                {"seq": seq, "ts": ts, "kind": kind, "tags": tags}
                for seq, ts, kind, tags in events
            ],
        }

    def tail(self, n=64):
        return self.snapshot(limit=n)

    def format_tail(self, n=64):
        """Human-readable tail for log dumps."""
        snap = self.snapshot(limit=n)
        lines = [
            "flight recorder tail (%d/%d events, %d dropped):"
            % (len(snap["events"]), snap["total_events"], snap["dropped"])
        ]
        for e in snap["events"]:
            tags = " ".join(
                f"{k}={v}" for k, v in sorted(e["tags"].items()))
            lines.append("  #%d %.6f %s %s"
                         % (e["seq"], e["ts"], e["kind"], tags))
        return "\n".join(lines)

    def clear(self):
        with self._lock:
            self._events.clear()
            self._seq = 0


# ------------------------------------------------------------- module recorder

_recorder = FlightRecorder()


def get_recorder():
    return _recorder


def configure(size):
    """Install a fresh ring of the given size (0 disables). Returns it."""
    global _recorder
    _recorder = FlightRecorder(size)
    return _recorder


def record(kind, **tags):
    """The producer fast path: one attribute check when disabled."""
    rec = _recorder
    if rec.enabled:
        rec.record(kind, tags)


def snapshot(limit=None):
    return _recorder.snapshot(limit=limit)


def tail(n=64):
    return _recorder.tail(n)


# ------------------------------------------------------------------ stack dump

def format_all_stacks():
    """Every thread's Python stack (same shape as GET /debug/pprof/threads)."""
    frames = sys._current_frames()
    names = {t.ident: t.name for t in threading.enumerate()}
    out = []
    for ident, frame in frames.items():
        out.append("thread %s (%s):" % (names.get(ident, "?"), ident))
        out.append("".join(traceback.format_stack(frame)).rstrip())
    return "\n".join(out)


class _PrintfAdapter:
    """Adapt the repo's printf-style Logger (utils/logger.py) to the
    stdlib error/exception calls used here."""

    def __init__(self, inner):
        self._inner = inner

    def error(self, fmt, *args):
        self._inner.printf(fmt, *args)

    def exception(self, fmt, *args):
        self._inner.printf(fmt + "\n" + traceback.format_exc(), *args)


def _coerce_logger(logger):
    if logger is None:
        return _log
    if hasattr(logger, "error"):
        return logger
    if hasattr(logger, "printf"):
        return _PrintfAdapter(logger)
    return _log


def dump(logger=None, reason="dump"):
    """Recorder tail + all thread stacks to the log, one call."""
    logger = _coerce_logger(logger)
    logger.error("flightrec dump (%s)\n%s\n%s",
                 reason, _recorder.format_tail(), format_all_stacks())


# -------------------------------------------------------------------- watchdog

class _Op:
    __slots__ = ("kind", "start", "deadline", "thread", "tags", "tripped")

    def __init__(self, kind, start, deadline, thread, tags):
        self.kind = kind
        self.start = start
        self.deadline = deadline
        self.thread = thread
        self.tags = tags
        self.tripped = False


class Watchdog:
    """Trips when a registered op (a dispatch holding _DISPATCH_LOCK, a
    whole query) runs past its deadline: counter + event + full dump.

    begin/end are two dict ops under a lock — cheap enough for every
    dispatch. Each op trips at most once; it stays registered so the log
    shows how long past the deadline it eventually ran (or never ended)."""

    def __init__(self, deadline, logger=None, poll_interval=None):
        if deadline <= 0:
            raise ValueError("watchdog deadline must be > 0")
        self.deadline = float(deadline)
        self.logger = _coerce_logger(logger)
        self.poll_interval = poll_interval or min(
            max(self.deadline / 4.0, 0.01), 1.0)
        self.stalls = 0
        self._ops = {}
        self._lock = threading.Lock()
        self._tokens = itertools.count(1)
        self._stop = threading.Event()
        self._thread = None

    # -- op registry ---------------------------------------------------------

    def begin_op(self, kind, deadline=None, **tags):
        op = _Op(kind, time.monotonic(), deadline or self.deadline,
                 threading.current_thread().name, tags)
        token = next(self._tokens)
        with self._lock:
            self._ops[token] = op
        return token

    def end_op(self, token):
        if token is None:
            return
        with self._lock:
            self._ops.pop(token, None)

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        if self._thread is not None:
            return self
        self._thread = threading.Thread(
            target=self._loop, name="pilosa-watchdog", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None

    # -- detection -----------------------------------------------------------

    def check(self, now=None):
        """One poll pass; factored out of the loop so tests (and the
        bench stall leg) can force a check without waiting for the
        thread. Returns the ops that tripped on THIS pass."""
        now = time.monotonic() if now is None else now
        tripped = []
        with self._lock:
            for op in self._ops.values():
                if not op.tripped and now - op.start > op.deadline:
                    op.tripped = True
                    tripped.append(op)
        for op in tripped:
            self._trip(op, now)
        return tripped

    def _trip(self, op, now):
        self.stalls += 1
        overdue = now - op.start
        tags = {"kind": op.kind}
        global_stats.count("watchdog_stalls", 1, tags)
        # the device-link state splits "stall" into its two causes at a
        # glance: DOWN/DEGRADED = dead tunnel, LIVE = lock contention or
        # genuinely slow work (lazy import — devhealth imports stats too)
        from . import devhealth as _devhealth

        link_state = _devhealth.state()
        evt = dict(op.tags, kind=op.kind, thread=op.thread,
                   running_seconds=round(overdue, 3),
                   deadline_seconds=op.deadline,
                   device_link_state=link_state)
        if _recorder.enabled:
            _recorder.record("watchdog.stall", evt)
        self.logger.error(
            "WATCHDOG STALL: op %r on thread %s running %.3fs "
            "(deadline %.3fs) device_link=%s tags=%s\n%s\n%s",
            op.kind, op.thread, overdue, op.deadline, link_state, op.tags,
            _recorder.format_tail(), format_all_stacks())
        from . import incident as _incident

        # evt's "kind" is the stalled OP's kind — rename so it cannot
        # collide with the trigger kind parameter. A wedged collective
        # (an spmd.* op: entered but never exited past its deadline) is
        # its own incident class: collective_stall bundles additionally
        # capture every peer's step ring via the spmd collector.
        trigger = "collective_stall" if op.kind.startswith("spmd.") \
            else "watchdog_stall"
        _incident.maybe_trigger(
            trigger,
            **{("op" if k == "kind" else k): v for k, v in evt.items()})

    def open_ops(self, now=None):
        """Snapshot of every in-flight op (incident bundles + debug):
        what was holding the dispatch lock / running a query at the
        moment of the anomaly."""
        now = time.monotonic() if now is None else now
        with self._lock:
            ops = list(self._ops.values())
        return [dict(op.tags, kind=op.kind, thread=op.thread,
                     running_seconds=round(now - op.start, 3),
                     deadline_seconds=op.deadline, tripped=op.tripped)
                for op in ops]

    def _loop(self):
        while not self._stop.wait(self.poll_interval):
            try:
                self.check()
            except Exception:  # noqa: BLE001 — the watchdog must not die
                self.logger.exception("watchdog check failed")


_watchdog = None


def get_watchdog():
    return _watchdog


def configure_watchdog(deadline, logger=None):
    """Install and start the process watchdog (0/None uninstalls)."""
    global _watchdog
    old = _watchdog
    _watchdog = Watchdog(deadline, logger=logger).start() \
        if deadline and deadline > 0 else None
    if old is not None:
        old.stop()
    return _watchdog


def stop_watchdog():
    configure_watchdog(0)


def watch_begin(kind, deadline=None, **tags):
    """Register an in-flight op; None token when no watchdog is running."""
    wd = _watchdog
    if wd is None:
        return None
    return wd.begin_op(kind, deadline=deadline, **tags)


def watch_end(token):
    if token is None:
        return
    wd = _watchdog
    if wd is not None:
        wd.end_op(token)


# --------------------------------------------------------------- crash handler

_crash_installed = False


def install_crash_handler(logger=None):
    """Fatal-signal forensics, installed once per process:

    - `faulthandler.enable()`: C-level handler dumps every thread stack
      to stderr on SIGSEGV/SIGFPE/SIGABRT/SIGBUS/SIGILL — works even
      when the interpreter can't run Python code.
    - a Python SIGTERM handler that logs the recorder tail + stacks,
      then CHAINS to whatever handler was installed before (cli.py owns
      SIGHUP for TLS reload; we must not clobber other handlers).

    Main-thread only (signal.signal requirement); a no-op elsewhere."""
    global _crash_installed
    if _crash_installed:
        return
    if threading.current_thread() is not threading.main_thread():
        return
    try:
        faulthandler.enable()
    except Exception:  # noqa: BLE001 — stderr may be closed under tests
        pass

    logger = _coerce_logger(logger)
    prev = signal.getsignal(signal.SIGTERM)

    def _on_term(signum, frame):
        try:
            dump(logger, reason="SIGTERM")
        except Exception:  # noqa: BLE001 — never mask the shutdown
            pass
        try:
            # synchronous: the process is dying, there is no later
            from . import incident as _incident

            _incident.maybe_trigger("fatal_signal", sync=True,
                                    signal="SIGTERM")
        except Exception:  # noqa: BLE001 — never mask the shutdown
            pass
        if callable(prev) and prev not in (signal.SIG_IGN, signal.SIG_DFL):
            prev(signum, frame)
        else:
            signal.signal(signal.SIGTERM, prev or signal.SIG_DFL)
            signal.raise_signal(signal.SIGTERM)

    try:
        signal.signal(signal.SIGTERM, _on_term)
        _crash_installed = True
    except (ValueError, OSError):
        pass


# ---------------------------------------------------------- bench debug server

class _DebugHandler(http.server.BaseHTTPRequestHandler):
    def do_GET(self):  # noqa: N802 — BaseHTTPRequestHandler API
        path = self.path.split("?", 1)[0]
        if path == "/debug/device":
            # the bench parent reads the child's prober through this
            # same bare port to diagnose (and fast-abort on) dead links
            from . import devhealth as _devhealth

            body = json.dumps(_devhealth.snapshot(limit=8)).encode()
        elif path == "/debug/flightrecorder":
            body = json.dumps(snapshot()).encode()
        elif path == "/debug/dispatch":
            # process-wide dispatch-phase aggregate: which phase
            # (lock_wait / transfer_in / compile / ack / sync) a wedged
            # attempt's round trips were spending in — attached by
            # bench.py to missed-deadline kill records
            from ..exec.stacked import global_dispatch_phases

            body = json.dumps(
                {"phases": global_dispatch_phases()}).encode()
        elif path == "/debug/incidents":
            # the bench parent attaches the newest bundle path to a
            # failed attempt's record (see bench.py _run_attempt)
            from . import incident as _incident

            body = json.dumps(_incident.snapshot()).encode()
        else:
            self.send_error(404)
            return
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        self.end_headers()
        self.wfile.write(body)

    def log_message(self, format, *args):  # noqa: A002 — stdlib signature
        pass


def start_debug_server(host="127.0.0.1", port=0):
    """Expose the recorder on a bare localhost HTTP port for processes
    that run no PilosaHTTPServer (the bench child). Returns the server;
    its bound port is `server.server_address[1]`."""
    srv = http.server.ThreadingHTTPServer((host, port), _DebugHandler)
    srv.daemon_threads = True
    t = threading.Thread(
        target=srv.serve_forever, name="pilosa-flightrec-debug", daemon=True)
    t.start()
    return srv
