"""Anomaly-triggered postmortem bundles — the push half of observability.

Everything PRs 4-8 built (flightrec ring, devhealth prober, dispatch
phase clocks, workload/SLO tables, query profiles) is pull-only: an
operator curls /debug/* AFTER noticing a problem, and the evidence dies
with the process. BENCH_r04/r05 ("device tunnel hung") left exactly one
bit of forensic data — the kill record. This module inverts the flow:
the existing EDGE signals

    devhealth_down    device-link prober transitions to DOWN
    watchdog_stall    an in-flight op ran past its watchdog deadline
    collective_stall  the SPMD plane wedged: a step-stream sequence gap
                      opened (cluster/spmd.py _stream_loop, at ONSET) or
                      a collective step ran past its watchdog deadline
                      (flightrec Watchdog, spmd.* op kinds)
    slo_burn          error-budget burn alert fired (both windows)
    deadline_storm    >= N deadline-expired rejections inside a window
    fatal_signal      SIGTERM / crash-handler chain
    manual            POSTed by an operator or a test

trigger a bundle write: a timestamped directory under --incident-dir
containing the flightrec dump, every thread's stack, the /debug/*
snapshots an operator would have curled (device, dispatch, workload,
heat, slo, fusion, oplog...), recent query profiles, and the open-op
table. Bundles are capped (--incident-max, oldest deleted), rate-limited
per trigger kind, and written off-thread (except on the dying-process
path). Served at GET /debug/incidents; bench.py attaches the newest
bundle path to failed-attempt records.

Default path cost: with no manager configured every hook is one module
global check (`maybe_trigger` / `note_deadline_expiry` return
immediately), the same discipline as flightrec/devhealth.
"""

import json
import os
import shutil
import threading
import time

from . import flightrec
from .stats import global_stats

DEFAULT_MAX_INCIDENTS = 16
#: per-kind refractory period — one DOWN flap must not write 50 bundles
DEFAULT_MIN_INTERVAL = 30.0
#: deadline-expiry storm edge: this many rejections inside the window
DEADLINE_STORM_COUNT = 20
DEADLINE_STORM_WINDOW = 10.0

#: cap on any single file returned inline by GET /debug/incidents/{id}
MAX_INLINE_BYTES = 1 << 20


def _json_default(obj):
    return repr(obj)


class IncidentManager:
    """Writes, caps, and serves postmortem bundles for one process."""

    def __init__(self, directory, max_incidents=DEFAULT_MAX_INCIDENTS,
                 min_interval=DEFAULT_MIN_INTERVAL,
                 storm_count=DEADLINE_STORM_COUNT,
                 storm_window=DEADLINE_STORM_WINDOW, logger=None):
        self.directory = os.path.abspath(directory)
        os.makedirs(self.directory, exist_ok=True)
        self.max_incidents = int(max_incidents)
        self.min_interval = float(min_interval)
        self.storm_count = int(storm_count)
        self.storm_window = float(storm_window)
        self.logger = logger
        self._lock = threading.Lock()
        self._last_trigger = {}   # kind -> monotonic time of last bundle
        self._storm = []          # monotonic times of deadline expiries
        self._seq = 0
        self._writing = False
        self.written_total = 0
        self.suppressed_total = 0
        self.errors_total = 0
        # collector name -> zero-arg fn returning a JSON-able object;
        # each becomes <name>.json in the bundle. Failures are captured
        # per-collector ({"error": ...}) — one broken surface must not
        # sink the whole autopsy.
        self._collectors = dict(_default_collectors())

    def register_collector(self, name, fn):
        with self._lock:
            self._collectors[str(name)] = fn

    # -- triggers ------------------------------------------------------------

    def trigger(self, kind, sync=False, **tags):
        """Request a bundle for `kind`. Returns the bundle path (sync) or
        the reserved path (async), or None when rate-limited / busy.

        Async by default: collectors walk every /debug surface and the
        write hits disk — none of that belongs on a prober/watchdog/SLO
        thread. `sync=True` is for the dying-process (SIGTERM) path and
        tests."""
        now = time.monotonic()
        with self._lock:
            last = self._last_trigger.get(kind)
            if last is not None and now - last < self.min_interval:
                self.suppressed_total += 1
                return None
            if self._writing:
                self.suppressed_total += 1
                return None
            self._last_trigger[kind] = now
            self._writing = True
            self._seq += 1
            seq = self._seq
        wall = time.time()
        incident_id = "%s-%03d-%s" % (
            time.strftime("%Y%m%dT%H%M%S", time.gmtime(wall)), seq, kind)
        path = os.path.join(self.directory, incident_id)
        flightrec.record("incident.triggered", id=incident_id, trigger=kind,
                         **{k: v for k, v in tags.items()
                            if k != "kind"
                            and isinstance(v, (str, int, float, bool))})
        if sync:
            self._write(incident_id, kind, tags, wall)
            return path
        t = threading.Thread(
            target=self._write, args=(incident_id, kind, tags, wall),
            name="pilosa-incident-writer", daemon=True)
        t.start()
        return path

    def note_deadline_expiry(self):
        """One deadline-expired rejection. A few are client impatience;
        a storm of them inside the window means the server (or the
        device link under it) stopped making progress — edge-trigger a
        bundle then."""
        now = time.monotonic()
        fire = 0
        with self._lock:
            self._storm.append(now)
            cutoff = now - self.storm_window
            while self._storm and self._storm[0] < cutoff:
                self._storm.pop(0)
            if len(self._storm) >= self.storm_count:
                fire = len(self._storm)
                self._storm.clear()
        if fire:
            self.trigger("deadline_storm", count=fire,
                         window_seconds=self.storm_window)

    # -- bundle writer -------------------------------------------------------

    def _write(self, incident_id, kind, tags, wall):
        try:
            self._write_bundle(incident_id, kind, tags, wall)
        except Exception:  # noqa: BLE001 — autopsy must never crash serving
            self.errors_total += 1
            if self.logger is not None:
                try:
                    self.logger.error(
                        "incident bundle %s failed to write", incident_id)
                except Exception:  # noqa: BLE001
                    pass
        finally:
            with self._lock:
                self._writing = False

    def _write_bundle(self, incident_id, kind, tags, wall):
        path = os.path.join(self.directory, incident_id)
        os.makedirs(path, exist_ok=True)
        files = []

        def put(name, payload, text=False):
            try:
                if text:
                    body = payload
                else:
                    body = json.dumps(payload, indent=1, sort_keys=True,
                                      default=_json_default)
            except Exception as e:  # noqa: BLE001 — capture, don't die
                name = name.rsplit(".", 1)[0] + ".json"
                body = json.dumps({"error": repr(e)})
            with open(os.path.join(path, name), "w") as f:
                f.write(body)
            files.append(name)

        put("flightrec.json", flightrec.snapshot(limit=512))
        put("threads.txt", flightrec.format_all_stacks(), text=True)
        with self._lock:
            collectors = list(self._collectors.items())
        for name, fn in collectors:
            try:
                payload = fn()
            except Exception as e:  # noqa: BLE001 — per-collector isolation
                payload = {"error": repr(e)}
            put(f"{name}.json", payload)
        # meta.json is written LAST: its presence marks the bundle
        # complete, so listings never show a half-written directory
        meta = {
            "id": incident_id,
            "kind": kind,
            "t": wall,
            "iso_time": time.strftime("%Y-%m-%dT%H:%M:%SZ",
                                      time.gmtime(wall)),
            "pid": os.getpid(),
            "trigger": {k: v for k, v in tags.items()},
            "files": sorted(files),
        }
        put("meta.json", meta)
        self.written_total += 1
        global_stats.count("incidents_written", 1, {"kind": kind})
        flightrec.record("incident.written", id=incident_id, trigger=kind)
        if self.logger is not None:
            try:
                self.logger.error("incident bundle written: %s (%s)",
                                  path, kind)
            except Exception:  # noqa: BLE001
                pass
        self._sweep()

    def _sweep(self):
        """Retention: delete the oldest bundles past max_incidents."""
        entries = sorted(
            e for e in os.listdir(self.directory)
            if os.path.isdir(os.path.join(self.directory, e)))
        for e in entries[:max(0, len(entries) - self.max_incidents)]:
            shutil.rmtree(os.path.join(self.directory, e),
                          ignore_errors=True)

    # -- readers -------------------------------------------------------------

    def list(self):
        """Completed bundles, newest first (GET /debug/incidents)."""
        out = []
        try:
            entries = os.listdir(self.directory)
        except OSError:
            return out
        for e in sorted(entries, reverse=True):
            meta_path = os.path.join(self.directory, e, "meta.json")
            try:
                with open(meta_path) as f:
                    meta = json.load(f)
            except (OSError, ValueError):
                continue  # half-written or foreign directory
            meta["path"] = os.path.join(self.directory, e)
            out.append(meta)
        return out

    def get(self, incident_id):
        """One bundle with file contents inlined (JSON parsed, text
        passed through, each capped at MAX_INLINE_BYTES), or None."""
        if os.sep in incident_id or incident_id in (".", ".."):
            return None
        path = os.path.join(self.directory, incident_id)
        meta_path = os.path.join(path, "meta.json")
        try:
            with open(meta_path) as f:
                meta = json.load(f)
        except (OSError, ValueError):
            return None
        contents = {}
        for name in meta.get("files", []):
            try:
                with open(os.path.join(path, name)) as f:
                    body = f.read(MAX_INLINE_BYTES)
            except OSError:
                continue
            if name.endswith(".json"):
                try:
                    contents[name] = json.loads(body)
                except ValueError:
                    contents[name] = body
            else:
                contents[name] = body
        meta["path"] = path
        meta["contents"] = contents
        return meta

    def snapshot(self):
        with self._lock:
            stats = {
                "written_total": self.written_total,
                "suppressed_total": self.suppressed_total,
                "errors_total": self.errors_total,
            }
        return {
            "enabled": True,
            "dir": self.directory,
            "max_incidents": self.max_incidents,
            "min_interval_seconds": self.min_interval,
            "deadline_storm": {"count": self.storm_count,
                               "window_seconds": self.storm_window},
            **stats,
            "incidents": self.list(),
        }


def _default_collectors():
    """The /debug surfaces every bundle snapshots. Each import is lazy
    and each call is wrapped by the writer — surfaces that are not
    configured in this process degrade to their 'disabled' snapshot or
    an {"error": ...} stub instead of failing the bundle."""

    def device():
        from . import devhealth
        return devhealth.snapshot(limit=64)

    def dispatch():
        from ..exec.stacked import global_dispatch_phases
        return {"phases": global_dispatch_phases()}

    def workload_():
        from . import workload
        return workload.table().snapshot(top=20)

    def heat():
        from . import workload
        return workload.heat().report(None, top=20)

    def slo():
        from . import workload
        return workload.slo().snapshot()

    def fusion():
        from ..exec import fusion as _fusion
        return _fusion.snapshot()

    def queries():
        from . import profile
        return {"recent": profile.recent()[:16]}

    def open_ops():
        wd = flightrec.get_watchdog()
        return {"watchdog": None if wd is None else wd.open_ops()}

    def traces():
        from . import tracing
        return tracing.trace_index().stats()

    def spmd():
        # the SPMD plane's observatory: step ring, per-phase tables, and
        # (best-effort) the cross-node timeline — in EVERY bundle, so a
        # devhealth_down or watchdog_stall autopsy also shows where the
        # collective plane was, not just the collective_stall trigger
        from ..cluster import spmd as spmd_mod
        return spmd_mod.observatory_snapshot()

    return {"device": device, "dispatch": dispatch,
            "workload": workload_, "heat": heat, "slo": slo,
            "fusion": fusion, "queries": queries,
            "open_ops": open_ops, "traces": traces, "spmd": spmd}


# -- module singleton (the flightrec/devhealth pattern) ----------------------

_manager = None


def configure(directory, max_incidents=DEFAULT_MAX_INCIDENTS,
              min_interval=DEFAULT_MIN_INTERVAL,
              storm_count=DEADLINE_STORM_COUNT,
              storm_window=DEADLINE_STORM_WINDOW, logger=None):
    """Install the process incident manager (None/"" directory disables).
    Returns it."""
    global _manager
    if not directory:
        _manager = None
        return None
    _manager = IncidentManager(
        directory, max_incidents=max_incidents, min_interval=min_interval,
        storm_count=storm_count, storm_window=storm_window, logger=logger)
    return _manager


def stop():
    global _manager
    _manager = None


def get_manager():
    return _manager


def maybe_trigger(kind, sync=False, **tags):
    """Producer fast path: one global check when no manager is installed."""
    mgr = _manager
    if mgr is None:
        return None
    try:
        return mgr.trigger(kind, sync=sync, **tags)
    except Exception:  # noqa: BLE001 — never let autopsy break the signal path
        return None


def note_deadline_expiry():
    mgr = _manager
    if mgr is None:
        return
    try:
        mgr.note_deadline_expiry()
    except Exception:  # noqa: BLE001
        pass


def register_collector(name, fn):
    mgr = _manager
    if mgr is not None:
        mgr.register_collector(name, fn)


def snapshot():
    mgr = _manager
    if mgr is None:
        return {"enabled": False,
                "hint": "start the server with --incident-dir to enable "
                        "anomaly-triggered postmortem bundles"}
    return mgr.snapshot()
