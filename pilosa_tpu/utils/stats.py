"""Metrics (reference: stats/stats.go StatsClient iface + backends).

The reference's pluggable StatsClient (stats/stats.go:31) with the same
backend set: in-process registry with Prometheus/expvar exposition
(prometheus/prometheus.go, stats.go:84), StatsD UDP emitter
(statsd/statsd.go, DataDog-tagged datagrams), nop, and multi fan-out
(stats.go:164). `RuntimeMonitor` is the runtime sampler loop
(server.go:813-860, gcnotify/gopsutil analog) publishing process gauges."""

import bisect
import json
import os
import socket
import threading
import time
from collections import defaultdict

from . import tracing

#: log-spaced latency bucket upper bounds (seconds) shared by every
#: timing series — 100µs to 10s, ~×2.5 per step, with an implicit +Inf
#: bucket. Log spacing keeps relative error roughly constant from
#: cache-hit kernels to slow cluster fan-outs.
TIMING_BUCKETS = (0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01,
                  0.025, 0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0)

#: smoothing factor for the per-series timing EWMA — ~last 5 samples
#: dominate, so a post-warmup regime shift shows within a handful of
#: observations where the cumulative mean would take thousands
EWMA_ALPHA = 0.2


def _key(name, tags):
    if not tags:
        return name, ()
    return name, tuple(sorted(tags.items()))


def _escape_label(value):
    """Escape one label VALUE per the Prometheus exposition format
    (backslash, double-quote, and newline must be escaped; anything else
    passes through)."""
    return (str(value).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", "\\n"))


def _quantile(count, bucket_counts, q):
    """Estimate the q-quantile from log-bucket counts: linear
    interpolation inside the target bucket (Prometheus histogram_quantile
    semantics; the lowest bucket interpolates from 0)."""
    if count <= 0:
        return 0.0
    target = q * count
    cum = 0
    for i, n in enumerate(bucket_counts):
        if n <= 0:
            continue
        if cum + n >= target:
            lo = 0.0 if i == 0 else TIMING_BUCKETS[i - 1]
            # +Inf bucket: report the largest finite bound rather than inf
            hi = TIMING_BUCKETS[i] if i < len(TIMING_BUCKETS) \
                else TIMING_BUCKETS[-1]
            return lo + (hi - lo) * (target - cum) / n
        cum += n
    return TIMING_BUCKETS[-1]


def tail_count(bucket_counts, threshold_seconds):
    """Observations ABOVE `threshold_seconds` from per-bucket counts
    (+Inf last, aligned to TIMING_BUCKETS). The threshold snaps UP to
    the nearest bucket bound — bucket resolution is the guarantee, so an
    SLO threshold between bounds under-counts rather than over-counts.
    Thresholds past the largest finite bound (10s) are untrackable and
    return 0."""
    i = bisect.bisect_left(TIMING_BUCKETS, threshold_seconds)
    if i >= len(TIMING_BUCKETS):
        return 0
    return sum(bucket_counts[i + 1:])


class StatsClient:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters = defaultdict(float)
        self._gauges = {}
        self._gauge_fns = {}
        # per series: [count, total seconds, per-bucket counts (+Inf
        # last), EWMA seconds]. Fields 0-2 are the cumulative series
        # /metrics exposes (unchanged forever); field 3 is the
        # recency-weighted view the adaptive layer calibrates from.
        self._timings = defaultdict(
            lambda: [0, 0.0, [0] * (len(TIMING_BUCKETS) + 1), 0.0])
        # Exemplars (OpenMetrics): when enabled, each timing series keeps
        # ONE recent (trace_id, value, wall_ts) per bucket, linking a
        # histogram bucket straight to an assembled trace. Off by default:
        # the flag check is the only cost on the disabled path.
        self._exemplars_on = False
        self._exemplars = {}  # series key -> [exemplar|None per bucket]

    def count(self, name, value=1, tags=None):
        with self._lock:
            self._counters[_key(name, tags)] += value

    def gauge(self, name, value, tags=None):
        with self._lock:
            self._gauges[_key(name, tags)] = value

    def gauge_fn(self, name, fn, tags=None):
        """Scrape-time gauge: `fn()` is evaluated on every snapshot. For
        liveness ages (e.g. seconds since a sampler last ran) — a stored
        gauge freezes when its writer wedges, which is exactly the moment
        the metric matters."""
        with self._lock:
            self._gauge_fns[_key(name, tags)] = fn

    def enable_exemplars(self, enabled=True):
        with self._lock:
            self._exemplars_on = bool(enabled)
            if not enabled:
                self._exemplars.clear()

    def timing(self, name, seconds, tags=None, trace_id=None):
        k = _key(name, tags)
        i = bisect.bisect_left(TIMING_BUCKETS, seconds)
        with self._lock:
            t = self._timings[k]
            t[0] += 1
            t[1] += seconds
            t[2][i] += 1
            # first sample seeds the EWMA; later samples alpha-blend
            t[3] = seconds if t[0] == 1 \
                else t[3] + EWMA_ALPHA * (seconds - t[3])
            if self._exemplars_on:
                if trace_id is None:
                    span = tracing.current_span()
                    trace_id = span.trace_id if span is not None else None
                if trace_id is not None:
                    ex = self._exemplars.get(k)
                    if ex is None:
                        ex = self._exemplars[k] = \
                            [None] * (len(TIMING_BUCKETS) + 1)
                    ex[i] = (trace_id, seconds, time.time())

    def exemplars(self, name=None):
        """{series key: {le_label: {"traceID","value","timestamp"}}} for
        series with at least one exemplar; `name` filters to one family
        (how /debug/slo links a burning objective to traces)."""
        with self._lock:
            items = [(k, list(v)) for k, v in self._exemplars.items()
                     if name is None or k[0] == name]
        out = {}
        for k, buckets in items:
            per = {}
            for i, e in enumerate(buckets):
                if e is None:
                    continue
                le = (f"{TIMING_BUCKETS[i]:g}"
                      if i < len(TIMING_BUCKETS) else "+Inf")
                per[le] = {"traceID": e[0], "value": e[1],
                           "timestamp": e[2]}
            if per:
                out[k] = per
        return out

    def snapshot(self):
        """(counters, gauges, timings) — timings as (count, sum) pairs;
        `histograms()` adds the bucket counts."""
        with self._lock:
            counters = dict(self._counters)
            gauges = dict(self._gauges)
            timings = {k: (v[0], v[1]) for k, v in self._timings.items()}
            fns = list(self._gauge_fns.items())
        for k, fn in fns:  # outside the lock: fns may call gauge()
            try:
                gauges[k] = fn()
            except Exception:
                pass
        return (counters, gauges, timings)

    def histograms(self):
        """{key: (count, sum, bucket_counts)} — bucket_counts are
        per-bucket (NOT cumulative), +Inf last, aligned to
        TIMING_BUCKETS."""
        with self._lock:
            return {k: (v[0], v[1], tuple(v[2]))
                    for k, v in self._timings.items()}

    def timing_summary(self, name):
        """{(name, tags): (count, sum)} for ONE timing family — the
        explain cost model reads `kernel_seconds{kernel}` means without
        copying every histogram's buckets."""
        with self._lock:
            return {k: (v[0], v[1]) for k, v in self._timings.items()
                    if k[0] == name}

    def timing_ewma(self, name):
        """{(name, tags): (ewma_seconds, count)} for ONE timing family —
        the recency-weighted companion to `timing_summary`. The
        cumulative /metrics series are untouched; this view exists so
        the adaptive layer can forget a slow cold-start regime."""
        with self._lock:
            return {k: (v[3], v[0]) for k, v in self._timings.items()
                    if k[0] == name}

    def timing_ewma_force(self, name, seconds, tags=None):
        """Overwrite a series' EWMA with an observed value WITHOUT
        touching the cumulative count/sum/buckets — the misestimate
        feedback path: a >3× plan-vs-actual deviation re-seeds the
        calibration from reality instead of waiting for the blend to
        catch up."""
        with self._lock:
            t = self._timings[_key(name, tags)]
            t[3] = seconds

    def prometheus_text(self):
        """Prometheus exposition format (reference: prometheus/prometheus.go
        + /metrics route http/handler.go:282): escaped label values, one
        # TYPE line per metric family, and real histogram series
        (_bucket{le=...}/_count/_sum) for timings."""
        counters, gauges, _ = self.snapshot()
        hists = self.histograms()
        with self._lock:
            exemplars = {k: list(v) for k, v in self._exemplars.items()}
        lines = []
        seen_families = set()

        def exemplar_suffix(key, bucket_i):
            # OpenMetrics exemplar: `value # {trace_id="..."} v ts`.
            # Exemplar-aware scrapers (and humans) get the trace link;
            # plain Prometheus text parsers that reject it simply should
            # not enable --metrics-exemplars.
            ex = exemplars.get(key)
            if not ex or ex[bucket_i] is None:
                return ""
            tid, v, ts = ex[bucket_i]
            return (f' # {{trace_id="{_escape_label(tid)}"}}'
                    f" {v:g} {ts:.3f}")

        def family(fqname, typ):
            # dedupe: one TYPE line per family, before its first sample
            if fqname not in seen_families:
                seen_families.add(fqname)
                lines.append(f"# TYPE {fqname} {typ}")

        def fmt(name, labels, value, extra=()):
            pairs = tuple(labels) + tuple(extra)
            if pairs:
                inner = ",".join(
                    f'{k}="{_escape_label(v)}"' for k, v in pairs)
                return f"{name}{{{inner}}} {value}"
            return f"{name} {value}"

        for (name, labels), value in sorted(counters.items()):
            fq = f"pilosa_tpu_{name}_total"
            family(fq, "counter")
            lines.append(fmt(fq, labels, value))
        for (name, labels), value in sorted(gauges.items()):
            fq = f"pilosa_tpu_{name}"
            family(fq, "gauge")
            lines.append(fmt(fq, labels, value))
        for (name, labels), (count, total, buckets) in sorted(hists.items()):
            fq = f"pilosa_tpu_{name}"
            family(fq, "histogram")
            key = (name, labels)
            cum = 0
            for i, (bound, n) in enumerate(zip(TIMING_BUCKETS, buckets)):
                cum += n
                lines.append(fmt(f"{fq}_bucket", labels, cum,
                                 extra=(("le", f"{bound:g}"),))
                             + exemplar_suffix(key, i))
            lines.append(fmt(f"{fq}_bucket", labels, count,
                             extra=(("le", "+Inf"),))
                         + exemplar_suffix(key, len(TIMING_BUCKETS)))
            lines.append(fmt(f"{fq}_count", labels, count))
            lines.append(fmt(f"{fq}_sum", labels, total))
        return "\n".join(lines) + "\n"

    def expvar_json(self):
        """JSON snapshot (reference: expvar backend stats.go:84 + the
        /debug/vars route http/handler.go:281). Timings carry estimated
        p50/p99 from the log buckets."""
        counters, gauges, _ = self.snapshot()
        hists = self.histograms()

        def flat(d):
            return {
                (name if not labels else
                 name + "{" + ",".join(f"{k}={v}" for k, v in labels) + "}"):
                    value
                for (name, labels), value in sorted(d.items())}

        return json.dumps({
            "counters": flat(counters),
            "gauges": flat(gauges),
            "timings": {k: {"count": c, "sum": s,
                            "p50": _quantile(c, b, 0.50),
                            "p99": _quantile(c, b, 0.99)}
                        for k, (c, s, b) in flat(hists).items()},
        })


class NopStats:
    """Discards everything (reference: nopStatsClient stats.go:54)."""

    def count(self, name, value=1, tags=None):
        pass

    def gauge(self, name, value, tags=None):
        pass

    def timing(self, name, seconds, tags=None, trace_id=None):
        pass


class StatsDClient:
    """UDP StatsD emitter with DataDog-style |#k:v tags (reference:
    statsd/statsd.go). Fire-and-forget: send errors are ignored, matching
    UDP statsd semantics."""

    def __init__(self, host="127.0.0.1", port=8125, prefix="pilosa_tpu"):
        self.prefix = prefix
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            # Resolve once and connect() so the datagram hot path never
            # does a DNS lookup (the http dispatch emits per request).
            self._sock.connect((host, port))
        except OSError:
            pass  # unresolvable now; sends just drop (UDP semantics)

    def _send(self, name, value, kind, tags):
        msg = f"{self.prefix}.{name}:{value}|{kind}"
        if tags:
            msg += "|#" + ",".join(f"{k}:{v}" for k, v in sorted(tags.items()))
        try:
            self._sock.send(msg.encode())
        except OSError:
            pass

    def count(self, name, value=1, tags=None):
        self._send(name, value, "c", tags)

    def gauge(self, name, value, tags=None):
        self._send(name, value, "g", tags)

    def timing(self, name, seconds, tags=None, trace_id=None):
        self._send(name, round(seconds * 1000, 3), "ms", tags)

    def close(self):
        self._sock.close()


class MultiStats:
    """Fans every metric out to several clients (reference: multiStatsClient
    stats.go:164). The registry is usually first so exposition still works."""

    def __init__(self, clients):
        self.clients = list(clients)

    def count(self, name, value=1, tags=None):
        for c in self.clients:
            c.count(name, value, tags)

    def gauge(self, name, value, tags=None):
        for c in self.clients:
            c.gauge(name, value, tags)

    def timing(self, name, seconds, tags=None, trace_id=None):
        for c in self.clients:
            c.timing(name, seconds, tags, trace_id=trace_id)


class RuntimeMonitor:
    """Background sampler publishing process runtime gauges every interval
    (reference: server.monitorRuntime server.go:813-860 — goroutines, heap,
    GC; here: threads, RSS, fds, uptime from /proc)."""

    def __init__(self, stats, interval=10.0):
        self.stats = stats
        # Event.wait(0) would busy-spin the sampler loop.
        self.interval = max(float(interval), 1.0)
        self._stop = threading.Event()
        self._thread = None
        self._t0 = time.time()
        self.last_sample_time = None

    def sample(self):
        self.stats.gauge("uptime_seconds", time.time() - self._t0)
        self.stats.gauge("threads", threading.active_count())
        try:
            with open("/proc/self/status") as f:
                for line in f:
                    if line.startswith("VmRSS:"):
                        self.stats.gauge(
                            "rss_bytes", int(line.split()[1]) * 1024)
                        break
            self.stats.gauge("open_fds", len(os.listdir("/proc/self/fd")))
        except OSError:
            pass  # non-procfs platform
        self._sample_devices()
        self.last_sample_time = time.time()

    def _sample_devices(self):
        """Per-device JAX memory gauges so HBM pressure sits next to RSS.
        Only samples when a backend is ALREADY initialized — metrics must
        never be what initializes one (jax.local_devices() would, and in
        --spmd mode that must wait for jax.distributed.initialize; see
        cluster/spmd.py) — and tolerates backends that don't implement
        memory_stats (CPU returns None/raises)."""
        import sys

        jax = sys.modules.get("jax")
        if jax is None:
            return
        try:
            from jax._src import xla_bridge

            if not xla_bridge.backends_are_initialized():
                return
        except Exception:
            return  # can't prove a live backend; don't risk initializing one
        try:
            for d in jax.local_devices():
                mem = d.memory_stats()
                if not mem:
                    continue
                tags = {"device": f"{d.platform}:{d.id}"}
                if "bytes_in_use" in mem:
                    self.stats.gauge("device_memory_bytes",
                                     mem["bytes_in_use"], tags)
                if "peak_bytes_in_use" in mem:
                    self.stats.gauge("device_peak_memory_bytes",
                                     mem["peak_bytes_in_use"], tags)
                if "bytes_limit" in mem:
                    self.stats.gauge("device_memory_limit_bytes",
                                     mem["bytes_limit"], tags)
        except Exception:
            pass  # backend without memory introspection

    def _run(self):
        while not self._stop.wait(self.interval):
            self.sample()

    def _sample_age(self):
        return (time.time() - self.last_sample_time
                if self.last_sample_time is not None else -1)

    def start(self):
        # Evaluated at scrape time, so a wedged sampler thread shows up
        # as an ever-growing age instead of a frozen small value.
        registry_of(self.stats).gauge_fn(
            "runtime_monitor_last_sample_age_seconds", self._sample_age)
        self.sample()
        self._thread = threading.Thread(
            target=self._run, name="pilosa-runtime-monitor", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)


def registry_of(stats):
    """The exposition-capable registry behind a configured stats client
    (a MultiStats wraps one; NopStats has none -> global registry)."""
    if isinstance(stats, StatsClient):
        return stats
    if isinstance(stats, MultiStats):
        for c in stats.clients:
            if isinstance(c, StatsClient):
                return c
    return global_stats


def build_stats(kind, statsd_host=None, registry=None):
    """Config-selected backend (reference: server.go:419 NewStatsClient).
    `kind`: "local" (registry only, default), "statsd" (registry + UDP so
    /metrics keeps working), "none", or "expvar" (alias of local)."""
    registry = registry if registry is not None else global_stats
    if kind in (None, "", "local", "expvar", "prometheus"):
        return registry
    if kind == "none":
        return NopStats()
    if kind == "statsd":
        host, _, port = (statsd_host or "127.0.0.1:8125").partition(":")
        return MultiStats(
            [registry, StatsDClient(host, int(port or 8125))])
    raise ValueError(f"unknown stats backend {kind!r}")


def configure_exemplars(enabled, registry=None):
    """Toggle histogram exemplar capture on the exposition registry
    (--metrics-exemplars). Nop-cheap when off: one flag check per
    timing() call."""
    (registry if registry is not None else global_stats) \
        .enable_exemplars(enabled)


global_stats = StatsClient()
