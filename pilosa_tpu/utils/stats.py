"""Metrics (reference: stats/stats.go StatsClient + prometheus backend).

A small counter/gauge/timing registry with Prometheus text exposition —
the reference's pluggable StatsClient collapsed to one thread-safe
implementation with the same call surface (count/gauge/timing, tags)."""

import threading
from collections import defaultdict


def _key(name, tags):
    if not tags:
        return name, ()
    return name, tuple(sorted(tags.items()))


class StatsClient:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters = defaultdict(float)
        self._gauges = {}
        self._timings = defaultdict(lambda: [0, 0.0])  # count, total seconds

    def count(self, name, value=1, tags=None):
        with self._lock:
            self._counters[_key(name, tags)] += value

    def gauge(self, name, value, tags=None):
        with self._lock:
            self._gauges[_key(name, tags)] = value

    def timing(self, name, seconds, tags=None):
        with self._lock:
            t = self._timings[_key(name, tags)]
            t[0] += 1
            t[1] += seconds

    def snapshot(self):
        with self._lock:
            return (dict(self._counters), dict(self._gauges),
                    {k: tuple(v) for k, v in self._timings.items()})

    def prometheus_text(self):
        """Prometheus exposition format (reference: prometheus/prometheus.go
        + /metrics route http/handler.go:282)."""
        counters, gauges, timings = self.snapshot()
        lines = []

        def fmt(name, labels, value):
            if labels:
                inner = ",".join(f'{k}="{v}"' for k, v in labels)
                return f"{name}{{{inner}}} {value}"
            return f"{name} {value}"

        for (name, labels), value in sorted(counters.items()):
            lines.append(fmt(f"pilosa_tpu_{name}_total", labels, value))
        for (name, labels), value in sorted(gauges.items()):
            lines.append(fmt(f"pilosa_tpu_{name}", labels, value))
        for (name, labels), (count, total) in sorted(timings.items()):
            lines.append(fmt(f"pilosa_tpu_{name}_count", labels, count))
            lines.append(fmt(f"pilosa_tpu_{name}_sum", labels, total))
        return "\n".join(lines) + "\n"


global_stats = StatsClient()
