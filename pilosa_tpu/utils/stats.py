"""Metrics (reference: stats/stats.go StatsClient iface + backends).

The reference's pluggable StatsClient (stats/stats.go:31) with the same
backend set: in-process registry with Prometheus/expvar exposition
(prometheus/prometheus.go, stats.go:84), StatsD UDP emitter
(statsd/statsd.go, DataDog-tagged datagrams), nop, and multi fan-out
(stats.go:164). `RuntimeMonitor` is the runtime sampler loop
(server.go:813-860, gcnotify/gopsutil analog) publishing process gauges."""

import json
import os
import socket
import threading
import time
from collections import defaultdict


def _key(name, tags):
    if not tags:
        return name, ()
    return name, tuple(sorted(tags.items()))


class StatsClient:
    def __init__(self):
        self._lock = threading.Lock()
        self._counters = defaultdict(float)
        self._gauges = {}
        self._timings = defaultdict(lambda: [0, 0.0])  # count, total seconds

    def count(self, name, value=1, tags=None):
        with self._lock:
            self._counters[_key(name, tags)] += value

    def gauge(self, name, value, tags=None):
        with self._lock:
            self._gauges[_key(name, tags)] = value

    def timing(self, name, seconds, tags=None):
        with self._lock:
            t = self._timings[_key(name, tags)]
            t[0] += 1
            t[1] += seconds

    def snapshot(self):
        with self._lock:
            return (dict(self._counters), dict(self._gauges),
                    {k: tuple(v) for k, v in self._timings.items()})

    def prometheus_text(self):
        """Prometheus exposition format (reference: prometheus/prometheus.go
        + /metrics route http/handler.go:282)."""
        counters, gauges, timings = self.snapshot()
        lines = []

        def fmt(name, labels, value):
            if labels:
                inner = ",".join(f'{k}="{v}"' for k, v in labels)
                return f"{name}{{{inner}}} {value}"
            return f"{name} {value}"

        for (name, labels), value in sorted(counters.items()):
            lines.append(fmt(f"pilosa_tpu_{name}_total", labels, value))
        for (name, labels), value in sorted(gauges.items()):
            lines.append(fmt(f"pilosa_tpu_{name}", labels, value))
        for (name, labels), (count, total) in sorted(timings.items()):
            lines.append(fmt(f"pilosa_tpu_{name}_count", labels, count))
            lines.append(fmt(f"pilosa_tpu_{name}_sum", labels, total))
        return "\n".join(lines) + "\n"

    def expvar_json(self):
        """JSON snapshot (reference: expvar backend stats.go:84 + the
        /debug/vars route http/handler.go:281)."""
        counters, gauges, timings = self.snapshot()

        def flat(d):
            return {
                (name if not labels else
                 name + "{" + ",".join(f"{k}={v}" for k, v in labels) + "}"):
                    value
                for (name, labels), value in sorted(d.items())}

        return json.dumps({
            "counters": flat(counters),
            "gauges": flat(gauges),
            "timings": {k: {"count": c, "sum": s}
                        for k, (c, s) in flat(timings).items()},
        })


class NopStats:
    """Discards everything (reference: nopStatsClient stats.go:54)."""

    def count(self, name, value=1, tags=None):
        pass

    def gauge(self, name, value, tags=None):
        pass

    def timing(self, name, seconds, tags=None):
        pass


class StatsDClient:
    """UDP StatsD emitter with DataDog-style |#k:v tags (reference:
    statsd/statsd.go). Fire-and-forget: send errors are ignored, matching
    UDP statsd semantics."""

    def __init__(self, host="127.0.0.1", port=8125, prefix="pilosa_tpu"):
        self.prefix = prefix
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_DGRAM)
        try:
            # Resolve once and connect() so the datagram hot path never
            # does a DNS lookup (the http dispatch emits per request).
            self._sock.connect((host, port))
        except OSError:
            pass  # unresolvable now; sends just drop (UDP semantics)

    def _send(self, name, value, kind, tags):
        msg = f"{self.prefix}.{name}:{value}|{kind}"
        if tags:
            msg += "|#" + ",".join(f"{k}:{v}" for k, v in sorted(tags.items()))
        try:
            self._sock.send(msg.encode())
        except OSError:
            pass

    def count(self, name, value=1, tags=None):
        self._send(name, value, "c", tags)

    def gauge(self, name, value, tags=None):
        self._send(name, value, "g", tags)

    def timing(self, name, seconds, tags=None):
        self._send(name, round(seconds * 1000, 3), "ms", tags)

    def close(self):
        self._sock.close()


class MultiStats:
    """Fans every metric out to several clients (reference: multiStatsClient
    stats.go:164). The registry is usually first so exposition still works."""

    def __init__(self, clients):
        self.clients = list(clients)

    def count(self, name, value=1, tags=None):
        for c in self.clients:
            c.count(name, value, tags)

    def gauge(self, name, value, tags=None):
        for c in self.clients:
            c.gauge(name, value, tags)

    def timing(self, name, seconds, tags=None):
        for c in self.clients:
            c.timing(name, seconds, tags)


class RuntimeMonitor:
    """Background sampler publishing process runtime gauges every interval
    (reference: server.monitorRuntime server.go:813-860 — goroutines, heap,
    GC; here: threads, RSS, fds, uptime from /proc)."""

    def __init__(self, stats, interval=10.0):
        self.stats = stats
        # Event.wait(0) would busy-spin the sampler loop.
        self.interval = max(float(interval), 1.0)
        self._stop = threading.Event()
        self._thread = None
        self._t0 = time.time()

    def sample(self):
        self.stats.gauge("uptime_seconds", time.time() - self._t0)
        self.stats.gauge("threads", threading.active_count())
        try:
            with open("/proc/self/status") as f:
                for line in f:
                    if line.startswith("VmRSS:"):
                        self.stats.gauge(
                            "rss_bytes", int(line.split()[1]) * 1024)
                        break
            self.stats.gauge("open_fds", len(os.listdir("/proc/self/fd")))
        except OSError:
            pass  # non-procfs platform

    def _run(self):
        while not self._stop.wait(self.interval):
            self.sample()

    def start(self):
        self.sample()
        self._thread = threading.Thread(
            target=self._run, name="pilosa-runtime-monitor", daemon=True)
        self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)


def registry_of(stats):
    """The exposition-capable registry behind a configured stats client
    (a MultiStats wraps one; NopStats has none -> global registry)."""
    if isinstance(stats, StatsClient):
        return stats
    if isinstance(stats, MultiStats):
        for c in stats.clients:
            if isinstance(c, StatsClient):
                return c
    return global_stats


def build_stats(kind, statsd_host=None, registry=None):
    """Config-selected backend (reference: server.go:419 NewStatsClient).
    `kind`: "local" (registry only, default), "statsd" (registry + UDP so
    /metrics keeps working), "none", or "expvar" (alias of local)."""
    registry = registry if registry is not None else global_stats
    if kind in (None, "", "local", "expvar", "prometheus"):
        return registry
    if kind == "none":
        return NopStats()
    if kind == "statsd":
        host, _, port = (statsd_host or "127.0.0.1:8125").partition(":")
        return MultiStats(
            [registry, StatsDClient(host, int(port or 8125))])
    raise ValueError(f"unknown stats backend {kind!r}")


global_stats = StatsClient()
