"""Logger interface (reference: logger/logger.go:27 — Logger iface with
Printf/Debugf, NopLogger, standard + verbose impls)."""

import sys
import threading
import time


class NopLogger:
    def printf(self, fmt, *args):
        pass

    def debugf(self, fmt, *args):
        pass


class StandardLogger:
    """Timestamped printf logging to a stream; debugf only when verbose
    (reference: verboseLogger logger.go:57)."""

    def __init__(self, stream=None, verbose=False):
        self.stream = stream if stream is not None else sys.stderr
        self.verbose = verbose
        self._lock = threading.Lock()

    def _emit(self, fmt, args):
        msg = (fmt % args) if args else fmt
        stamp = time.strftime("%Y-%m-%dT%H:%M:%S")
        with self._lock:
            self.stream.write(f"{stamp} {msg}\n")
            self.stream.flush()

    def printf(self, fmt, *args):
        self._emit(fmt, args)

    def debugf(self, fmt, *args):
        if self.verbose:
            self._emit(fmt, args)


class CaptureLogger:
    """Collects log lines; for tests."""

    def __init__(self):
        self.lines = []
        self._lock = threading.Lock()

    def printf(self, fmt, *args):
        with self._lock:
            self.lines.append((fmt % args) if args else fmt)

    debugf = printf
