"""Device-link health: a continuous canary prober + readiness state.

BENCH r04/r05 both died on a guess — "device tunnel hung?" — because
nothing in the process could say whether the accelerator link was alive.
This module keeps one cheap, continuously-refreshed answer: a background
prober issues tiny canary dispatches on a jittered interval through the
SAME process-wide dispatch lock as real queries (so a wedged real
dispatch also wedges the canary — which is the point: the canary
measures the serving path, not a side channel), keeps a bounded ring of
samples with the pure-RTT vs lock-wait split, and drives a

    LIVE -> DEGRADED -> DOWN

state machine with hysteresis. Transitions emit flight-recorder events
and Prometheus gauges; the full ring is served at `GET /debug/device`;
`/readyz` and the query fail-fast gate read `state()`.

Module-singleton pattern like utils/flightrec.py: `configure()` builds
and starts the prober, `state()`/`snapshot()` read it, `stop()` tears it
down. When never configured, `state()` is DISABLED and the module is
guaranteed to issue ZERO device dispatches — bench.py's parent process
and pure-host tests import this file without ever touching jax.

A canary that never returns cannot be cancelled (a blocked device call
is not interruptible from Python), so probes run on a dedicated runner
thread: the prober submits a probe and waits up to the deadline. On
timeout the sample is recorded as failed and the runner stays wedged on
the in-flight call; follow-up probe slots are marked failed immediately
("canary still in flight") until the wedged call finally returns — at
which point normal probing resumes and the recovery hysteresis applies.
At most one extra (daemon) thread can be wedged at any time.
"""

import random
import threading
import time

from .stats import global_stats

#: state machine vocabulary; DISABLED means "no prober running" and is
#: deliberately ready (a node without a device link still serves
#: host-side work, and tests/CLI default to no prober).
LIVE = "LIVE"
DEGRADED = "DEGRADED"
DOWN = "DOWN"
DISABLED = "DISABLED"

#: numeric codes for the `device_link_state` gauge (alert rules compare
#: numbers, not strings)
STATE_CODES = {LIVE: 0, DEGRADED: 1, DOWN: 2, DISABLED: -1}

DEFAULT_INTERVAL = 1.0
DEFAULT_DEADLINE = 5.0
DEFAULT_RING = 256

_canary_fn = None  # lazily-jitted default canary program (one per process)


def default_canary():
    """One tiny device round trip through the real dispatch path.

    Acquires the stacked evaluator's process-wide `_DISPATCH_LOCK` (the
    same serialization point every query kernel goes through), launches
    a trivial jitted program, and blocks until the result is ready.
    Returns the seconds spent waiting on the lock so the prober can
    split lock contention from pure link RTT. jax is imported lazily —
    merely importing this module must never pull in the device runtime.
    """
    global _canary_fn
    import jax
    import jax.numpy as jnp

    from ..exec import stacked as _stacked

    if _canary_fn is None:
        _canary_fn = jax.jit(lambda x: x + 1)
    t0 = time.perf_counter()
    with _stacked._DISPATCH_LOCK:
        t1 = time.perf_counter()
        out = _canary_fn(jnp.uint32(1))
        out.block_until_ready()
    return t1 - t0


class _CanaryRunner(threading.Thread):
    """Dedicated thread that actually calls the canary, so a hung device
    call wedges THIS thread instead of the prober's control loop."""

    def __init__(self, canary):
        super().__init__(name="devhealth-canary", daemon=True)
        self._canary = canary
        self._go = threading.Event()
        self._stopped = False
        #: set while a canary call is in flight (read by the prober to
        #: mark follow-up probe slots failed without stacking threads)
        self.busy = False
        self.result = None  # (ok, lock_wait_seconds, wall_seconds, err)
        self.done = threading.Event()

    def submit(self):
        self.busy = True
        self.done.clear()
        self._go.set()

    def stop(self):
        self._stopped = True
        self._go.set()

    def run(self):
        while True:
            self._go.wait()
            self._go.clear()
            if self._stopped:
                return
            t0 = time.perf_counter()
            try:
                lock_wait = self._canary()
                ok, err = True, None
            except Exception as e:  # noqa: BLE001 — any failure = link sample
                lock_wait, ok, err = 0.0, False, f"{type(e).__name__}: {e}"
            wall = time.perf_counter() - t0
            self.result = (ok, float(lock_wait or 0.0), wall, err)
            self.busy = False
            self.done.set()


class DeviceLinkProber:
    """Background prober + LIVE/DEGRADED/DOWN state machine."""

    def __init__(self, canary=None, interval=DEFAULT_INTERVAL,
                 deadline=DEFAULT_DEADLINE, ring_size=DEFAULT_RING,
                 degraded_after=1, down_after=3, live_after=2,
                 jitter=0.2, logger=None):
        """degraded_after/down_after: consecutive canary failures before
        leaving LIVE / entering DOWN. live_after: consecutive successes
        before a degraded or down link is trusted again (hysteresis — one
        lucky probe must not flip a dead tunnel back to ready).
        jitter: +/- fraction applied to every sleep so a fleet of nodes
        doesn't synchronize its probes."""
        self.canary = canary or default_canary
        self.interval = float(interval)
        self.deadline = float(deadline)
        self.degraded_after = max(1, int(degraded_after))
        self.down_after = max(self.degraded_after, int(down_after))
        self.live_after = max(1, int(live_after))
        self.jitter = float(jitter)
        self.logger = logger
        self._ring_size = int(ring_size)
        self._lock = threading.Lock()
        self._ring = []  # newest last, trimmed to ring_size
        self._transitions = []  # last 32 transitions, newest last
        self.state = LIVE
        self.state_since = time.time()
        self.consecutive_failures = 0
        self.consecutive_successes = 0
        self.probes_total = 0
        self.probes_ok = 0
        self.probes_timeout = 0
        self.probes_error = 0
        self.last_sample = None
        self._last_probe_mono = None
        self._stop = threading.Event()
        self._runner = _CanaryRunner(self.canary)
        self._thread = threading.Thread(
            target=self._loop, name="devhealth-prober", daemon=True)
        self._started = False
        global_stats.gauge("device_link_state", STATE_CODES[self.state])
        global_stats.gauge_fn(
            "device_link_last_probe_age_seconds", self._probe_age)

    # -- lifecycle -----------------------------------------------------------

    def start(self):
        if not self._started:
            self._started = True
            self._runner.start()
            self._thread.start()
        return self

    def stop(self):
        self._stop.set()
        self._runner.stop()
        if self._started:
            self._thread.join(timeout=2)

    # -- probe loop ----------------------------------------------------------

    def _loop(self):
        while not self._stop.is_set():
            self.probe_once()
            sleep = self.interval * (
                1.0 + random.uniform(-self.jitter, self.jitter))
            self._stop.wait(max(0.01, sleep))

    def probe_once(self):
        """One probe slot: submit a canary (unless one is still wedged in
        flight) and judge it against the deadline. Called by the loop;
        tests call it directly for deterministic stepping."""
        self._last_probe_mono = time.monotonic()
        if not self._runner.is_alive():
            # start(start=False) probers stepped by hand still need the
            # runner thread — without it every slot times out
            try:
                self._runner.start()
            except RuntimeError:  # already started and since stopped
                pass
        if self._runner.busy:
            # previous canary still in flight past its deadline: the
            # link is not answering — fail this slot without waiting
            self._record(ok=False, timeout=True, lock_wait=0.0,
                         wall=None, error="canary still in flight")
            return
        self._runner.submit()
        if not self._runner.done.wait(self.deadline):
            self._record(ok=False, timeout=True, lock_wait=0.0,
                         wall=None, error="canary deadline exceeded")
            return
        ok, lock_wait, wall, err = self._runner.result
        self._record(ok=ok, timeout=False, lock_wait=lock_wait,
                     wall=wall, error=err)

    def _record(self, ok, timeout, lock_wait, wall, error):
        sample = {
            "t": round(time.time(), 3),
            "ok": bool(ok),
            "timeout": bool(timeout),
            "rtt_seconds": round(wall, 6) if wall is not None else None,
            "lock_wait_seconds": round(lock_wait, 6),
            "pure_rtt_seconds": (round(max(0.0, wall - lock_wait), 6)
                                 if wall is not None else None),
            "error": error,
        }
        with self._lock:
            self.probes_total += 1
            if ok:
                self.probes_ok += 1
            elif timeout:
                self.probes_timeout += 1
            else:
                self.probes_error += 1
            self.last_sample = sample
            self._ring.append(sample)
            if len(self._ring) > self._ring_size:
                del self._ring[:len(self._ring) - self._ring_size]
        if ok and wall is not None:
            global_stats.timing("device_canary_rtt_seconds", wall)
            global_stats.timing(
                "device_canary_pure_rtt_seconds",
                max(0.0, wall - lock_wait))
            global_stats.gauge("device_link_last_rtt_seconds",
                               round(wall, 6))
        self._advance(ok)
        sample["state"] = self.state

    # -- state machine -------------------------------------------------------

    def _advance(self, ok):
        if ok:
            self.consecutive_failures = 0
            self.consecutive_successes += 1
            if self.state in (DEGRADED, DOWN) \
                    and self.consecutive_successes >= self.live_after:
                self._transition(LIVE)
        else:
            self.consecutive_successes = 0
            self.consecutive_failures += 1
            if self.state == LIVE \
                    and self.consecutive_failures >= self.degraded_after:
                self._transition(DEGRADED)
            if self.state == DEGRADED \
                    and self.consecutive_failures >= self.down_after:
                self._transition(DOWN)

    def _transition(self, new):
        old, self.state = self.state, new
        self.state_since = time.time()
        evt = {
            "t": round(self.state_since, 3),
            "from": old, "to": new,
            "consecutive_failures": self.consecutive_failures,
            "consecutive_successes": self.consecutive_successes,
        }
        with self._lock:
            self._transitions.append(evt)
            del self._transitions[:-32]
        global_stats.gauge("device_link_state", STATE_CODES[new])
        global_stats.count("device_link_transitions", 1,
                           {"from": old, "to": new})
        from . import flightrec as _flightrec

        _flightrec.record("devhealth.transition", **evt)
        if new == DOWN:
            # edge-triggered postmortem: capture the process state the
            # moment the link dies, not when an operator shows up
            from . import incident as _incident

            _incident.maybe_trigger("devhealth_down", **evt)
        if self.logger is not None:
            try:
                self.logger.error(
                    "DEVICE LINK %s -> %s (failures=%d successes=%d)",
                    old, new, self.consecutive_failures,
                    self.consecutive_successes)
            except Exception:  # noqa: BLE001 — telemetry only
                pass

    # -- readers -------------------------------------------------------------

    def _probe_age(self):
        if self._last_probe_mono is None:
            return -1.0
        return round(time.monotonic() - self._last_probe_mono, 3)

    def summary(self):
        """Compact roll-up (no ring) for /status observability."""
        with self._lock:
            last = dict(self.last_sample) if self.last_sample else None
        return {
            "state": self.state,
            "state_since": round(self.state_since, 3),
            "consecutive_failures": self.consecutive_failures,
            "consecutive_successes": self.consecutive_successes,
            "interval_seconds": self.interval,
            "deadline_seconds": self.deadline,
            "probes": {
                "total": self.probes_total, "ok": self.probes_ok,
                "timeout": self.probes_timeout,
                "error": self.probes_error,
            },
            "last": last,
        }

    def snapshot(self, limit=None):
        """Full ring + transitions for GET /debug/device."""
        out = self.summary()
        with self._lock:
            ring = list(self._ring)
            out["transitions"] = list(self._transitions)
        if limit is not None and limit >= 0:
            ring = ring[-limit:] if limit else []
        out["ring"] = ring
        out["thresholds"] = {
            "degraded_after": self.degraded_after,
            "down_after": self.down_after,
            "live_after": self.live_after,
        }
        return out


# -- module singleton (the flightrec pattern) --------------------------------

_prober = None
_mod_lock = threading.Lock()


def configure(canary=None, interval=DEFAULT_INTERVAL,
              deadline=DEFAULT_DEADLINE, ring_size=DEFAULT_RING,
              degraded_after=1, down_after=3, live_after=2,
              jitter=0.2, logger=None, start=True):
    """Build (replacing any previous) and optionally start the process
    prober. Returns it. start=False builds an idle prober for tests that
    step `probe_once()` by hand."""
    global _prober
    with _mod_lock:
        if _prober is not None:
            _prober.stop()
        _prober = DeviceLinkProber(
            canary=canary, interval=interval, deadline=deadline,
            ring_size=ring_size, degraded_after=degraded_after,
            down_after=down_after, live_after=live_after,
            jitter=jitter, logger=logger)
        if start:
            _prober.start()
        return _prober


def get_prober():
    return _prober


def stop():
    global _prober
    with _mod_lock:
        if _prober is not None:
            _prober.stop()
            _prober = None
    global_stats.gauge("device_link_state", STATE_CODES[DISABLED])


def state():
    """Current link state; DISABLED (ready) when no prober runs."""
    p = _prober
    return p.state if p is not None else DISABLED


def is_down():
    p = _prober
    return p is not None and p.state == DOWN


def retry_after_seconds():
    """What a 503 should tell clients: one probe interval from now the
    state machine will have fresh evidence."""
    p = _prober
    return p.interval if p is not None else DEFAULT_INTERVAL


def summary():
    p = _prober
    if p is None:
        return {"state": DISABLED}
    return p.summary()


def snapshot(limit=None):
    p = _prober
    if p is None:
        return {"state": DISABLED, "ring": [], "transitions": []}
    return p.snapshot(limit=limit)
