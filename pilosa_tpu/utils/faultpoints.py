"""Named fault points for crash/fault testing (utils/faultpoints.py).

Production code marks the instants a crash test wants to hit —
``faultpoints.reached("oplog.fsync")`` — and tests arm those names to
raise, delay, or kill the process there. The discipline is the same as
the nop tracer and the disabled device-link prober: when nothing is
armed, the producer hook is ONE module-global check and returns, so the
hot write path pays nothing (verified by a bench_suite gate).

Arming:
  - env: ``PILOSA_TPU_FAULTPOINTS="import.post-append=exit@3;oplog.fsync=delay:0.2"``
    parsed by :func:`configure_from_env` (the server calls it at boot, so
    a crash-matrix harness arms a child before it starts serving);
  - HTTP: ``POST /debug/faultpoints {"arm": "resize.drain.apply=raise"}``
    on a live server (``GET`` lists armed points + hit counts).

Spec grammar: ``name=action[:param][@nth][xTimes]``
  - action ``raise``  -> raise :class:`FaultInjected` (default 1 time);
  - action ``delay``  -> sleep ``param`` seconds (default 0.1, default
    unlimited times — a delay is a slowdown, not a one-shot);
  - action ``exit``   -> ``os._exit(EXIT_CODE)`` — a hard crash: no
    atexit, no finally, no flush. Exactly what a kill -9 test wants.
  - ``@nth``   -> trigger starting at the Nth hit (1-based; default 1),
    so ``exit@5`` crashes under load, not on the first write;
  - ``xTimes`` -> trigger at most that many times (``xinf`` = unlimited).

Well-known point names (grep for ``faultpoints.reached``):
  ``import.post-append``      after the oplog append, before apply/ack
  ``import.pre-ack``          after apply, before the ack returns
  ``oplog.fsync``             inside the oplog, before os.fsync
  ``resize.drain.apply``      before applying one queued resize write
  ``resize.fetch``            before a resize shard fetch (drain timing)
  ``fragment.snapshot.rename``before the snapshot temp->live rename
"""

import os
import threading
import time

#: exit status used by the ``exit`` action — distinguishable in a crash
#: harness from an ordinary interpreter death
EXIT_CODE = 86

ENV_VAR = "PILOSA_TPU_FAULTPOINTS"


class FaultInjected(Exception):
    """Raised at an armed ``raise`` fault point."""


#: "no explicit xTimes suffix" marker — distinct from None (= unlimited)
_UNSET = object()


class _Spec:
    __slots__ = ("name", "action", "param", "nth", "times", "hits", "fired")

    def __init__(self, name, action, param=None, nth=1, times=_UNSET):
        if action not in ("raise", "delay", "exit"):
            raise ValueError(f"unknown fault action: {action!r}")
        self.name = name
        self.action = action
        self.param = param
        self.nth = max(1, int(nth))
        # raise/exit default to one-shot; a delay is a slowdown and
        # defaults to every hit
        if times is _UNSET:
            times = None if action == "delay" else 1
        self.times = times  # None = unlimited
        self.hits = 0
        self.fired = 0

    def to_json(self):
        return {"name": self.name, "action": self.action,
                "param": self.param, "nth": self.nth,
                "times": self.times, "hits": self.hits,
                "fired": self.fired}


_lock = threading.Lock()
_specs = {}
#: fast-path flag — `reached()` checks ONLY this when nothing is armed
_armed = False


def parse_spec(text):
    """``name=action[:param][@nth][xTimes]`` -> :class:`_Spec`."""
    text = text.strip()
    name, sep, rhs = text.partition("=")
    if not sep or not name or not rhs:
        raise ValueError(f"invalid fault spec: {text!r}")
    times = _UNSET
    if "x" in rhs:
        # only a real ``xN``/``xinf`` suffix — the action ``exit``
        # contains an 'x' of its own
        head, _, t = rhs.rpartition("x")
        if t.isdigit() or t.lower() == "inf":
            rhs = head
            times = None if t.lower() == "inf" else int(t)
    nth = 1
    if "@" in rhs:
        rhs, _, n = rhs.partition("@")
        nth = int(n)
    action, _, param = rhs.partition(":")
    parsed = None
    if param:
        parsed = float(param)
    elif action == "delay":
        parsed = 0.1
    return _Spec(name.strip(), action.strip(), param=parsed,
                 nth=nth, times=times)


def arm(spec_text):
    """Arm one fault point from its spec string; re-arming a name
    replaces its spec (hit counters restart)."""
    global _armed
    spec = parse_spec(spec_text)
    with _lock:
        _specs[spec.name] = spec
        _armed = True
    return spec


def disarm(name=None):
    """Disarm one point, or every point when name is None."""
    global _armed
    with _lock:
        if name is None:
            _specs.clear()
        else:
            _specs.pop(name, None)
        _armed = bool(_specs)


def configure_from_env(environ=None):
    """Arm every ``;``-separated spec in $PILOSA_TPU_FAULTPOINTS. Called
    by the server at boot so subprocess crash harnesses arm points the
    child reaches before HTTP is up (boot replay, fragment open)."""
    raw = (environ if environ is not None else os.environ).get(ENV_VAR, "")
    specs = [s for s in raw.split(";") if s.strip()]
    for s in specs:
        arm(s)
    return len(specs)


def reached(name):
    """Producer hook. Unarmed: one global check, nothing else — safe to
    leave on the hottest write path."""
    if not _armed:
        return
    _fire(name)


def _fire(name):
    with _lock:
        spec = _specs.get(name)
        if spec is None:
            return
        spec.hits += 1
        if spec.hits < spec.nth:
            return
        if spec.times is not None and spec.fired >= spec.times:
            return
        spec.fired += 1
        action, param = spec.action, spec.param
    # act OUTSIDE the lock: a delay must not serialize unrelated points,
    # and a raise must not leave the registry wedged
    if action == "delay":
        time.sleep(param)
    elif action == "exit":
        os._exit(EXIT_CODE)
    else:
        raise FaultInjected(f"fault point triggered: {name}")


def armed():
    return _armed


def snapshot():
    """State for GET /debug/faultpoints."""
    with _lock:
        return {"armed": _armed,
                "points": [s.to_json() for s in _specs.values()]}
