"""Tracing: global-tracer indirection with nop default.

Reference: tracing/tracing.go:27-75 (GlobalTracer var + StartSpanFromContext)
and the opentracing adapter wired by cmd/server.go:78-93. Here the same
shape: a process-global `Tracer` defaulting to nop, spans started on every
executor/API hot path, and trace context propagated across nodes via HTTP
headers (reference: http/handler.go extractTracing / http/client.go inject).

Backends: `NopTracer` (default, zero overhead), `InMemoryTracer` (tests +
/debug inspection), and — when opentelemetry happens to be importable —
`OTelTracer` adapting to an OTel tracer. No hard OTel dependency.
"""

import contextlib
import random
import threading
import time
from collections import OrderedDict

TRACE_HEADER = "X-Pilosa-Trace-Id"
PARENT_HEADER = "X-Pilosa-Span-Id"

_local = threading.local()


class Span:
    """One timed operation. Finished spans carry duration + tags.

    `start` is wall-clock (for display and cross-node alignment);
    `duration` is measured on the monotonic clock so NTP steps and
    operator clock changes cannot corrupt it — durations feed both the
    profile tree and the skew estimator, which assumes they are real
    elapsed time."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "tags",
                 "start", "duration", "_t0")

    def __init__(self, name, trace_id, span_id, parent_id, tags):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.tags = dict(tags)
        self.start = time.time()
        self.duration = None
        self._t0 = time.perf_counter()

    @classmethod
    def from_dict(cls, d):
        """Rebuild a (finished) span from its to_dict shape — used when the
        coordinator merges spans fetched from remote nodes."""
        span = cls(d.get("name", ""), d.get("traceID"), d.get("spanID"),
                   d.get("parentID"), d.get("tags") or {})
        span.start = d.get("start")
        span.duration = d.get("duration")
        return span

    def set_tag(self, key, value):
        self.tags[key] = value

    def finish(self):
        if self.duration is None:
            self.duration = time.perf_counter() - self._t0

    def to_dict(self):
        """JSON shape for /debug/traces and query profiles."""
        return {"name": self.name, "traceID": self.trace_id,
                "spanID": self.span_id, "parentID": self.parent_id,
                "tags": dict(self.tags), "start": self.start,
                "duration": self.duration}


class NopTracer:
    """Default tracer: allocates nothing, records nothing."""

    def on_finish(self, span):
        pass


class InMemoryTracer:
    """Collects finished spans in a bounded ring — the OLDEST spans are
    evicted past max_spans, so /debug/traces always shows recent activity
    on a long-lived server (trace retention); for tests and debugging."""

    def __init__(self, max_spans=10000):
        self.max_spans = max_spans
        self.spans = []
        self._lock = threading.Lock()

    def on_finish(self, span):
        with self._lock:
            self.spans.append(span)
            if len(self.spans) > self.max_spans:
                del self.spans[:len(self.spans) - self.max_spans]

    def find(self, name):
        with self._lock:
            return [s for s in self.spans if s.name == name]

    def to_dicts(self):
        """JSON dump for GET /debug/traces, oldest first."""
        with self._lock:
            return [s.to_dict() for s in self.spans]

    def clear(self):
        with self._lock:
            self.spans.clear()


class TraceIndex:
    """Finished spans indexed by trace id in a bounded two-level ring:
    at most `max_traces` trace ids retained (oldest-touched evicted), at
    most `max_spans_per_trace` spans per trace (later spans dropped and
    counted). This is the per-node half of cross-node trace assembly —
    the coordinator pulls a remote node's slice of a trace via
    GET /debug/traces/{trace_id}?local=true and merges it into one tree.

    Always on, but free on the default path: under the NopTracer with no
    incoming trace context no Span objects exist to index (see
    start_span's nop-fast path), so the index only ever sees spans from
    profiled / explicitly traced queries."""

    def __init__(self, max_traces=256, max_spans_per_trace=256):
        self.max_traces = max_traces
        self.max_spans_per_trace = max_spans_per_trace
        self._traces = OrderedDict()  # trace_id -> [Span, ...]
        self._lock = threading.Lock()
        self.dropped_spans = 0
        self.evicted_traces = 0

    def add(self, span):
        if span.trace_id is None:
            return
        with self._lock:
            spans = self._traces.get(span.trace_id)
            if spans is None:
                spans = self._traces[span.trace_id] = []
            else:
                self._traces.move_to_end(span.trace_id)
            if len(spans) < self.max_spans_per_trace:
                spans.append(span)
            else:
                self.dropped_spans += 1
            while len(self._traces) > self.max_traces:
                self._traces.popitem(last=False)
                self.evicted_traces += 1

    def get(self, trace_id):
        """Finished spans of one trace as dicts (oldest-started first),
        or [] when unknown/evicted."""
        with self._lock:
            spans = list(self._traces.get(trace_id, ()))
        return [s.to_dict() for s in spans]

    def stats(self):
        with self._lock:
            return {"traces": len(self._traces),
                    "maxTraces": self.max_traces,
                    "maxSpansPerTrace": self.max_spans_per_trace,
                    "droppedSpans": self.dropped_spans,
                    "evictedTraces": self.evicted_traces}

    def clear(self):
        with self._lock:
            self._traces.clear()
            self.dropped_spans = 0
            self.evicted_traces = 0


_global_tracer = NopTracer()

# Secondary finished-span consumer (utils/profile.py registers its
# per-query router here). Separate from the tracer so query profiling
# works with the nop tracer still installed.
_span_sink = None

# Per-node finished-span index for cross-node assembly. Module-level and
# always present (zero-cost when no spans are created — see class doc).
_trace_index = TraceIndex()


def set_tracer(tracer):
    """Install the process-global tracer (reference: tracing.go SetGlobal)."""
    global _global_tracer
    _global_tracer = tracer if tracer is not None else NopTracer()


def get_tracer():
    return _global_tracer


def set_span_sink(sink):
    global _span_sink
    _span_sink = sink


def trace_index():
    return _trace_index


def configure_trace_index(max_traces=256, max_spans_per_trace=256):
    """Resize (and reset) the per-node trace index; max_traces=0 disables
    retention entirely (spans still flow to the tracer/sink)."""
    global _trace_index
    _trace_index = TraceIndex(max_traces=max_traces,
                              max_spans_per_trace=max_spans_per_trace)
    return _trace_index


def index_span(span):
    """Feed one finished span into the trace index (also called by
    profile.finish for the query root span, which bypasses start_span)."""
    if _trace_index.max_traces > 0:
        _trace_index.add(span)


def get_trace(trace_id):
    """This node's finished spans for one trace id, as dicts."""
    return _trace_index.get(trace_id)


def _new_id():
    return "%016x" % random.getrandbits(64)


def new_trace_id():
    return _new_id()


def current_span():
    return getattr(_local, "span", None)


@contextlib.contextmanager
def with_span(span):
    """Adopt `span` as the active context on THIS thread (for worker
    threads continuing a request's trace; does not finish the span)."""
    prev = current_span()
    _local.span = span
    try:
        yield span
    finally:
        _local.span = prev


@contextlib.contextmanager
def start_span(name, **tags):
    """Start a child of the current thread's active span (or a new trace).

    Nop-fast: when the global tracer is the NopTracer and there is no
    incoming context, this allocates no Span at all.
    """
    tracer = _global_tracer
    parent = current_span()
    if isinstance(tracer, NopTracer) and parent is None:
        yield None
        return
    trace_id = parent.trace_id if parent else _new_id()
    span = Span(name, trace_id, _new_id(),
                parent.span_id if parent else None, tags)
    prev = parent
    _local.span = span
    try:
        yield span
    finally:
        _local.span = prev
        span.finish()
        tracer.on_finish(span)
        if _span_sink is not None:
            _span_sink(span)
        index_span(span)


# -- cross-node propagation (reference: handler extractTracing / client
#    inject) ---------------------------------------------------------------

def inject_headers(headers=None):
    """Add trace context headers for an outgoing internal request."""
    headers = dict(headers or {})
    span = current_span()
    if span is not None:
        headers[TRACE_HEADER] = span.trace_id
        headers[PARENT_HEADER] = span.span_id
    return headers


def _header_get(headers, name):
    """Case-insensitive header lookup. http.server's Message headers are
    already case-insensitive, but plain dicts (tests, proxies that
    lowercase header names per HTTP/2) are not — fall back to a scan."""
    value = headers.get(name)
    if value is not None:
        return value
    want = name.lower()
    for k in headers:
        if isinstance(k, str) and k.lower() == want:
            return headers[k]
    return None


@contextlib.contextmanager
def span_from_headers(name, headers, **tags):
    """Continue a remote trace from incoming HTTP headers (case-insensitive
    lookup — see _header_get)."""
    trace_id = _header_get(headers, TRACE_HEADER)
    parent_id = _header_get(headers, PARENT_HEADER)
    if trace_id is None:
        with start_span(name, **tags) as span:
            yield span
        return
    tracer = _global_tracer
    span = Span(name, trace_id, _new_id(), parent_id, tags)
    prev = current_span()
    _local.span = span
    try:
        yield span
    finally:
        _local.span = prev
        span.finish()
        tracer.on_finish(span)
        if _span_sink is not None:
            _span_sink(span)
        index_span(span)


# -- cross-node assembly (Dapper, Sigelman et al. 2010 §5) ------------------
#
# Remote nodes timestamp spans with THEIR wall clock. The coordinator
# estimates each node's clock offset from the fan-out request it sent:
# for a request dispatched at local wall time t_send that returned at
# t_recv, the remote handler span covering it ran [r_start, r_end] in
# remote wall time. Assuming symmetric network delay (NTP's assumption):
#
#     theta = ((r_start - t_send) + (r_end - t_recv)) / 2
#
# is the remote clock minus the local clock; subtracting theta from
# every remote span start places it on the coordinator's timeline. When
# several request/response pairs exist for one node, the pair with the
# smallest round-trip envelope (t_recv - t_send) bounds theta tightest
# and wins. Durations are never adjusted — they are monotonic-clock
# measurements and already comparable across nodes.

def estimate_skew(local_spans, remote_spans):
    """Estimate one remote node's clock offset (remote - local, seconds).

    `local_spans`: span dicts recorded on this node (the fan-out client
    spans among them). `remote_spans`: span dicts fetched from the
    remote node. A pairing is any remote span whose parentID is a local
    span's spanID — i.e. the remote server span directly under our
    client span. Returns 0.0 when no pairing exists (spans merge
    uncorrected rather than not at all)."""
    by_id = {s["spanID"]: s for s in local_spans
             if s.get("spanID") and s.get("duration") is not None}
    best = None  # (rtt, theta)
    for r in remote_spans:
        local = by_id.get(r.get("parentID"))
        if local is None or r.get("duration") is None:
            continue
        t_send, t_recv = local["start"], local["start"] + local["duration"]
        r_start, r_end = r["start"], r["start"] + r["duration"]
        theta = ((r_start - t_send) + (r_end - t_recv)) / 2.0
        rtt = local["duration"]
        if best is None or rtt < best[0]:
            best = (rtt, theta)
    return best[1] if best else 0.0


def merge_remote_spans(local_spans, remote_by_node):
    """Merge per-node remote span dicts into the local timeline.

    Returns (all_spans, skew_by_node): remote starts are shifted by each
    node's estimated offset, every remote span is tagged with its node
    id, and duplicates (same spanID) are dropped. `remote_by_node` maps
    node id -> list of span dicts as returned by get_trace()."""
    seen = {s["spanID"] for s in local_spans if s.get("spanID")}
    merged = list(local_spans)
    skew_by_node = {}
    for node_id, spans in remote_by_node.items():
        theta = estimate_skew(local_spans, spans)
        skew_by_node[node_id] = theta
        for s in spans:
            if s.get("spanID") in seen:
                continue
            seen.add(s.get("spanID"))
            s = dict(s)
            if s.get("start") is not None:
                s["start"] = s["start"] - theta
            tags = dict(s.get("tags") or {})
            tags.setdefault("node", node_id)
            s["tags"] = tags
            merged.append(s)
    return merged, skew_by_node


def assemble_tree(spans):
    """Build the span forest from flat span dicts: children nested under
    their parentID when present, orphans become roots. Children sort by
    corrected start time. Returns the list of root nodes."""
    nodes = {}
    for s in spans:
        n = dict(s)
        n["children"] = []
        nodes[s["spanID"]] = n
    roots = []
    for s in spans:
        n = nodes[s["spanID"]]
        parent = nodes.get(s.get("parentID"))
        if parent is not None and parent is not n:
            parent["children"].append(n)
        else:
            roots.append(n)

    def _sort(children):
        children.sort(key=lambda c: (c.get("start") or 0.0))
        for c in children:
            _sort(c["children"])
    _sort(roots)
    return roots
