"""Tracing: global-tracer indirection with nop default.

Reference: tracing/tracing.go:27-75 (GlobalTracer var + StartSpanFromContext)
and the opentracing adapter wired by cmd/server.go:78-93. Here the same
shape: a process-global `Tracer` defaulting to nop, spans started on every
executor/API hot path, and trace context propagated across nodes via HTTP
headers (reference: http/handler.go extractTracing / http/client.go inject).

Backends: `NopTracer` (default, zero overhead), `InMemoryTracer` (tests +
/debug inspection), and — when opentelemetry happens to be importable —
`OTelTracer` adapting to an OTel tracer. No hard OTel dependency.
"""

import contextlib
import random
import threading
import time

TRACE_HEADER = "X-Pilosa-Trace-Id"
PARENT_HEADER = "X-Pilosa-Span-Id"

_local = threading.local()


class Span:
    """One timed operation. Finished spans carry duration + tags."""

    __slots__ = ("name", "trace_id", "span_id", "parent_id", "tags",
                 "start", "duration")

    def __init__(self, name, trace_id, span_id, parent_id, tags):
        self.name = name
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.tags = dict(tags)
        self.start = time.time()
        self.duration = None

    def set_tag(self, key, value):
        self.tags[key] = value

    def finish(self):
        if self.duration is None:
            self.duration = time.time() - self.start

    def to_dict(self):
        """JSON shape for /debug/traces and query profiles."""
        return {"name": self.name, "traceID": self.trace_id,
                "spanID": self.span_id, "parentID": self.parent_id,
                "tags": dict(self.tags), "start": self.start,
                "duration": self.duration}


class NopTracer:
    """Default tracer: allocates nothing, records nothing."""

    def on_finish(self, span):
        pass


class InMemoryTracer:
    """Collects finished spans in a bounded ring — the OLDEST spans are
    evicted past max_spans, so /debug/traces always shows recent activity
    on a long-lived server (trace retention); for tests and debugging."""

    def __init__(self, max_spans=10000):
        self.max_spans = max_spans
        self.spans = []
        self._lock = threading.Lock()

    def on_finish(self, span):
        with self._lock:
            self.spans.append(span)
            if len(self.spans) > self.max_spans:
                del self.spans[:len(self.spans) - self.max_spans]

    def find(self, name):
        with self._lock:
            return [s for s in self.spans if s.name == name]

    def to_dicts(self):
        """JSON dump for GET /debug/traces, oldest first."""
        with self._lock:
            return [s.to_dict() for s in self.spans]

    def clear(self):
        with self._lock:
            self.spans.clear()


_global_tracer = NopTracer()

# Secondary finished-span consumer (utils/profile.py registers its
# per-query router here). Separate from the tracer so query profiling
# works with the nop tracer still installed.
_span_sink = None


def set_tracer(tracer):
    """Install the process-global tracer (reference: tracing.go SetGlobal)."""
    global _global_tracer
    _global_tracer = tracer if tracer is not None else NopTracer()


def get_tracer():
    return _global_tracer


def set_span_sink(sink):
    global _span_sink
    _span_sink = sink


def _new_id():
    return "%016x" % random.getrandbits(64)


def new_trace_id():
    return _new_id()


def current_span():
    return getattr(_local, "span", None)


@contextlib.contextmanager
def with_span(span):
    """Adopt `span` as the active context on THIS thread (for worker
    threads continuing a request's trace; does not finish the span)."""
    prev = current_span()
    _local.span = span
    try:
        yield span
    finally:
        _local.span = prev


@contextlib.contextmanager
def start_span(name, **tags):
    """Start a child of the current thread's active span (or a new trace).

    Nop-fast: when the global tracer is the NopTracer and there is no
    incoming context, this allocates no Span at all.
    """
    tracer = _global_tracer
    parent = current_span()
    if isinstance(tracer, NopTracer) and parent is None:
        yield None
        return
    trace_id = parent.trace_id if parent else _new_id()
    span = Span(name, trace_id, _new_id(),
                parent.span_id if parent else None, tags)
    prev = parent
    _local.span = span
    try:
        yield span
    finally:
        _local.span = prev
        span.finish()
        tracer.on_finish(span)
        if _span_sink is not None:
            _span_sink(span)


# -- cross-node propagation (reference: handler extractTracing / client
#    inject) ---------------------------------------------------------------

def inject_headers(headers=None):
    """Add trace context headers for an outgoing internal request."""
    headers = dict(headers or {})
    span = current_span()
    if span is not None:
        headers[TRACE_HEADER] = span.trace_id
        headers[PARENT_HEADER] = span.span_id
    return headers


def _header_get(headers, name):
    """Case-insensitive header lookup. http.server's Message headers are
    already case-insensitive, but plain dicts (tests, proxies that
    lowercase header names per HTTP/2) are not — fall back to a scan."""
    value = headers.get(name)
    if value is not None:
        return value
    want = name.lower()
    for k in headers:
        if isinstance(k, str) and k.lower() == want:
            return headers[k]
    return None


@contextlib.contextmanager
def span_from_headers(name, headers, **tags):
    """Continue a remote trace from incoming HTTP headers (case-insensitive
    lookup — see _header_get)."""
    trace_id = _header_get(headers, TRACE_HEADER)
    parent_id = _header_get(headers, PARENT_HEADER)
    if trace_id is None:
        with start_span(name, **tags) as span:
            yield span
        return
    tracer = _global_tracer
    span = Span(name, trace_id, _new_id(), parent_id, tags)
    prev = current_span()
    _local.span = span
    try:
        yield span
    finally:
        _local.span = prev
        span.finish()
        tracer.on_finish(span)
        if _span_sink is not None:
            _span_sink(span)
