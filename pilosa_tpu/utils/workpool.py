"""Shared bounded worker pool for host-side shard work.

Reference: executor.mapReduce fans per-shard work across a bounded worker
pool (executor.go:2455 + shardsByNode); our port kept the map-reduce
STRUCTURE but ran every shard loop serially in Python, while the cluster
layer spawned an unbounded thread per node per query — the opposite
failure mode. This module is the single bounded pool both sides share:

- `WorkPool.map_ordered(fn, items)` — results in SUBMISSION order, so an
  order-sensitive reduce (Min/Max tie-breaking, MinRow best-tracking)
  over pool results is bit-identical to the serial loop it replaced.
- Fail-fast: the first task error cancels every not-yet-claimed task in
  the job and re-raises on the submitter. In-flight tasks finish (they
  hold locks and device handles the pool cannot safely interrupt).
- `shard_map_reduce(shards, mapper, reducer)` — the per-shard loop shape
  in one place: ordered map, then an ordered host reduce.
- Per-task trace spans: tasks adopt the SUBMITTER's span context, so a
  query profile attributes pool work to the query that submitted it
  (same propagation contract as cluster/executor.py's fan-out threads).
- Queue-depth / busy-worker gauges in the global stats registry
  (`workpool_*` at /metrics, snapshot dict at /debug/vars).

Concurrency discipline (load-bearing):

- Workers do HOST work only. Per-shard tasks may enqueue SINGLE-device
  ops (fragment plane uploads, per-shard popcounts) — those are safe to
  issue concurrently on every backend. Every MULTI-device (GSPMD) launch
  still goes through exec/stacked.py's process-wide _DISPATCH_LOCK, so
  the CPU-backend rendezvous-wedge fix (PR 1) is untouched: the pool
  parallelizes the work AROUND the dispatch lock, never launches inside
  workers that could interleave with it.
- Worker threads NEVER block on the pool: a map_ordered call made from
  inside a worker runs its tasks inline (serially) on that worker.
  Submitters therefore always make progress, nested fan-out cannot
  deadlock a bounded pool, and the thread count stays exactly
  `workers` no matter how deep the call tree.
- `workers=1` (or a single-item job) bypasses the threads entirely and
  runs inline on the caller — byte-for-byte the old serial behavior,
  which the differential tests use as the oracle.

Pool size: `--workers` flag / PILOSA_TPU_WORKERS env, default
min(32, cpu). Threads (not processes) suffice: the gathers are
numpy-copy heavy and numpy/XLA release the GIL in the copies.
"""

import os
import queue
import threading

from . import flightrec, tracing
from .stats import global_stats


def default_workers():
    """min(32, cpu), overridable via PILOSA_TPU_WORKERS (invalid or
    non-positive values fall back to the default rather than crashing
    the server at import time)."""
    env = os.environ.get("PILOSA_TPU_WORKERS")
    if env:
        try:
            n = int(env)
            if n >= 1:
                return n
        except ValueError:
            pass
    return min(32, os.cpu_count() or 1)


class _Job:
    """One map_ordered call: a task vector with ordered results.

    Claim protocol: workers (and nothing else) claim the next unclaimed
    index under the job lock; the first error flips the job into
    cancelled state, so later claims return None and unclaimed tasks
    never run. Completion = every index claimed AND every claimed task
    finished."""

    __slots__ = ("fn", "items", "results", "error", "lock", "next_idx",
                 "in_flight", "cancelled_at", "done", "span")

    def __init__(self, fn, items, span):
        self.fn = fn
        self.items = items
        self.results = [None] * len(items)
        self.error = None
        self.lock = threading.Lock()
        self.next_idx = 0
        self.in_flight = 0
        self.cancelled_at = None  # first index that never ran
        self.done = threading.Event()
        self.span = span  # submitter's trace context

    def claim(self):
        with self.lock:
            if self.error is not None or self.next_idx >= len(self.items):
                return None
            i = self.next_idx
            self.next_idx += 1
            self.in_flight += 1
            return i

    def _finish_locked(self):
        if self.in_flight == 0 and (
                self.error is not None or self.next_idx >= len(self.items)):
            self.done.set()

    def run_one(self, i):
        try:
            r = self.fn(self.items[i])
        except BaseException as exc:  # noqa: BLE001 — re-raised on submitter
            with self.lock:
                if self.error is None:
                    self.error = exc
                    # cancel: unclaimed indices never run
                    self.cancelled_at = self.next_idx
                    self.next_idx = len(self.items)
                self.in_flight -= 1
                self._finish_locked()
            return
        with self.lock:
            self.results[i] = r
            self.in_flight -= 1
            self._finish_locked()


class WorkPool:
    """Bounded pool of daemon worker threads shared by every submitter.

    One instance per process (see get_pool); tests build private
    instances to pin the worker count."""

    def __init__(self, workers=None, name="workpool"):
        self.workers = int(workers) if workers else default_workers()
        if self.workers < 1:
            raise ValueError(f"workers must be >= 1, got {self.workers}")
        self.name = name
        self._queue = queue.SimpleQueue()
        self._threads = []
        self._threads_lock = threading.Lock()
        self._stop = False
        self._in_worker = threading.local()
        # observability (pushed as gauges; snapshot at /debug/vars)
        self._stats_lock = threading.Lock()
        self._queued_tasks = 0
        self._busy = 0
        self.tasks_total = 0
        self.jobs_total = 0
        self.inline_jobs_total = 0
        self.errors_total = 0
        self._push_gauges()  # register the metrics at zero

    # -- lifecycle -----------------------------------------------------------

    def _ensure_threads(self):
        """Start workers lazily: importing the module (or a workers=1
        pool) must never spawn threads."""
        if self._threads or self.workers <= 1:
            return
        with self._threads_lock:
            if self._threads or self._stop:
                return
            for i in range(self.workers):
                t = threading.Thread(
                    target=self._worker_loop,
                    name=f"pilosa-{self.name}-{i}", daemon=True)
                t.start()
                self._threads.append(t)

    def shutdown(self):
        """Stop the workers (tests; the server relies on daemon exit).
        Workers exit only via the sentinel AFTER finishing any job they
        hold, and jobs that raced into the queue are drained inline here,
        so no submitter can hang on a replaced pool."""
        with self._threads_lock:
            self._stop = True
            threads = list(self._threads)
        for _ in threads:
            self._queue.put(None)
        for t in threads:
            t.join(timeout=5)
        while True:
            try:
                job = self._queue.get_nowait()
            except queue.Empty:
                break
            if job is None:
                continue
            while True:
                i = job.claim()
                if i is None:
                    break
                job.run_one(i)

    # -- gauges --------------------------------------------------------------

    def _push_gauges(self):
        global_stats.gauge("workpool_queue_depth", self._queued_tasks)
        global_stats.gauge("workpool_busy_workers", self._busy)

    def stats(self):
        """Snapshot for /debug/vars."""
        with self._stats_lock:
            return {
                "workers": self.workers,
                "queue_depth": self._queued_tasks,
                "busy_workers": self._busy,
                "tasks": self.tasks_total,
                "jobs": self.jobs_total,
                "inline_jobs": self.inline_jobs_total,
                "errors": self.errors_total,
            }

    # -- execution -----------------------------------------------------------

    def _worker_loop(self):
        self._in_worker.active = True
        while True:
            job = self._queue.get()
            if job is None:  # exit ONLY via sentinel: a popped job is
                return       # always drained, never dropped on shutdown
            while True:
                i = job.claim()
                if i is None:
                    break
                with self._stats_lock:
                    self._queued_tasks -= 1
                    self._busy += 1
                    self._push_gauges()
                try:
                    self._run_traced(job, i)
                finally:
                    with self._stats_lock:
                        self._busy -= 1
                        self._push_gauges()

    def _run_traced(self, job, i):
        """Run one task under the submitter's trace context so profiles
        and traces attribute pool work to the submitting query."""
        if job.span is None:
            job.run_one(i)
            return
        with tracing.with_span(job.span):
            with tracing.start_span(f"{self.name}.task", task=i):
                job.run_one(i)

    def _run_inline(self, fn, items):
        """The workers=1 / nested / single-item path: the exact serial
        loop (no threads, no spans, no counters beyond totals)."""
        with self._stats_lock:
            self.inline_jobs_total += 1
            self.tasks_total += len(items)
        return [fn(item) for item in items]

    def map_ordered(self, fn, items):
        """fn over items on the pool; returns results in ITEM order.
        The first task exception cancels unclaimed tasks and re-raises
        here. Calls from inside a pool worker run inline (see module
        docstring)."""
        items = list(items)
        if self.workers == 1 or len(items) <= 1 \
                or getattr(self._in_worker, "active", False) or self._stop:
            return self._run_inline(fn, items)
        self._ensure_threads()
        job = _Job(fn, items, tracing.current_span())
        with self._stats_lock:
            self.jobs_total += 1
            self.tasks_total += len(items)
            # Saturation: every worker is already busy when more work
            # arrives — the new job queues behind in-flight shards.
            saturated = self._busy >= self.workers and self._queued_tasks > 0
            backlog = self._queued_tasks
            self._queued_tasks += len(items)
            self._push_gauges()
        if saturated:
            flightrec.record("workpool.saturated", pool=self.name,
                             workers=self.workers, backlog=backlog,
                             incoming=len(items))
        for _ in range(min(self.workers, len(items))):
            self._queue.put(job)
        while not job.done.wait(timeout=1.0):
            if self._stop:
                # pool replaced mid-job (configure during serving): the
                # submitter finishes the remaining tasks itself, then
                # waits out whatever is still in flight on old workers
                while True:
                    i = job.claim()
                    if i is None:
                        break
                    job.run_one(i)
                job.done.wait()
                break
        if job.error is not None:
            with self._stats_lock:
                self.errors_total += 1
                # cancelled tasks were counted queued; settle the gauge
                if job.cancelled_at is not None:
                    self._queued_tasks -= len(items) - job.cancelled_at
                    self._push_gauges()
            raise job.error
        return job.results


# ---------------------------------------------------------------- process pool

# Register the gauges at import (zero), so /metrics and /debug/vars show
# them before the first job ever runs.
global_stats.gauge("workpool_queue_depth", 0)
global_stats.gauge("workpool_busy_workers", 0)

_pool = None
_pool_lock = threading.Lock()


def get_pool():
    """The process-shared pool (created on first use)."""
    global _pool
    if _pool is None:
        with _pool_lock:
            if _pool is None:
                _pool = WorkPool()
    return _pool


def configure(workers):
    """Install a process pool of the given size (--workers flag; tests).
    Replaces any existing pool; its workers drain and exit."""
    global _pool
    with _pool_lock:
        old = _pool
        _pool = WorkPool(workers)
    if old is not None:
        old.shutdown()
    return _pool


def worker_count():
    return get_pool().workers


def shard_map_reduce(shards, mapper, reducer=None, initial=None, pool=None):
    """Map `mapper` over `shards` on the shared pool, then reduce the
    results IN SHARD ORDER on the caller: ordered reduction makes
    order-sensitive merges (Min/Max tie-breaks, MinRow best-tracking)
    identical at every worker count — `workers=1` is the oracle the
    differential tests compare against.

    reducer(acc, result) -> acc; None returns the ordered result list.
    """
    results = (pool or get_pool()).map_ordered(mapper, shards)
    if reducer is None:
        return results
    acc = initial
    for r in results:
        acc = reducer(acc, r)
    return acc
