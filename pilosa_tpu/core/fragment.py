"""Fragment: the (index, field, view, shard) storage unit.

Reference: fragment.go:100. There, a fragment is an mmap'd roaring file plus
an appended op log; bit position = rowID*ShardWidth + colID%ShardWidth
(fragment.go:3090). Here the same roaring file (+WAL) is the at-rest format,
while the query-time representation is dense row planes in device HBM:
`row_device(rowID)` densifies the row's containers into a [WORDS_PER_ROW]
uint32 array and caches it on device, invalidated by writes. All set algebra
on those planes happens in the executor via pilosa_tpu.ops.

Durability model (reference: fragment.go:2311-2395, roaring op log):
  file = roaring snapshot ++ op log. Every mutation appends an op record;
  when the op count exceeds max_op_n (default 10k) the fragment is
  snapshotted (file rewritten via temp+rename, op log reset).
"""

import itertools
import os
import hashlib
import threading
from collections import OrderedDict

import numpy as np

_fragment_uids = itertools.count(1)

# Cross-fragment LRU of resident mutex rows-vectors (~8 MB each; see
# Fragment._mutex_vector). 64 bounds worst-case host RAM at ~512 MB.
_MUTEX_VECTOR_CAP = 64
_MUTEX_VECTOR_LOCK = threading.Lock()
_MUTEX_VECTORS = OrderedDict()

from ..roaring import (
    Bitmap,
    OP_ADD,
    OP_ADD_BATCH,
    OP_ADD_ROARING,
    OP_REMOVE,
    OP_REMOVE_BATCH,
    OP_REMOVE_ROARING,
    deserialize,
    encode_op,
    merge_bitmaps,
    serialize,
)
from ..shardwidth import (
    CONTAINERS_PER_SHARD,
    SHARD_WIDTH,
    WORDS_PER_CONTAINER,
    WORDS_PER_ROW,
)
from ..storage import oplog as oplog_mod
from ..utils import faultpoints

# Number of rows per merkle hash block (reference: fragment.go:80).
HASH_BLOCK_SIZE = 100

# Default op threshold before snapshotting (reference: fragment.go:85).
DEFAULT_MAX_OP_N = 10_000

# BSI row layout (reference: fragment.go:91-93).
BSI_EXISTS_BIT = 0
BSI_SIGN_BIT = 1
BSI_OFFSET_BIT = 2

# Boolean field rows (reference: fragment.go:88-89).
FALSE_ROW_ID = 0
TRUE_ROW_ID = 1


class Fragment:
    def __init__(self, path, index, field, view, shard,
                 max_op_n=DEFAULT_MAX_OP_N, snapshot_queue=None, mutexed=False,
                 cache_type="none", cache_size=0):
        from .cache import new_cache

        self.path = path
        self.index = index
        self.field = field
        self.view = view
        self.shard = shard
        self.max_op_n = max_op_n
        self.snapshot_queue = snapshot_queue
        self.mutexed = mutexed
        # TopN candidate cache (reference: fragment.cache fragment.go:129)
        self.cache = new_cache(cache_type, cache_size)

        self.storage = Bitmap()
        self.op_n = 0
        self.flags = 0
        self._file = None
        self._snapshot_pending = False
        self._row_ids_cache = None
        # Mutex rows-vector: column offset -> row id, built lazily and
        # maintained incrementally so single-bit mutex writes are O(1)
        # instead of probing every row (reference: rowsVector
        # fragment.go:3102). None = not built / invalidated by a bulk op.
        self._mutex_vec = None
        self._lock = threading.RLock()

        # Device plane cache: rowID -> jax array; bumped generation
        # invalidates derived stacks. uid is process-unique so caches keyed
        # by (uid, generation) can never confuse a recreated fragment
        # (same path, fresh counter) with its predecessor.
        self._row_cache = {}
        self.generation = 0
        self.uid = next(_fragment_uids)
        # optional owner hook (View._bump_mutations): lets a container
        # keep an O(1) any-fragment-changed fingerprint for serving caches
        self.on_mutate = None

        # Block checksums cache (anti-entropy; reference fragment.checksums).
        self._checksums = {}

    # -- lifecycle ----------------------------------------------------------

    def open(self):
        with self._lock:
            os.makedirs(os.path.dirname(self.path), exist_ok=True)
            if os.path.exists(self.path) and os.path.getsize(self.path) > 0:
                with open(self.path, "rb") as f:
                    data = f.read()
                self.storage, self.flags, self.op_n = deserialize(data)
                if self.op_n > self.max_op_n:
                    self._snapshot_locked()
            else:
                # Fresh fragment: seed the file with an empty-bitmap snapshot
                # header so appended WAL ops always follow a valid roaring
                # section (the reference's file is likewise snapshot ++ ops).
                with open(self.path, "wb") as f:
                    f.write(serialize(self.storage, flags=self.flags))
            if self._file is None:  # _snapshot_locked may have opened it
                self._file = open(self.path, "ab")
            from .cache import load_cache

            load_cache(self.cache, self.cache_path)
            # Staleness guard: a populated fragment with an empty cache
            # (pre-cache data dir, lost .cache file) would otherwise serve
            # TopN from whatever rows get written next — rebuild instead.
            if (self.cache is not None and len(self.cache) == 0
                    and self.storage.count() > 0):
                self.recalculate_cache()
        return self

    @property
    def cache_path(self):
        return self.path + ".cache"

    def flush_cache(self):
        """(reference: fragment.FlushCache fragment.go:2397)"""
        from .cache import save_cache

        with self._lock:
            save_cache(self.cache, self.cache_path)

    def recalculate_cache(self):
        """Rebuild cached counts from storage (reference:
        fragment.RecalculateCache fragment.go:2389)."""
        if self.cache is None:
            return
        with self._lock:
            self.cache.clear()
            for row_id in self.row_ids():
                self.cache.add(row_id, self.row_count(row_id))

    def close(self):
        with self._lock:
            self.flush_cache()
            if self._file:
                if oplog_mod.fsync_policy() != "never":
                    oplog_mod.fsync_file(self._file)
                self._file.close()
                self._file = None
            self._row_cache.clear()
        self._drop_mutex_vec()

    def sync(self):
        """Force the WAL tail to disk regardless of fsync policy (used by
        the oplog checkpoint: fragments must be durable before the log
        above them truncates)."""
        with self._lock:
            if self._file is not None:
                oplog_mod.fsync_file(self._file)

    @property
    def is_open(self):
        return self._file is not None

    # -- positions ----------------------------------------------------------

    def pos(self, row_id, column_id):
        """Bit position in storage (reference: fragment.pos fragment.go:3090)."""
        if column_id // SHARD_WIDTH != self.shard:
            raise ValueError(
                f"column:{column_id} out of bounds for shard {self.shard}")
        return row_id * SHARD_WIDTH + column_id % SHARD_WIDTH

    # -- single-bit mutation -------------------------------------------------

    def set_bit(self, row_id, column_id):
        with self._lock:
            if self.mutexed:
                self._handle_mutex(row_id, column_id)
            return self._set_bit_locked(row_id, column_id)

    def _set_bit_locked(self, row_id, column_id):
        pos = self.pos(row_id, column_id)
        changed = self.storage.add(pos)
        if changed:
            # local ref: a concurrent LRU eviction may null the attribute
            # mid-write; mutating the discarded array is harmless (the
            # rebuild re-reads storage)
            vec = self._mutex_vec
            if self.mutexed and vec is not None:
                vec[column_id % SHARD_WIDTH] = row_id
            self._append_op(encode_op(OP_ADD, value=pos))
            self._invalidate_row(row_id)
            self._cache_update(row_id)
        return changed

    def clear_bit(self, row_id, column_id):
        with self._lock:
            return self._clear_bit_locked(row_id, column_id)

    def _clear_bit_locked(self, row_id, column_id):
        pos = self.pos(row_id, column_id)
        changed = self.storage.remove(pos)
        if changed:
            vec = self._mutex_vec  # local ref: see _set_bit_locked
            if self.mutexed and vec is not None:
                off = column_id % SHARD_WIDTH
                if int(vec[off]) == row_id:
                    vec[off] = -1
            self._append_op(encode_op(OP_REMOVE, value=pos))
            self._invalidate_row(row_id)
            self._cache_update(row_id)
        return changed

    def _handle_mutex(self, row_id, column_id):
        """Clear this column from any other row (reference: handleMutex
        fragment.go:670 via mutexVector)."""
        existing = self.row_for_column(column_id)
        if existing is not None and existing != row_id:
            self._clear_bit_locked(existing, column_id)

    def _drop_mutex_vec(self):
        """Null the rows-vector AND release its LRU slot — a
        vector-less fragment left registered would consume cap budget and
        evict live vectors (close() and every bulk-invalidation route
        through here)."""
        self._mutex_vec = None
        with _MUTEX_VECTOR_LOCK:
            _MUTEX_VECTORS.pop(self.uid, None)

    def _mutex_vector(self):
        """The mutex rows-vector (column offset -> row id, int64 array of
        SHARD_WIDTH with -1 = unset, ~8 MB/fragment), built lazily with one
        slice_range pass per row, then maintained incrementally by
        _set_bit_locked/_clear_bit_locked (bulk ops invalidate or patch
        it). O(1) lookups replace the per-write all-rows probe (reference:
        rowsVector fragment.go:3102, boltRowsVector). Mutex fragments only
        — non-mutexed fragments have no single-row-per-column invariant
        and their writes don't maintain the vector.

        Resident vectors are LRU-bounded ACROSS fragments
        (_MUTEX_VECTOR_CAP): a node holding hundreds of mutex fragments
        that each saw one write must not pin hundreds x 8 MB of host RAM.
        Eviction is a plain cross-thread `_mutex_vec = None` — safe
        because the vector is a pure cache of storage and every user
        holds a LOCAL reference under its own fragment lock (a lost
        update to a discarded array is harmless; the rebuild re-reads
        storage)."""
        vec = self._mutex_vec
        if vec is None:
            # int64: row ids range to ~2^44 (pos() is uint64); int32 would
            # overflow at row >= 2^31
            vec = np.full(SHARD_WIDTH, -1, dtype=np.int64)
            for row_id in self.row_ids():
                base = row_id * SHARD_WIDTH
                offs = (self.storage.slice_range(
                    base, base + SHARD_WIDTH) - np.uint64(base)
                ).astype(np.int64)
                vec[offs] = row_id
            self._mutex_vec = vec
        with _MUTEX_VECTOR_LOCK:
            _MUTEX_VECTORS[self.uid] = self
            _MUTEX_VECTORS.move_to_end(self.uid)
            while len(_MUTEX_VECTORS) > _MUTEX_VECTOR_CAP:
                _, victim = _MUTEX_VECTORS.popitem(last=False)
                victim._mutex_vec = None  # rebuilt lazily on next use
        return vec

    def row_for_column(self, column_id):
        """Row containing the column, or None — O(1) mutex rows-vector
        lookup (reference: rowsVector fragment.go:3102); falls back to a
        storage scan on non-mutexed fragments (no maintained vector)."""
        with self._lock:
            if not self.mutexed:
                for row_id in self.row_ids():
                    if self.storage.contains(self.pos(row_id, column_id)):
                        return row_id
                return None
            row = int(self._mutex_vector()[column_id % SHARD_WIDTH])
            return None if row < 0 else row

    def rows_for_columns(self, column_ids):
        """{column_id: row_id} for the given columns via the rows-vector
        (mutex bulk imports)."""
        with self._lock:
            if not self.mutexed:
                # vectorized one-slice_range-per-row scan (no maintained
                # vector on non-mutexed fragments)
                col_by_offset = {int(c) % SHARD_WIDTH: int(c)
                                 for c in column_ids}
                wanted = np.array(sorted(col_by_offset), dtype=np.uint64)
                out = {}
                for row_id in self.row_ids():
                    if len(wanted) == 0:
                        break
                    base = np.uint64(row_id * SHARD_WIDTH)
                    offs = self.storage.slice_range(
                        int(base), int(base) + SHARD_WIDTH) - base
                    mask = np.isin(wanted, offs)
                    if mask.any():
                        for off in wanted[mask]:
                            out[col_by_offset[int(off)]] = row_id
                        wanted = wanted[~mask]
                return out
            vec = self._mutex_vector()
            out = {}
            for c in column_ids:
                row = int(vec[int(c) % SHARD_WIDTH])
                if row >= 0:
                    out[int(c)] = row
            return out

    def contains(self, row_id, column_id):
        with self._lock:
            return self.storage.contains(self.pos(row_id, column_id))

    # -- BSI value ops (reference: fragment.go:896-1000) ---------------------

    def value(self, column_id, bit_depth):
        with self._lock:
            # direct storage probes: contains() would re-acquire the
            # RLock per bit (up to ~66 acquisitions for wide BSI fields)
            def bit(row_id):
                return self.storage.contains(self.pos(row_id, column_id))

            if not bit(BSI_EXISTS_BIT):
                return 0, False
            value = 0
            for i in range(bit_depth):
                if bit(BSI_OFFSET_BIT + i):
                    value |= 1 << i
            if bit(BSI_SIGN_BIT):
                value = -value
            return value, True

    def set_value(self, column_id, bit_depth, value):
        """Sign-magnitude write of base-adjusted value; returns changed."""
        to_set, to_clear = self.positions_for_value(column_id, bit_depth, value)
        return self.import_positions(to_set, to_clear) > 0

    def clear_value(self, column_id, bit_depth):
        to_set, to_clear = self.positions_for_value(
            column_id, bit_depth, 0, clear=True)
        return self.import_positions(to_set, to_clear) > 0

    def positions_for_value(self, column_id, bit_depth, value, clear=False):
        to_set, to_clear = [], []
        uvalue = abs(int(value))
        # existence bit
        (to_clear if clear else to_set).append(self.pos(BSI_EXISTS_BIT, column_id))
        # sign bit
        if value < 0 and not clear:
            to_set.append(self.pos(BSI_SIGN_BIT, column_id))
        else:
            to_clear.append(self.pos(BSI_SIGN_BIT, column_id))
        for i in range(bit_depth):
            p = self.pos(BSI_OFFSET_BIT + i, column_id)
            if (uvalue >> i) & 1:
                to_set.append(p)
            else:
                to_clear.append(p)
        return to_set, to_clear

    # -- bulk ----------------------------------------------------------------

    def import_positions(self, to_set, to_clear):
        """Batched set/clear by raw position (reference: importPositions
        fragment.go:2053). Returns changed count."""
        with self._lock:
            changed = 0
            if len(to_set):
                arr = np.asarray(to_set, dtype=np.uint64)
                n = self.storage.add_many(arr)
                if n:
                    self._append_op(encode_op(OP_ADD_BATCH, values=arr))
                    changed += n
            if len(to_clear):
                arr = np.asarray(to_clear, dtype=np.uint64)
                n = self.storage.remove_many(arr)
                if n:
                    self._append_op(encode_op(OP_REMOVE_BATCH, values=arr))
                    changed += n
            if changed:
                self._invalidate_all_rows()
                if self.cache is not None:
                    touched = set()
                    for arr in (to_set, to_clear):
                        if len(arr):
                            touched.update(
                                (np.asarray(arr, dtype=np.uint64)
                                 // np.uint64(SHARD_WIDTH)).tolist())
                    for row_id in touched:
                        self._cache_update(int(row_id))
            return changed

    def bulk_import(self, row_ids, column_ids, clear=False):
        """Bulk bit import (reference: bulkImport fragment.go:1997). For
        mutex fragments, each column keeps only its last-written row."""
        row_ids = np.asarray(row_ids, dtype=np.uint64)
        column_ids = np.asarray(column_ids, dtype=np.uint64)
        if self.mutexed and not clear:
            # Clears don't need last-write-wins resolution (reference:
            # bulkImport takes the mutex path only when !options.Clear).
            return self._bulk_import_mutex(row_ids, column_ids)
        positions = row_ids * np.uint64(SHARD_WIDTH) + (
            column_ids % np.uint64(SHARD_WIDTH))
        if clear:
            return self.import_positions([], positions)
        return self.import_positions(positions, [])

    def _bulk_import_mutex(self, row_ids, column_ids):
        with self._lock:
            changed = 0
            # last write per column wins (reference: bulkImportMutex)
            last = {}
            for r, c in zip(row_ids, column_ids):
                last[int(c)] = int(r)
            existing = self.rows_for_columns(list(last))
            vec = self._mutex_vec  # built by rows_for_columns
            to_set, to_clear = [], []
            for c, r in last.items():
                old = existing.get(c)
                if old == r:
                    continue
                if old is not None:
                    to_clear.append(self.pos(old, c))
                to_set.append(self.pos(r, c))
            changed += self.import_positions(to_set, to_clear)
            # import_positions invalidated the vector; the bulk outcome is
            # exactly last-write-wins per column, so patch it back instead
            # of paying a full rebuild on the next mutex write.
            if vec is not None:
                for c, r in last.items():
                    vec[c % SHARD_WIDTH] = r
                self._mutex_vec = vec
            return changed

    def import_roaring(self, data, clear=False):
        """Merge a serialized roaring blob of positions — the fastest ingest
        path (reference: importRoaring fragment.go:2255). Returns changed."""
        other, _, _ = deserialize(data, with_ops=True)
        if os.environ.get("PILOSA_TPU_PARANOIA") == "1":
            other.check()  # reject malformed foreign blobs loudly
        with self._lock:
            changed = merge_bitmaps(self.storage, other, clear=clear)
            if changed:
                op = OP_REMOVE_ROARING if clear else OP_ADD_ROARING
                self._append_op(encode_op(op, roaring=serialize(other), op_n=changed))
                self._invalidate_all_rows()
                if self.cache is not None:
                    touched = {
                        key // CONTAINERS_PER_SHARD for key in other.keys()}
                    for row_id in touched:
                        self._cache_update(int(row_id))
            return changed

    # -- row planes (the device path) ----------------------------------------

    def row_plane(self, row_id):
        """Host dense words for one row: containers
        [row*CPS, (row+1)*CPS) (reference: rowFromStorage fragment.go:623
        via OffsetRange). Locked: readers must never observe a container
        mid-mutation (the reference guards reads with fragment.mu
        RLock; the stress suite reproduces torn reads without this)."""
        with self._lock:
            return self.storage.dense_range_words(
                row_id * CONTAINERS_PER_SHARD, CONTAINERS_PER_SHARD)

    def row_device(self, row_id):
        """Device plane for one row, cached until the row is written.

        The device upload happens outside the lock (it can be slow), so
        the cache insert is generation-guarded: a write that lands between
        the snapshot and the insert invalidates the cache slot, and a
        stale plane must not be re-inserted over that invalidation."""
        import jax.numpy as jnp

        cached = self._row_cache.get(row_id)
        if cached is None:
            with self._lock:
                gen = self.generation
                plane = self.storage.dense_range_words(
                    row_id * CONTAINERS_PER_SHARD, CONTAINERS_PER_SHARD)
            cached = jnp.asarray(plane)
            with self._lock:
                if self.generation == gen:
                    self._row_cache[row_id] = cached
        return cached

    def row_ids(self):
        """Sorted rowIDs with any bit set (reference: fragment.rows),
        memoized per write-generation (mutex set_bit probes this per write).

        The lock-free fast path is a deliberate exception to this file's
        readers-take-the-lock discipline: the (gen, ids) TUPLE is
        published atomically by CPython reference assignment, so a racing
        reader sees either the old pair or the new pair, never a torn
        one; a stale pair fails the generation compare and falls to the
        locked rebuild."""
        cached = self._row_ids_cache
        if cached is not None and cached[0] == self.generation:
            return cached[1]
        with self._lock:
            gen = self.generation
            ids = sorted({
                key // CONTAINERS_PER_SHARD
                for key in self.storage.keys()
                if self.storage.containers[key].n > 0
            })
            self._row_ids_cache = (gen, ids)
        return ids

    def max_row_id(self):
        ids = self.row_ids()
        return ids[-1] if ids else 0

    def row_columns(self, row_id):
        """Absolute column ids of a row (host path, for result assembly)."""
        with self._lock:
            base = row_id * SHARD_WIDTH
            cols = self.storage.slice_range(base, base + SHARD_WIDTH)
        return (cols - np.uint64(base)) + np.uint64(self.shard * SHARD_WIDTH)

    def set_row_plane(self, row_id, plane_words):
        """Overwrite a whole row from dense words (Store/ClearRow writes;
        reference: fragment.setRow fragment.go:760). Returns True when the
        stored row actually changed (bit-exact comparison)."""
        plane_words = np.asarray(plane_words, dtype=np.uint32)
        with self._lock:
            old = self.row_plane(row_id)
            if np.array_equal(old, plane_words):
                return False
            self.storage.replace_dense_words(
                row_id * CONTAINERS_PER_SHARD, CONTAINERS_PER_SHARD,
                plane_words)
            # WAL: remove whole old row, add new row, as a roaring op pair.
            row_bitmap = Bitmap()
            row_bitmap.replace_dense_words(
                row_id * CONTAINERS_PER_SHARD, CONTAINERS_PER_SHARD,
                plane_words)
            full = Bitmap()
            full.merge_dense_words(
                row_id * CONTAINERS_PER_SHARD,
                np.full(CONTAINERS_PER_SHARD * WORDS_PER_CONTAINER, 0xFFFFFFFF,
                        dtype=np.uint32))
            self._append_op(encode_op(
                OP_REMOVE_ROARING, roaring=serialize(full), op_n=0))
            self._append_op(encode_op(
                OP_ADD_ROARING, roaring=serialize(row_bitmap), op_n=0))
            self._invalidate_row(row_id)
            self._drop_mutex_vec()  # whole-row overwrite: rebuild lazily
            self._cache_update(row_id)
            return True

    # -- persistence ---------------------------------------------------------

    def _append_op(self, op_bytes):
        if self._file is not None:
            self._file.write(op_bytes)
            self._file.flush()
            # honor the node-wide fsync policy (one knob for the oplog
            # AND the fragment WAL — the documented durability level is
            # only as strong as its weakest layer)
            oplog_mod.after_append(self._file)
        self.op_n += 1
        if self.op_n > self.max_op_n:
            if self.snapshot_queue is not None:
                if not self._snapshot_pending:
                    self._snapshot_pending = True
                    self.snapshot_queue.enqueue(self)
            else:
                self._snapshot_locked()

    def snapshot(self):
        with self._lock:
            self._snapshot_locked()

    def _snapshot_locked(self):
        """Rewrite the file without the op log (reference:
        unprotectedWriteToFragment fragment.go:2347, temp+rename)."""
        if os.environ.get("PILOSA_TPU_PARANOIA") == "1":
            # paranoid-build analog (reference: roaring_paranoia.go):
            # validate storage invariants before persisting them
            self.storage.check()
        tmp = self.path + ".snapshotting"
        with open(tmp, "wb") as f:
            f.write(serialize(self.storage, flags=self.flags))
            if oplog_mod.fsync_policy() != "never":
                # the rename below atomically replaces snapshot+oplog
                # with snapshot-only; an unsynced temp would make that
                # swap a downgrade on power loss
                oplog_mod.fsync_file(f)
        if self._file:
            self._file.close()
        faultpoints.reached("fragment.snapshot.rename")
        os.replace(tmp, self.path)
        self._file = open(self.path, "ab")
        self.op_n = 0
        self._snapshot_pending = False

    # -- cache/invalidation ---------------------------------------------------

    def _invalidate_row(self, row_id):
        self._row_cache.pop(row_id, None)
        self._checksums.pop(row_id // HASH_BLOCK_SIZE, None)
        self.generation += 1
        if self.on_mutate is not None:
            self.on_mutate()

    def _invalidate_all_rows(self):
        self._row_cache.clear()
        self._checksums.clear()
        self._drop_mutex_vec()  # bulk mutation: rebuild lazily
        self.generation += 1
        if self.on_mutate is not None:
            self.on_mutate()

    # -- anti-entropy blocks (reference: Blocks fragment.go:1778) -------------

    def blocks(self):
        """[(block_id, checksum_bytes)] for every 100-row block with bits."""
        out = []
        with self._lock:
            block_ids = sorted({r // HASH_BLOCK_SIZE for r in self.row_ids()})
            for bid in block_ids:
                chk = self._checksums.get(bid)
                if chk is None:
                    positions = self.storage.slice_range(
                        bid * HASH_BLOCK_SIZE * SHARD_WIDTH,
                        (bid + 1) * HASH_BLOCK_SIZE * SHARD_WIDTH)
                    if len(positions) == 0:
                        continue
                    chk = hashlib.blake2b(
                        positions.astype("<u8").tobytes(), digest_size=16).digest()
                    self._checksums[bid] = chk
                out.append((bid, chk))
        return out

    def block_data(self, block_id):
        """(row_ids, column_ids) pairs within a block (reference: blockData)."""
        with self._lock:
            positions = self.storage.slice_range(
                block_id * HASH_BLOCK_SIZE * SHARD_WIDTH,
                (block_id + 1) * HASH_BLOCK_SIZE * SHARD_WIDTH)
        rows = positions // np.uint64(SHARD_WIDTH)
        cols = positions % np.uint64(SHARD_WIDTH)
        return rows, cols

    # -- row counts / cache ---------------------------------------------------

    def row_count(self, row_id):
        """Exact bit count of one row, from container cardinalities —
        row ranges are container-aligned so no densification happens."""
        with self._lock:
            return int(self.storage.count_range(
                row_id * SHARD_WIDTH, (row_id + 1) * SHARD_WIDTH))

    def _cache_update(self, row_id):
        if self.cache is not None:
            self.cache.add(row_id, self.row_count(row_id))

    # -- stats ----------------------------------------------------------------

    def cardinality(self):
        with self._lock:
            return self.storage.count()

    def __repr__(self):
        return (f"<Fragment {self.index}/{self.field}/{self.view}/"
                f"{self.shard} n={self.cardinality()}>")
