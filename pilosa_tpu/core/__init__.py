"""Host metadata tree: holder -> index -> field -> view -> fragment, plus
the Row result type (reference layer map: SURVEY.md §1)."""

from .field import Field, FieldOptions
from .fragment import Fragment
from .holder import Holder, SnapshotQueue
from .index import EXISTENCE_FIELD_NAME, Index, IndexOptions
from .row import Row
from .view import VIEW_BSI_GROUP_PREFIX, VIEW_STANDARD, View
